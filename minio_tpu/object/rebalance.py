"""Background pool rebalancer: drain a pool's objects into the active
pools while serving traffic.

Upstream's decommission (cmd/erasure-server-pool-decom.go) walks every
bucket of the draining pool, re-PUTs each version through the regular
object path into the surviving pools, and deletes the source copy only
after the target write succeeded; progress is checkpointed so a restart
resumes instead of rescanning. This is that walker, wired to this
repo's planes:

  * moves ride the PIPELINED encode path (target pool's regular
    put_object) and the engine's reconstructing reads — a degraded
    source object (dead drives ≤ parity) is rebuilt on the fly by the
    same hedged shard readers the heal path uses;
  * failed moves feed the source pool's MRF heal queue (heal first,
    move on the next pass) and count in
    ``minio_tpu_rebalance_failed_total``;
  * the walker THROTTLES itself off live ``BatchScheduler`` occupancy
    and ``BytePool`` wait gauges — foreground traffic always wins, the
    drain takes the idle cycles;
  * per-object moves are span roots (``rebalance.move``) so slow or
    failed moves surface in ``/minio/admin/v3/spans``;
  * the checkpoint (bucket + name marker + counters) persists in the
    hidden config bucket of every ACTIVE pool after every
    ``MINIO_TPU_REBALANCE_CHECKPOINT_EVERY`` objects — a kill mid-drain
    resumes from the marker.

Knobs (README "Topology operations"):

  MINIO_TPU_REBALANCE_CHECKPOINT_EVERY=16   objects between checkpoints
  MINIO_TPU_REBALANCE_PAGE=256              listing page size
  MINIO_TPU_REBALANCE_BACKOFF_S=0.05        first backoff when busy
  MINIO_TPU_REBALANCE_BACKOFF_MAX_S=1.0     backoff cap
  MINIO_TPU_REBALANCE_BACKOFF_TRIES=8       busy polls before proceeding
"""

from __future__ import annotations

import json
import threading
import time
from typing import TYPE_CHECKING, Optional

from ..storage.xl_storage import MINIO_META_BUCKET
from ..utils import atomicfile, crashpoint, eventlog, knobs, telemetry
from ..utils.pressure import ForegroundPressure
from ..utils.streams import IterStream as _IterStream
from . import api_errors
from .engine import GetOptions, PutOptions
from .topology import POOL_DRAINING, TOPOLOGY_PREFIX

if TYPE_CHECKING:  # pragma: no cover — typing only
    from .server_sets import ErasureServerSets

CHECKPOINT_EVERY = knobs.get_int("MINIO_TPU_REBALANCE_CHECKPOINT_EVERY")
PAGE = knobs.get_int("MINIO_TPU_REBALANCE_PAGE")
MPU_GRACE_S = knobs.get_float("MINIO_TPU_REBALANCE_MPU_GRACE_S")
BACKOFF_S = knobs.get_float("MINIO_TPU_REBALANCE_BACKOFF_S")
BACKOFF_MAX_S = knobs.get_float("MINIO_TPU_REBALANCE_BACKOFF_MAX_S")
BACKOFF_TRIES = knobs.get_int("MINIO_TPU_REBALANCE_BACKOFF_TRIES")

# meta-bucket prefixes that must NOT migrate: per-pool internals (tmp
# staging, live multipart sessions, bucket metadata replicated per
# pool) and the topology/checkpoint/tier-config docs themselves
# (written to every pool on purpose)
META_SKIP_PREFIXES = ("tmp/", "multipart/", "buckets/", TOPOLOGY_PREFIX,
                      "tier/", "replicate/", "qos/")


def _checkpoint_object(pool: int) -> str:
    return f"{TOPOLOGY_PREFIX}rebalance-{pool}.json"


def _metrics():
    reg = telemetry.REGISTRY
    return (
        reg.counter("minio_tpu_rebalance_objects_total",
                    "Object versions moved off draining pools"),
        reg.counter("minio_tpu_rebalance_bytes_total",
                    "Bytes moved off draining pools"),
        reg.counter("minio_tpu_rebalance_failed_total",
                    "Object moves that failed (fed to MRF, retried "
                    "next pass)"),
        reg.gauge("minio_tpu_rebalance_active",
                  "1 while a pool drain is running"),
    )


class Rebalancer:
    """One pool drain: a daemon thread walking the source pool and
    moving every object version into the active pools."""

    def __init__(self, server_sets: "ErasureServerSets", source: int,
                 resume: bool = False,
                 checkpoint_every: Optional[int] = None,
                 page: Optional[int] = None,
                 busy_fn=None, throttle_s: Optional[float] = None,
                 mpu_grace_s: Optional[float] = None):
        self.obj = server_sets
        self.source = source
        self.checkpoint_every = checkpoint_every or CHECKPOINT_EVERY
        self.page = page or PAGE
        # live multipart sessions idle less than this keep their grace;
        # past it the drain migrates them to an active pool instead of
        # waiting them out (ROADMAP carried-over item 6)
        self.mpu_grace_s = MPU_GRACE_S if mpu_grace_s is None \
            else mpu_grace_s
        # busy probe override (tests); default samples the live
        # scheduler queue + staging-ring waits (utils/pressure.py —
        # shared with the tier transition worker)
        self._pressure = ForegroundPressure(server_sets, busy_fn=busy_fn)
        self._throttle_base = BACKOFF_S if throttle_s is None \
            else throttle_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()
        self.state = {
            "pool": source, "status": "pending",
            "bucket": "", "marker": "",
            "objects_moved": 0, "bytes_moved": 0, "objects_failed": 0,
            "mpu_migrated": 0, "mpu_failed": 0,
            "passes": 0, "started": time.time(), "updated": time.time(),
        }
        if resume:
            doc = self.load_checkpoint(server_sets, source)
            if doc is not None and doc.get("status") not in ("complete",):
                for k in ("bucket", "marker", "objects_moved",
                          "bytes_moved", "objects_failed", "passes"):
                    if k in doc:
                        self.state[k] = doc[k]
                self.state["resumed"] = True

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> "Rebalancer":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"rebalance-p{self.source}")
        self._thread.start()
        return self

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def stop(self, timeout: float = 10.0) -> bool:
        """Signal + join the drain thread; True when it actually
        stopped (callers reactivating the pool must not proceed while
        a move is still in flight)."""
        self._stop.set()
        if self._thread is not None and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout)
        return not self.running()

    def status(self) -> dict:
        with self._mu:
            out = dict(self.state)
        out["running"] = self.running()
        return out

    # ------------------------------------------------------------------
    # the drain loop
    # ------------------------------------------------------------------

    def _run(self) -> None:
        objects_c, bytes_c, failed_c, active_g = _metrics()
        active_g.set(1)
        self._set(status="draining")
        try:
            while not self._stop.is_set():
                moved, failed, remaining = self.run_pass()
                with self._mu:
                    self.state["passes"] += 1
                if self._stop.is_set():
                    break
                if moved == 0 and remaining == 0 and failed == 0:
                    self._set(status="complete", bucket="", marker="")
                    self._save_checkpoint()
                    return
                # stragglers (failed moves healing through MRF, late
                # multipart commits): next pass sweeps again from the top
                self._set(bucket="", marker="")
                if moved == 0:
                    # nothing progressed: wait for MRF heals before the
                    # next sweep instead of spinning the listing
                    self._stop.wait(1.0)
            self._set(status="stopped")
            self._save_checkpoint()
        except Exception as e:  # noqa: BLE001 — surfaced via status
            self._set(status="failed", error=repr(e))
            self._save_checkpoint()
        finally:
            active_g.set(0)

    def run_pass(self, restart: bool = False) -> tuple[int, int, int]:
        """One sweep of the source pool from the current checkpoint
        (`restart=True` sweeps from the top — what the drain loop does
        between passes). Returns (moved, failed, remaining-at-end)."""
        if restart:
            self._set(bucket="", marker="")
        src = self.obj.server_sets[self.source]
        moved = failed = 0
        # lexically sorted INCLUDING the hidden config bucket (config/
        # IAM objects migrate too): iteration order must match the
        # checkpoint's `bucket < start_bucket` resume comparison
        buckets = sorted([v.name for v in src.list_buckets()]
                         + [MINIO_META_BUCKET])
        start_bucket = self.state["bucket"]
        for bucket in buckets:
            if self._stop.is_set():
                break
            if start_bucket and bucket < start_bucket:
                continue
            marker = self.state["marker"] \
                if bucket == start_bucket else ""
            m, f = self._drain_bucket(src, bucket, marker)
            moved += m
            failed += f
        if not self._stop.is_set():
            # actively drain LIVE multipart sessions (bounded grace,
            # then migrate) instead of waiting for clients to finish
            m, f = self._drain_multipart(src)
            moved += m
            failed += f
        remaining = 0 if self._stop.is_set() else self._remaining(src)
        return moved, failed, remaining

    def _drain_multipart(self, src) -> tuple[int, int]:
        """Migrate the source pool's in-flight multipart sessions to an
        active pool once their grace expired (``initiated`` tracks the
        session journal's last write, so an actively-uploading client
        keeps renewing its grace — but its own next part-write migrates
        the session anyway via the server-sets draining guard). Failed
        migrations count + feed the source MRF queue and retry next
        pass."""
        moved = failed = 0
        now = time.time()
        try:
            # ONE scan of the shared multipart volume per pass (each
            # entry carries its owning bucket) — the per-bucket lister
            # reads every session's xl.meta just to filter
            uploads = src.list_all_multipart_uploads()
        except api_errors.ObjectApiError:
            return 0, 0
        for up in uploads:
            if self._stop.is_set():
                return moved, failed
            if now - up.get("initiated", 0) < self.mpu_grace_s:
                continue                # bounded in-flight grace
            self._throttle()
            with telemetry.trace("rebalance.migrate_mpu",
                                 bucket=up["bucket"],
                                 object=up["object"],
                                 upload_id=up["upload_id"]):
                try:
                    self.obj.migrate_upload(up["bucket"], up["object"],
                                            up["upload_id"],
                                            source=self.source)
                except api_errors.InvalidUploadID:
                    # the session vanished under us (client completed
                    # or aborted, or a consumed leftover was purged):
                    # converged, nothing to count
                    moved += 1
                except Exception:  # noqa: BLE001 — per-session
                    failed += 1    # isolation; MRF heals, next
                    with self._mu:  # pass retries
                        self.state["mpu_failed"] += 1
                    self._on_move_failed(up["bucket"], up["object"])
                else:
                    moved += 1
                    with self._mu:
                        self.state["mpu_migrated"] += 1
        return moved, failed

    def _drain_bucket(self, src, bucket: str, marker: str
                      ) -> tuple[int, int]:
        moved = failed = since_ckpt = 0
        for name, versions in self._bucket_groups(src, bucket, marker):
            if self._stop.is_set():
                break
            self._throttle()
            try:
                moved_bytes = self._move_object(bucket, name, versions)
            except Exception:  # noqa: BLE001 — per-object isolation
                failed += 1
                self._on_move_failed(bucket, name)
            else:
                moved += 1
                with self._mu:
                    self.state["objects_moved"] += 1
                    self.state["bytes_moved"] += moved_bytes
                objects_c, bytes_c, _, _ = _metrics()
                objects_c.inc(len(versions), pool=str(self.source))
                bytes_c.inc(moved_bytes, pool=str(self.source))
            self._set(bucket=bucket, marker=name)
            since_ckpt += 1
            if since_ckpt >= self.checkpoint_every:
                self._save_checkpoint()
                since_ckpt = 0
        if since_ckpt:
            self._save_checkpoint()
        return moved, failed

    def _bucket_groups(self, src, bucket: str, marker: str):
        """(name, source-pool versions) groups in name order after
        `marker`. The metacache index (when attached) supplies the
        NAMES — the drain rides the one amortized walk instead of
        re-walking the namespace per pass — while the version list
        stays the SOURCE POOL's own quorum read (the index is
        cluster-wide; only pool-local truth may drive a pool drain).
        Falls back to marker-paged pool-local version listing, carrying
        a page-cut group across pages so an object's versions always
        move together."""
        feed = None
        mc = getattr(self.obj, "metacache", None)
        if mc is not None and bucket != MINIO_META_BUCKET:
            feed = mc.namespace_feed(bucket, versions=True,
                                     consumer="rebalance")
        if feed is not None:
            for name, _cluster_versions in feed:
                if self._stop.is_set():
                    return
                if marker and name <= marker:
                    continue
                try:
                    vs = src.object_versions(bucket, name)
                except api_errors.ObjectApiError:
                    continue
                if vs:
                    yield name, vs
            return
        from .metacache import walks_counter
        walks_counter().inc(consumer="rebalance", source="merge")
        vid_marker = ""
        carry_name = None
        carry: list = []
        while not self._stop.is_set():
            try:
                page, _pfx, nkm, nvm, trunc = src.list_object_versions(
                    bucket, "", marker, self.page, vid_marker)
            except api_errors.ObjectApiError:
                return                  # bucket vanished mid-drain
            for oi in page:
                if bucket == MINIO_META_BUCKET and \
                        oi.name.startswith(META_SKIP_PREFIXES):
                    continue
                if carry_name is not None and oi.name != carry_name:
                    yield carry_name, carry
                    carry = []
                carry_name = oi.name
                carry.append(oi)
            if not trunc:
                break
            marker, vid_marker = nkm, nvm
        if carry_name is not None and carry and not self._stop.is_set():
            yield carry_name, carry

    def _group(self, page, bucket: str) -> list[tuple[str, list]]:
        """Page of version ObjectInfos -> [(name, versions)] in listing
        order, meta-bucket internals filtered out."""
        groups: list[tuple[str, list]] = []
        for oi in page:
            if bucket == MINIO_META_BUCKET and \
                    oi.name.startswith(META_SKIP_PREFIXES):
                continue
            if groups and groups[-1][0] == oi.name:
                groups[-1][1].append(oi)
            else:
                groups.append((oi.name, [oi]))
        return groups

    def _remaining(self, src) -> int:
        """Movable objects still on the source pool (completion probe).
        Live multipart sessions count too: the drain is not complete
        until every session migrated (young ones ride their grace
        through another pass)."""
        remaining = 0
        buckets = [v.name for v in src.list_buckets()] \
            + [MINIO_META_BUCKET]
        for bucket in buckets:
            try:
                page, _, _, _, _ = src.list_object_versions(
                    bucket, "", "", self.page)
            except api_errors.ObjectApiError:
                continue
            remaining += len(self._group(page, bucket))
        try:
            remaining += len(src.list_all_multipart_uploads())
        except api_errors.ObjectApiError:
            pass
        return remaining

    # ------------------------------------------------------------------
    # one object
    # ------------------------------------------------------------------

    def _move_object(self, bucket: str, name: str, versions: list) -> int:
        """Copy every version (oldest first, so relative order is
        preserved wherever mod times tie) into an active pool, then
        delete the source copies. Source deletion happens only after
        EVERY version committed at target write quorum — a crash in
        between leaves the object readable in both pools (newest-wins)
        and the next pass's idempotency check finishes the job."""
        src = self.obj.server_sets[self.source]
        moved_bytes = 0
        with telemetry.trace("rebalance.move", bucket=bucket,
                             object=name, pool=self.source):
            for oi in sorted(versions, key=lambda o: o.mod_time or 0):
                if self._version_in_active_pool(bucket, name, oi):
                    continue            # crash-window leftover: done
                moved_bytes += self._copy_version(src, bucket, name, oi)
            if self._stop.is_set():
                # canceled mid-move: leave the source intact — the
                # copies are idempotent leftovers the next drain (or a
                # client overwrite after reactivation) supersedes;
                # purging here could race a write to the re-activated
                # pool
                return moved_bytes
            # a client DELETE that raced the copy must win: versions
            # gone from the source since we listed them were deleted
            # (the purge scanned the target before our copy committed),
            # so roll their fresh target copies back instead of
            # resurrecting them
            try:
                still = {v.version_id
                         for v in src.object_versions(bucket, name)}
            except api_errors.ObjectApiError:
                still = set()
            for oi in sorted(versions, key=lambda o: o.mod_time or 0):
                try:
                    if oi.version_id not in still:
                        self._rollback_target_copy(bucket, name, oi)
                    elif oi.version_id:
                        src.delete_object(bucket, name,
                                          version_id=oi.version_id)
                    else:
                        src.delete_object(bucket, name)
                except api_errors.ObjectNotFound:
                    pass                # already gone (raced a delete)
        return moved_bytes

    def _rollback_target_copy(self, bucket: str, name: str, oi) -> None:
        for i in self.obj.topology.write_pools():
            if i == self.source:
                continue
            z = self.obj.server_sets[i]
            try:
                if oi.version_id:
                    z.delete_object(bucket, name,
                                    version_id=oi.version_id)
                elif z.has_object_versions(bucket, name):
                    z.delete_object(bucket, name)
            except api_errors.ObjectApiError:
                pass

    def _version_in_active_pool(self, bucket: str, name: str, oi) -> bool:
        for i in self.obj.topology.write_pools():
            if i == self.source:
                continue
            z = self.obj.server_sets[i]
            try:
                if oi.delete_marker or oi.version_id:
                    # direct per-name read: O(versions of this object),
                    # not O(bucket), and never blind past a page cut
                    for v in z.object_versions(bucket, name):
                        if v.version_id == oi.version_id:
                            return True
                else:
                    got = z.get_object_info(bucket, name)
                    if got.etag == oi.etag and \
                            got.mod_time == oi.mod_time:
                        return True
            except api_errors.ObjectApiError:
                continue
        return False

    def _copy_version(self, src, bucket: str, name: str, oi) -> int:
        from ..storage.datatypes import is_restored, is_transitioned
        if oi.delete_marker:
            idx = self._target_pool(bucket, name, 1 << 20)
            self.obj.server_sets[idx].put_delete_marker(
                bucket, name, oi.version_id, oi.mod_time)
            return 0
        if is_transitioned(oi.user_defined or {}) \
                and not is_restored(oi.user_defined or {}):
            # a tiered zero-data stub: there are no local shards to
            # move and GET would refuse (InvalidObjectState) — copy the
            # xl.meta pointer alone, like a delete marker (the remote
            # copy stays where it is)
            idx = self._target_pool(bucket, name, 1 << 20)
            self.obj.server_sets[idx].put_stub_version(bucket, name, oi)
            return 0
        info, stream = src.get_object(
            bucket, name, opts=GetOptions(version_id=oi.version_id))
        metadata = dict(info.user_defined)
        if info.etag:
            metadata["etag"] = info.etag
        if info.content_type:
            metadata["content-type"] = info.content_type
        if info.content_encoding:
            metadata["content-encoding"] = info.content_encoding
        idx = self._target_pool(bucket, name, info.size)
        opts = PutOptions(metadata=metadata,
                          version_id=info.version_id,
                          versioned=bool(info.version_id),
                          mod_time=info.mod_time)
        reader = _IterStream(stream)
        try:
            self.obj.server_sets[idx].put_object(bucket, name, reader,
                                                 info.size, opts)
        finally:
            reader.close()
        return info.size

    def _target_pool(self, bucket: str, name: str, size: int) -> int:
        """Active pool for one moved version: keep affinity with an
        active pool already holding the object's history, else weighted
        free space — never the source."""
        for i in self.obj.topology.write_pools():
            if i != self.source and \
                    self.obj.server_sets[i].has_object_versions(bucket,
                                                                name):
                return i
        idx = self.obj.get_available_zone_idx(max(size, 1) * 2)
        if idx < 0 or idx == self.source:
            raise api_errors.InsufficientWriteQuorum(
                "no active pool has room for the rebalance target")
        return idx

    def _on_move_failed(self, bucket: str, name: str) -> None:
        with self._mu:
            self.state["objects_failed"] += 1
        _, _, failed_c, _ = _metrics()
        failed_c.inc(pool=str(self.source))
        # heal-first: a move that failed on a degraded source heals
        # through the MRF queue, then the next sweep retries the move
        src = self.obj.server_sets[self.source]
        mrf = getattr(src, "mrf", None)
        if mrf is not None:
            mrf.enqueue(bucket, name)

    # ------------------------------------------------------------------
    # throttle: foreground traffic always wins
    # ------------------------------------------------------------------

    def _busy(self) -> bool:
        return self._pressure.busy()

    def _throttle(self) -> None:
        # still busy after the cap: proceed at the slow cadence anyway
        # so a permanently-loaded cluster still drains
        self._pressure.throttle(self._stop, self._throttle_base,
                                BACKOFF_MAX_S, BACKOFF_TRIES)

    # ------------------------------------------------------------------
    # checkpoint persistence
    # ------------------------------------------------------------------

    def _set(self, **kw) -> None:
        with self._mu:
            self.state.update(kw)
            self.state["updated"] = time.time()

    def _save_checkpoint(self) -> None:
        with self._mu:
            doc = dict(self.state)
        eventlog.emit("rebalance.checkpoint", pool=self.source,
                      objects=doc.get("objects_moved", 0))
        payload = json.dumps(doc).encode()
        # every ACTIVE pool gets a copy: the checkpoint must survive the
        # source pool's removal
        for i in self.obj.topology.write_pools():
            if i == self.source:
                continue
            try:
                # one hit per pool (arm :<nth>): resume must tolerate
                # a stale checkpoint (idempotent re-pass) or a torn one
                crashpoint.hit("rebalance.checkpoint")
                self.obj.server_sets[i].put_object(
                    MINIO_META_BUCKET, _checkpoint_object(self.source),
                    payload)
            except Exception:  # noqa: BLE001 — best-effort per pool
                pass

    @staticmethod
    def load_checkpoint(server_sets: "ErasureServerSets",
                        pool: int) -> Optional[dict]:
        best: Optional[dict] = None
        for z in server_sets.server_sets:
            try:
                _, stream = z.get_object(MINIO_META_BUCKET,
                                         _checkpoint_object(pool))
                # a crash inside the checkpoint write can leave torn
                # JSON (or a truncated valid-JSON prefix of the wrong
                # type): treat it as absent, fall back to the previous
                # pool's copy / a fresh pass
                doc = atomicfile.load_json_doc(b"".join(stream))
            except api_errors.ObjectApiError:
                continue
            if doc is None:
                continue
            if best is None or doc.get("updated", 0) > \
                    best.get("updated", 0):
                best = doc
        return best
