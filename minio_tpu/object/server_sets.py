"""ErasureServerSets — zones ("server sets") for cluster expansion.

The reference's top ObjectLayer (cmd/erasure-server-sets.go): multiple
independent ErasureSets groups. PUT goes to the zone already holding the
object, else the zone with the most free space weighted by capacity
(getZoneIdx:195, getAvailableZoneIdx:122); GET/HEAD/DELETE scan zones;
listings merge across zones.

Topology plane (this repo's extension, modeled on upstream pool
decommission + CRUSH-style placement epochs): the zone list is no
longer frozen at boot. A persisted :class:`~.topology.TopologyMap`
gives every pool a state — ``active`` (reads+writes), ``draining``
(reads only, a background rebalancer is emptying it) or ``suspended``
(reads only, maintenance). New writes route ONLY to active pools;
reads scan every pool and the NEWEST version wins (markers included),
so an object mid-migration — or overwritten while its old home drains
— always reads correctly. Pools can be appended online
(:meth:`add_pool`) and drained empty (:meth:`start_decommission`).
"""

from __future__ import annotations

import random
import time
from typing import Optional

from ..storage.datatypes import ObjectInfo
from . import api_errors
from .sets import ErasureSets
from .topology import (POOL_ACTIVE, POOL_DRAINING, TopologyError,
                       TopologyMap, TopologyStore)

DISK_FILL_FRACTION = 0.95  # reference diskFillFraction


class ErasureServerSets:
    def __init__(self, server_sets: list[ErasureSets],
                 topology: Optional[TopologyMap] = None,
                 load_topology: bool = True):
        assert server_sets
        self.server_sets = server_sets
        self._rebalancer = None        # live Rebalancer (rebalance.py)
        # persisted bucket index (object/metacache.py): when attached,
        # listings serve from it (merge-walk fallback) and the engines'
        # namespace-change hooks feed its delta journal
        self.metacache = None
        # hot-object read cache (object/cache.py): attached at boot,
        # invalidated off the same namespace feed
        self.read_cache = None
        # active-active replication plane (minio_tpu/replicate/):
        # enqueues off the same namespace feed when attached
        self.replication = None
        # bucket event notification plane (minio_tpu/notify/):
        # classifies + delivers off the same namespace feed
        self.notifications = None
        # ONE namespace-change feed, many consumers: the engines call
        # _dispatch_namespace_change, which fans out to every attached
        # listener (metacache journal, read-cache invalidation)
        self._ns_listeners: list = []
        if topology is None and load_topology:
            # recover the newest persisted map (highest epoch across
            # pools); a fresh cluster starts all-active at epoch 0
            topology = TopologyStore.load(self)
        self.topology = topology or TopologyMap(len(server_sets))

    @property
    def supports_sse_device(self) -> bool:
        return all(getattr(z, "supports_sse_device", False)
                   for z in self.server_sets)

    def _dispatch_namespace_change(self, bucket: str,
                                   object_name: str) -> None:
        """Fan one engine namespace delta out to every listener; a
        broken listener never blocks the others (or the write path)."""
        for fn in self._ns_listeners:
            try:
                fn(bucket, object_name)
            except Exception:  # noqa: BLE001 — feed is best-effort
                pass

    def register_namespace_listener(self, fn) -> None:
        """Subscribe `fn(bucket, object_name)` to the engines' mutation
        feed and (re)wire every pool's hook at the dispatcher."""
        if fn not in self._ns_listeners:
            self._ns_listeners.append(fn)
        for z in self.server_sets:
            z.on_namespace_change = self._dispatch_namespace_change

    def attach_metacache(self, manager) -> None:
        """Wire the MetacacheManager: every pool's engines journal
        namespace deltas into it, and the listing paths consult it
        first (None = fall back to the merge-walk)."""
        self.metacache = manager
        self.register_namespace_listener(manager.record)

    def attach_read_cache(self, cache) -> None:
        """Wire the hot-object read cache's invalidation into the
        namespace feed (the serving side wraps this layer — see
        cluster boot)."""
        self.read_cache = cache
        self.register_namespace_listener(cache.on_namespace_change)

    def attach_replication(self, plane) -> None:
        """Wire the active-active replication plane into the ONE
        namespace feed: every engine mutation verb that fires
        _notify_namespace reaches the replication queue through this
        listener — no per-handler enqueue call sites to forget (the
        lint gate's hook-coverage rule pins the whole chain)."""
        self.replication = plane
        self.register_namespace_listener(plane.on_namespace_change)

    def attach_notifications(self, plane) -> None:
        """Wire the bucket event notification plane into the ONE
        namespace feed: every engine mutation verb that fires
        _notify_namespace reaches the notification queue through this
        listener — no per-handler send call sites to forget (the lint
        gate's hook-coverage rule pins the whole chain)."""
        self.notifications = plane
        self.register_namespace_listener(plane.on_namespace_change)

    def single_zone(self) -> bool:
        return len(self.server_sets) == 1

    # ------------------------------------------------------------------
    # zone choice
    # ------------------------------------------------------------------

    def _available_space(self, size: int) -> list[int]:
        """Per-zone available bytes after the write, 0 when it would cross
        the fill watermark (getServerSetsAvailableSpace,
        cmd/erasure-server-sets.go:143-190) — and 0 for every pool the
        topology excludes from new writes (draining/suspended)."""
        writable = set(self.topology.write_pools())
        out = []
        for i, z in enumerate(self.server_sets):
            if i not in writable:
                out.append(0)
                continue
            info = z.storage_info()
            total, available = info["total"], info["free"]
            if available < size:
                available = 0
            if available > 0:
                available -= size
                want_left = int(total * (1.0 - DISK_FILL_FRACTION))
                if available <= want_left:
                    available = 0
            out.append(available)
        return out

    def get_available_zone_idx(self, size: int) -> int:
        spaces = self._available_space(max(size, 0))
        total = sum(spaces)
        if total == 0:
            return -1
        choose = random.randrange(total)
        at = 0
        for i, a in enumerate(spaces):
            at += a
            if at > choose and a > 0:
                return i
        return -1

    def get_zone_idx(self, bucket: str, object_name: str, size: int) -> int:
        """Zone for a PUT: the ACTIVE zone holding ANY version of the
        object (including a delete marker — version history must stay
        together) wins; else weighted free space among active zones
        (getZoneIdx, cmd/erasure-server-sets.go:195). A holder that is
        draining or suspended does NOT get the write — new versions land
        in an active pool and the newest-wins read keeps them visible
        while the rebalancer catches the old ones up."""
        if self.single_zone():
            return 0
        for i, z in enumerate(self.server_sets):
            if self.topology.can_write(i) and \
                    z.has_object_versions(bucket, object_name):
                return i
        idx = self.get_available_zone_idx(size * 2)  # ×2 for parity
        if idx < 0:
            raise api_errors.to_object_err(
                api_errors.InsufficientWriteQuorum(), bucket, object_name)
        return idx

    # ------------------------------------------------------------------
    # bucket ops
    # ------------------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        for z in self.server_sets:
            z.make_bucket(bucket)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        if not force:
            objs, pfx, _ = self.list_objects(bucket, max_keys=1)
            if objs or pfx:
                raise api_errors.BucketNotEmpty(bucket)
        for z in self.server_sets:
            z.delete_bucket(bucket, force=True)
        if self.metacache is not None:
            # purge: the persisted index lives in .minio.sys and would
            # otherwise be reloaded by a recreated same-name bucket
            self.metacache.drop_bucket(bucket, purge=True)

    def bucket_exists(self, bucket: str) -> bool:
        return self.server_sets[0].bucket_exists(bucket)

    def get_bucket_info(self, bucket: str):
        return self.server_sets[0].get_bucket_info(bucket)

    def list_buckets(self):
        return self.server_sets[0].list_buckets()

    def heal_bucket(self, bucket: str) -> None:
        for z in self.server_sets:
            z.heal_bucket(bucket)

    # ------------------------------------------------------------------
    # object ops
    # ------------------------------------------------------------------

    def put_object(self, bucket, object_name, reader, size=-1, opts=None):
        idx = self.get_zone_idx(bucket, object_name,
                                max(size, 0) if size else 0)
        return self.server_sets[idx].put_object(bucket, object_name,
                                                reader, size, opts)

    def _first_zone_with(self, fn, bucket, object_name):
        last: Optional[Exception] = None
        for z in self.server_sets:
            try:
                return fn(z)
            except api_errors.ObjectNotFound as e:
                last = e
        raise last or api_errors.ObjectNotFound(bucket, object_name)

    def _zone_for_read(self, bucket: str, object_name: str):
        """(index, FileInfo) of the zone holding the NEWEST version
        (delete markers included) — the dual-read rule that keeps GETs
        correct while an object exists in two pools (mid-rebalance, or
        overwritten while its old home drains). A pool that cannot
        answer (offline mid-drain) is skipped so surviving pools still
        serve; its error only surfaces when NO pool holds the object."""
        best_i = -1
        best_fi = None
        nf: Optional[Exception] = None
        hard: Optional[Exception] = None
        for i, z in enumerate(self.server_sets):
            try:
                fi = z.latest_file_info(bucket, object_name)
            except api_errors.ObjectNotFound as e:
                nf = e
                continue
            except api_errors.ObjectApiError as e:
                hard = e
                continue
            if best_fi is None or (fi.mod_time or 0) > \
                    (best_fi.mod_time or 0):
                best_i, best_fi = i, fi
        if best_i < 0:
            raise hard or nf or api_errors.ObjectNotFound(bucket,
                                                          object_name)
        return best_i, best_fi

    def _read_newest(self, bucket, object_name, fn,
                     marker_is_found: bool = False):
        """Run `fn(zone)` on the newest-holding zone, re-picking when
        the copy moved between the pick and the read (a rebalance
        deletes the source copy only AFTER the target committed, so a
        re-pick always lands on a live copy; a true concurrent delete
        converges to ObjectNotFound)."""
        last: Optional[Exception] = None
        for _ in range(3):
            idx, fi = self._zone_for_read(bucket, object_name)
            if fi.deleted and not marker_is_found:
                raise api_errors.ObjectNotFound(bucket, object_name)
            try:
                return fn(self.server_sets[idx])
            except api_errors.ObjectNotFound as e:
                last = e            # moved mid-read: re-pick
        raise last or api_errors.ObjectNotFound(bucket, object_name)

    def get_object(self, bucket, object_name, offset=0, length=-1,
                   opts=None):
        if not self.single_zone() and not getattr(opts, "version_id", ""):
            return self._read_newest(
                bucket, object_name,
                lambda z: z.get_object(bucket, object_name, offset,
                                       length, opts))
        return self._first_zone_with(
            lambda z: z.get_object(bucket, object_name, offset, length,
                                   opts), bucket, object_name)

    def get_object_info(self, bucket, object_name, opts=None):
        if not self.single_zone() and not getattr(opts, "version_id", ""):
            return self._read_newest(
                bucket, object_name,
                lambda z: z.get_object_info(bucket, object_name, opts))
        return self._first_zone_with(
            lambda z: z.get_object_info(bucket, object_name, opts),
            bucket, object_name)

    def delete_object(self, bucket, object_name, version_id="",
                      versioned=False):
        self.get_bucket_info(bucket)  # missing bucket must not 204
        if self.single_zone():
            return self.server_sets[0].delete_object(
                bucket, object_name, version_id, versioned)
        if versioned and not version_id:
            # a versioned delete WRITES a marker: it must land in an
            # ACTIVE pool (writes never target draining/suspended
            # pools); when the newest holder is active, keep affinity
            # so version history stays together
            try:
                idx, _ = self._zone_for_read(bucket, object_name)
            except api_errors.ObjectNotFound:
                idx = -1
            if idx < 0 or not self.topology.can_write(idx):
                idx = self.get_available_zone_idx(1 << 20)
                if idx < 0:
                    raise api_errors.InsufficientWriteQuorum()
            return self.server_sets[idx].delete_object(
                bucket, object_name, version_id, versioned)
        if version_id:
            # remove one specific version from whichever pool holds it
            last: Optional[Exception] = None
            for z in self.server_sets:
                if not z.has_object_versions(bucket, object_name):
                    continue
                try:
                    return z.delete_object(bucket, object_name,
                                           version_id, versioned)
                except (api_errors.ObjectNotFound,
                        api_errors.VersionNotFound) as e:
                    last = e
            raise last or api_errors.ObjectNotFound(bucket, object_name)
        # unversioned delete: purge EVERY pool's copy — an object that
        # transiently exists in two pools (mid-rebalance) must not
        # resurrect from the copy a single-zone delete missed
        out = None
        found = False
        for z in self.server_sets:
            if not z.has_object_versions(bucket, object_name):
                continue
            out = z.delete_object(bucket, object_name, version_id,
                                  versioned)
            found = True
        if not found:
            raise api_errors.ObjectNotFound(bucket, object_name)
        return out

    def delete_objects(self, bucket, objects):
        if self.single_zone():
            self.get_bucket_info(bucket)
            return self.server_sets[0].delete_objects(bucket, objects)
        out = []
        for o in objects:
            try:
                self.delete_object(bucket, o)
                out.append(None)
            except Exception as e:  # noqa: BLE001 — per-key result list
                out.append(e)
        return out

    def heal_object(self, bucket, object_name, version_id="",
                    deep_scan=False, dry_run=False):
        return self._first_zone_with(
            lambda z: z.heal_object(bucket, object_name, version_id,
                                    deep_scan, dry_run),
            bucket, object_name)

    def update_object_metadata(self, bucket, object_name, metadata,
                               version_id=""):
        if not self.single_zone() and not version_id:
            # in-place update must hit the copy reads serve (newest),
            # not the first zone that happens to hold a shadowed copy
            # (marker_is_found: the engine answers MethodNotAllowed for
            # markers itself, matching its single-zone semantics)
            return self._read_newest(
                bucket, object_name,
                lambda z: z.update_object_metadata(bucket, object_name,
                                                   metadata, version_id),
                marker_is_found=True)
        return self._first_zone_with(
            lambda z: z.update_object_metadata(bucket, object_name,
                                               metadata, version_id),
            bucket, object_name)

    def transition_object(self, bucket, object_name, version_id="",
                          tier="", remote_object="", remote_version="",
                          expect_etag="", expect_mod_time=None):
        """Stub-rewrite one version in whichever pool holds it (the
        tier transition/reclaim commit). Version-targeted like
        version delete; the latest-version form hits the newest
        holder, matching what reads serve."""
        if not self.single_zone() and version_id:
            last: Optional[Exception] = None
            for z in self.server_sets:
                if not z.has_object_versions(bucket, object_name):
                    continue
                try:
                    return z.transition_object(
                        bucket, object_name, version_id, tier,
                        remote_object, remote_version, expect_etag,
                        expect_mod_time)
                except (api_errors.ObjectNotFound,
                        api_errors.VersionNotFound) as e:
                    last = e
            raise last or api_errors.ObjectNotFound(bucket, object_name)
        if not self.single_zone():
            return self._read_newest(
                bucket, object_name,
                lambda z: z.transition_object(bucket, object_name,
                                              version_id, tier,
                                              remote_object,
                                              remote_version,
                                              expect_etag,
                                              expect_mod_time))
        return self.server_sets[0].transition_object(
            bucket, object_name, version_id, tier, remote_object,
            remote_version, expect_etag, expect_mod_time)

    # ------------------------------------------------------------------
    # version-faithful writes (replication apply / rebalance copy)
    # ------------------------------------------------------------------

    def put_delete_marker(self, bucket, object_name, version_id="",
                          mod_time=None, metadata=None):
        """Write a delete marker with explicit identity into an ACTIVE
        pool (affinity with the pool holding the object's history, like
        every other write) — the replication-apply marker path."""
        idx = self.get_zone_idx(bucket, object_name, 1 << 20)
        return self.server_sets[idx].put_delete_marker(
            bucket, object_name, version_id, mod_time, metadata)

    def put_stub_version(self, bucket, object_name, info,
                         if_none_newer=False):
        """Write a transitioned zero-data stub from its ObjectInfo into
        an ACTIVE pool — the replication-apply form of the rebalance
        stub copy (the remote tier copy is never touched)."""
        idx = self.get_zone_idx(bucket, object_name, 1 << 20)
        return self.server_sets[idx].put_stub_version(bucket, object_name,
                                                      info, if_none_newer)

    def latest_file_info(self, bucket, object_name):
        """Cross-pool newest version's FileInfo, markers included."""
        _idx, fi = self._zone_for_read(bucket, object_name)
        return fi

    # ------------------------------------------------------------------
    # multipart: session created in the chosen PUT zone; subsequent calls
    # find the zone owning the uploadID
    # ------------------------------------------------------------------

    def new_multipart_upload(self, bucket, object_name, opts=None):
        idx = self.get_zone_idx(bucket, object_name, 1 << 30)
        return self.server_sets[idx].new_multipart_upload(
            bucket, object_name, opts)

    def _zone_of_upload(self, bucket, object_name, upload_id):
        return self.server_sets[
            self._zone_index_of_upload(bucket, object_name, upload_id)]

    def _zone_index_of_upload(self, bucket, object_name,
                              upload_id) -> int:
        """Owning pool of a session. A crash mid-migration can leave
        the session resolvable in TWO pools (the draining source and
        its migration target); the first WRITABLE holder wins — at
        most one exists, so the probe returns at the first writable
        hit (the common all-active case keeps the old first-resolver
        cost) and only a drained-out session scans the full list."""
        first = -1
        for i, z in enumerate(self.server_sets):
            try:
                z.list_object_parts(bucket, object_name, upload_id,
                                    max_parts=1)
            except api_errors.InvalidUploadID:
                continue
            if self.topology.can_write(i):
                return i
            if first < 0:
                first = i
        if first < 0:
            raise api_errors.InvalidUploadID(upload_id)
        return first

    def _writable_upload_zone(self, bucket, object_name,
                              upload_id) -> int:
        """The session's pool — migrated to an active pool first when
        its current home is draining/suspended (decommission stops
        accepting NEW parts on the leaving pool; the client's uploadID
        keeps resolving because the migration preserves it)."""
        idx = self._zone_index_of_upload(bucket, object_name, upload_id)
        if self.topology.can_write(idx) or self.single_zone():
            return idx
        return self.migrate_upload(bucket, object_name, upload_id,
                                   source=idx)

    def put_object_part(self, bucket, object_name, upload_id, part_number,
                        reader, size=-1):
        idx = self._writable_upload_zone(bucket, object_name, upload_id)
        try:
            return self.server_sets[idx].put_object_part(
                bucket, object_name, upload_id, part_number, reader,
                size)
        except api_errors.InvalidUploadID:
            # the drain migrated the session between our zone choice
            # and the write (no bytes consumed yet: the session check
            # precedes the encode) — re-resolve once
            z = self._zone_of_upload(bucket, object_name, upload_id)
            return z.put_object_part(bucket, object_name, upload_id,
                                     part_number, reader, size)

    def migrate_upload(self, bucket: str, object_name: str,
                       upload_id: str,
                       source: Optional[int] = None) -> int:
        """Move one LIVE multipart session onto an active pool —
        session metadata, every uploaded part (decoded through the
        verified GET readers, re-encoded in the target's geometry) and
        the client-held uploadID all survive. The whole copy+abort
        holds the SOURCE engine's session write lock (the one
        put_object_part takes), so a racing part-write either lands
        before the snapshot or blocks and then re-resolves to the
        target; a racing second migration loses the lock and returns
        the converged home. A crash between copy and abort leaves the
        session in both pools: clients continue on the writable target
        (_zone_index_of_upload prefers it) and the re-run copies only
        parts the target LACKS — target parts are authoritative, a
        stale source copy can never overwrite a newer client write.
        Returns the target pool index."""
        from ..utils.streams import IterStream
        from .engine import PutOptions
        from .hash_reader import HashReader
        if source is None:
            source = self._zone_index_of_upload(bucket, object_name,
                                                upload_id)
        import contextlib
        src = self.server_sets[source]
        src_engine = src.get_hashed_set(object_name)
        with contextlib.ExitStack() as stack:
            # per-pool namespace maps in every current assembly; if
            # pools ever shared one map this same-named lock would
            # self-deadlock against the dst part-writes below, so gate
            # on identity
            if not any(src_engine.ns is z.get_hashed_set(object_name).ns
                       for i, z in enumerate(self.server_sets)
                       if i != source):
                stack.enter_context(src_engine.ns.new_lock(
                    f"{bucket}/{object_name}/{upload_id}"
                ).write_locked())
            try:
                session_meta = src.get_multipart_info(
                    bucket, object_name, upload_id)
            except api_errors.InvalidUploadID:
                # lost a migration race: the winner already moved (and
                # aborted) the source session — converge on its home
                return self._zone_index_of_upload(bucket, object_name,
                                                  upload_id)
            parts = src.list_object_parts(bucket, object_name,
                                          upload_id, 0, 10000)
            # a crashed earlier migration may have left the session's
            # twin on SOME other pool — resume THERE, never re-choose
            # (re-choosing would mistake the surviving twin for a
            # consumed upload, or fork the session across three pools)
            idx = -1
            have: dict[int, str] = {}
            for i, z in enumerate(self.server_sets):
                if i == source:
                    continue
                try:
                    have = {p.part_number: p.etag
                            for p in z.list_object_parts(
                                bucket, object_name, upload_id,
                                0, 10000)}
                    idx = i
                    break
                except api_errors.ObjectApiError:
                    continue
            if idx < 0:
                if session_meta.get("x-minio-internal-migrated"):
                    # the marker is written only AFTER the target
                    # session exists; no twin anywhere now means the
                    # client completed/aborted the migrated upload —
                    # the source copy is a consumed leftover: purge,
                    # NEVER resurrect a finished upload as a zombie
                    src.abort_multipart_upload(bucket, object_name,
                                               upload_id)
                    raise api_errors.InvalidUploadID(upload_id)
                total = sum(p.size for p in parts)
                idx = self.get_available_zone_idx(
                    max(total, 1 << 20) * 2)
                if idx < 0 or idx == source:
                    raise api_errors.InsufficientWriteQuorum(
                        "no active pool has room for the session "
                        "migration")
                versioned = session_meta.get(
                    "x-minio-internal-versioned") == "true"
                user_meta = {k: v for k, v in session_meta.items()
                             if not k.startswith("x-minio-internal-")}
                self.server_sets[idx].new_multipart_upload(
                    bucket, object_name,
                    opts=PutOptions(metadata=user_meta,
                                    versioned=versioned),
                    upload_id=upload_id)
                # marker AFTER the target session exists, BEFORE the
                # parts copy: a crash from here on re-runs into the
                # resume-at-twin path above
                src.mark_multipart_session(
                    bucket, object_name, upload_id,
                    {"x-minio-internal-migrated": "1"})
            dst = self.server_sets[idx]
            for p in parts:
                if p.part_number in have:
                    continue        # crash-window leftover: dst wins
                info, stream = src.read_multipart_part(
                    bucket, object_name, upload_id, p.part_number)
                reader = IterStream(stream)
                try:
                    out = dst.put_object_part(
                        bucket, object_name, upload_id, p.part_number,
                        HashReader(reader, p.size,
                                   actual_size=p.actual_size), p.size)
                finally:
                    reader.close()
                if out.etag != p.etag:
                    # never silently swap bytes under a client-held
                    # etag: leave the source authoritative for this
                    # part and surface the fault (next sweep retries)
                    raise api_errors.ObjectApiError(
                        f"migrated part {p.part_number} etag mismatch "
                        f"({out.etag} != {p.etag})")
            src.abort_multipart_upload(bucket, object_name, upload_id)
        return idx

    def list_object_parts(self, bucket, object_name, upload_id,
                          part_marker=0, max_parts=1000):
        z = self._zone_of_upload(bucket, object_name, upload_id)
        return z.list_object_parts(bucket, object_name, upload_id,
                                   part_marker, max_parts)

    def list_multipart_uploads(self, bucket, object_name=""):
        out = []
        for z in self.server_sets:
            out.extend(z.list_multipart_uploads(bucket, object_name))
        out.sort(key=lambda u: (u["object"], u["upload_id"]))
        return out

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        z = self._zone_of_upload(bucket, object_name, upload_id)
        return z.abort_multipart_upload(bucket, object_name, upload_id)

    def get_multipart_info(self, bucket, object_name, upload_id):
        z = self._zone_of_upload(bucket, object_name, upload_id)
        return z.get_multipart_info(bucket, object_name, upload_id)

    def complete_multipart_upload(self, bucket, object_name, upload_id,
                                  parts, version_id="", mod_time=None,
                                  if_none_newer=False):
        # a commit is a new write: a session still homed on a draining
        # pool migrates first so the object lands in an ACTIVE pool
        # instead of being drained again right after the commit
        idx = self._writable_upload_zone(bucket, object_name, upload_id)
        return self.server_sets[idx].complete_multipart_upload(
            bucket, object_name, upload_id, parts, version_id, mod_time,
            if_none_newer)

    # ------------------------------------------------------------------
    # listing
    # ------------------------------------------------------------------

    def list_objects(self, bucket, prefix="", marker="", delimiter="",
                     max_keys=1000):
        from .sets import merge_listings
        t0 = time.monotonic()
        if self.metacache is not None:
            page = self.metacache.serve_list_objects(
                bucket, prefix, marker, delimiter, max_keys)
            if page is not None:
                self._observe_listing("list", "index", t0)
                return page
        per_zone = [z.list_objects(bucket, prefix, marker, delimiter,
                                   max_keys)
                    for z in self.server_sets]
        out = merge_listings(per_zone, max_keys)
        self._observe_listing("list", "walk", t0)
        return out

    def list_object_versions(self, bucket, prefix="", marker="",
                             max_keys=1000, version_marker="",
                             delimiter=""):
        from .sets import merge_version_listings
        t0 = time.monotonic()
        if self.metacache is not None:
            page = self.metacache.serve_list_object_versions(
                bucket, prefix, marker, max_keys, version_marker,
                delimiter)
            if page is not None:
                self._observe_listing("versions", "index", t0)
                return page
        per_zone = [z.list_object_versions(bucket, prefix, marker,
                                           max_keys, version_marker,
                                           delimiter)
                    for z in self.server_sets]
        out = merge_version_listings(per_zone, max_keys)
        self._observe_listing("versions", "walk", t0)
        return out

    def object_versions(self, bucket, name):
        """Cross-pool quorum-merged versions of one object (dedup by
        version id, newest first)."""
        out = []
        seen = set()
        for z in self.server_sets:
            try:
                for oi in z.object_versions(bucket, name):
                    if oi.version_id not in seen:
                        seen.add(oi.version_id)
                        out.append(oi)
            except api_errors.ObjectApiError:
                continue
        # (mod time, version id) newest first — the deterministic
        # conflict order shared with the engine quorum merge
        out.sort(key=lambda o: (o.mod_time or 0, o.version_id or ""),
                 reverse=True)
        return out

    @staticmethod
    def _observe_listing(verb: str, source: str, t0: float) -> None:
        from .metacache import listing_histogram
        listing_histogram().observe(time.monotonic() - t0, verb=verb,
                                    source=source)

    def storage_info(self) -> dict:
        zones = [z.storage_info() for z in self.server_sets]
        for i, z in enumerate(zones):
            z["pool_state"] = self.topology.state(i)
        return {"total": sum(z["total"] for z in zones),
                "free": sum(z["free"] for z in zones),
                "used": sum(z["used"] for z in zones),
                "online_disks": sum(z["online_disks"] for z in zones),
                "offline_disks": sum(z["offline_disks"] for z in zones),
                "topology_epoch": self.topology.epoch,
                "zones": zones}

    # ------------------------------------------------------------------
    # topology plane: expansion, decommission, rebalance control
    # ------------------------------------------------------------------

    def add_pool(self, sets: ErasureSets) -> int:
        """Online expansion: append one pool, replicate existing bucket
        namespace onto it, bump+persist the placement epoch. Returns
        the new pool index."""
        for vol in self.list_buckets():
            try:
                sets.make_bucket(vol.name)
            except api_errors.BucketExists:
                pass
        self.server_sets.append(sets)
        if self._ns_listeners:
            # the new pool's engines must feed the listeners like
            # boot-time pools, or its writes would be invisible to the
            # index/cache until reconcile
            sets.on_namespace_change = self._dispatch_namespace_change
        # boot-time RE-attach must not forget a persisted state: the
        # map loaded at boot was truncated to the CLI drive list's pool
        # count, so a node that crashed mid-drain and reboots with
        # --pool would re-register the draining pool as active and
        # silently abandon the drain (found by the crash harness).
        # Adopt the persisted doc's state for this index when it has
        # one; genuinely new pools still default to active.
        state = POOL_ACTIVE
        persisted = TopologyStore.load(self)
        idx = len(self.server_sets) - 1
        if persisted is not None and len(persisted.states) > idx \
                and persisted.epoch >= self.topology.epoch:
            state = persisted.states[idx]
        self.topology.add_pool(state)
        TopologyStore.save(self, self.topology)
        # a drain parked for lack of target capacity — or adopted as
        # still-draining above — can proceed now
        self.resume_rebalance_if_pending()
        return len(self.server_sets) - 1

    def set_pool_state(self, pool: int, state: str) -> int:
        """Persisted state transition (suspend/resume a pool for
        writes). Durable BEFORE it takes effect: the epoch doc is
        written first, so a crash mid-transition replays it."""
        prev = self.topology.state(pool) \
            if 0 <= pool < len(self.server_sets) else None
        epoch = self.topology.set_state(pool, state)
        try:
            TopologyStore.save(self, self.topology)
        except TopologyError:
            if prev is not None:        # roll back the in-memory map
                self.topology.set_state(pool, prev)
            raise
        return epoch

    def start_decommission(self, pool: int, **rebalance_kw) -> dict:
        """Mark `pool` draining and start the background rebalancer
        moving its objects into the remaining active pools."""
        from .rebalance import Rebalancer
        if self._rebalancer is not None and self._rebalancer.running():
            raise TopologyError(
                f"a rebalance of pool {self._rebalancer.source} is "
                "already running")
        if self.topology.state(pool) != POOL_DRAINING:
            self.set_pool_state(pool, POOL_DRAINING)
        # honor a persisted checkpoint by default (a canceled drain
        # restarted via the admin API continues where it stopped; the
        # drain loop's final full sweeps still catch earlier names)
        rebalance_kw.setdefault("resume", True)
        self._rebalancer = Rebalancer(self, pool, **rebalance_kw)
        self._rebalancer.start()
        return {"pool": pool, "epoch": self.topology.epoch,
                "status": "draining"}

    def resume_rebalance_if_pending(self) -> bool:
        """Boot hook (re-armed by add_pool): a pool left in `draining`
        state (process died mid-drain) resumes its rebalance from the
        persisted checkpoint instead of restarting from scratch. A
        drain with no active pool to move INTO stays parked until
        capacity attaches — every move would fail its target choice."""
        from .rebalance import Rebalancer
        if self._rebalancer is not None and self._rebalancer.running():
            return False
        targets = self.topology.write_pools()
        for pool in self.topology.draining_pools():
            if not any(t != pool for t in targets):
                continue
            self._rebalancer = Rebalancer(self, pool, resume=True)
            self._rebalancer.start()
            return True
        return False

    def rebalance_status(self) -> dict:
        out = {"topology": self.topology.to_dict()}
        if self._rebalancer is not None:
            out["rebalance"] = self._rebalancer.status()
        else:
            # a drain may have finished in a previous process: surface
            # the persisted checkpoint so status survives restarts
            from .rebalance import Rebalancer
            for pool in range(len(self.server_sets)):
                doc = Rebalancer.load_checkpoint(self, pool)
                if doc is not None:
                    out.setdefault("checkpoints", []).append(doc)
        return out

    def cancel_rebalance(self) -> dict:
        """Stop the drain and return the pool to active service; the
        checkpoint is kept so a later decommission resumes where this
        one stopped. The pool is reactivated only once the walker has
        ACTUALLY stopped — flipping it active with a move in flight
        would let the walker's source purge race a client write."""
        if self._rebalancer is None:
            raise TopologyError("no rebalance is running")
        reb = self._rebalancer
        if not reb.stop():
            return {"pool": reb.source, "status": "stopping",
                    "epoch": self.topology.epoch}
        if self.topology.state(reb.source) == POOL_DRAINING:
            self.set_pool_state(reb.source, POOL_ACTIVE)
        return {"pool": reb.source, "status": "canceled",
                "epoch": self.topology.epoch}

    # ------------------------------------------------------------------
    # MRF heal queue (per-zone queues, aggregated view)
    # ------------------------------------------------------------------

    def drain_mrf(self, timeout: float = 10.0) -> bool:
        # one shared deadline: N wedged zones must not stack N timeouts
        deadline = time.monotonic() + timeout
        ok = True
        for z in self.server_sets:
            ok = z.drain_mrf(max(0.0, deadline - time.monotonic())) and ok
        return ok

    def mrf_stats(self) -> dict:
        zones = [z.mrf_stats() for z in self.server_sets]
        keys = ("pending", "queued", "healed", "requeued", "failed",
                "dropped", "skipped")
        out = {k: sum(z.get(k, 0) for z in zones) for k in keys}
        out["zones"] = zones
        return out

    def close(self) -> None:
        if self._rebalancer is not None:
            self._rebalancer.stop()
            self._rebalancer = None
        if self.metacache is not None:
            self.metacache.close()
            self.metacache = None
        for z in self.server_sets:
            z.close()
