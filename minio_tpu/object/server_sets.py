"""ErasureServerSets — zones ("server sets") for cluster expansion.

The reference's top ObjectLayer (cmd/erasure-server-sets.go): multiple
independent ErasureSets groups. PUT goes to the zone already holding the
object, else the zone with the most free space weighted by capacity
(getZoneIdx:195, getAvailableZoneIdx:122); GET/HEAD/DELETE scan zones in
order; listings merge across zones.
"""

from __future__ import annotations

import random
import time
from typing import Optional

from ..storage.datatypes import ObjectInfo
from . import api_errors
from .sets import ErasureSets

DISK_FILL_FRACTION = 0.95  # reference diskFillFraction


class ErasureServerSets:
    def __init__(self, server_sets: list[ErasureSets]):
        assert server_sets
        self.server_sets = server_sets

    def single_zone(self) -> bool:
        return len(self.server_sets) == 1

    # ------------------------------------------------------------------
    # zone choice
    # ------------------------------------------------------------------

    def _available_space(self, size: int) -> list[int]:
        """Per-zone available bytes after the write, 0 when it would cross
        the fill watermark (getServerSetsAvailableSpace,
        cmd/erasure-server-sets.go:143-190)."""
        out = []
        for z in self.server_sets:
            info = z.storage_info()
            total, available = info["total"], info["free"]
            if available < size:
                available = 0
            if available > 0:
                available -= size
                want_left = int(total * (1.0 - DISK_FILL_FRACTION))
                if available <= want_left:
                    available = 0
            out.append(available)
        return out

    def get_available_zone_idx(self, size: int) -> int:
        spaces = self._available_space(max(size, 0))
        total = sum(spaces)
        if total == 0:
            return -1
        choose = random.randrange(total)
        at = 0
        for i, a in enumerate(spaces):
            at += a
            if at > choose and a > 0:
                return i
        return -1

    def get_zone_idx(self, bucket: str, object_name: str, size: int) -> int:
        """Zone for a PUT: the zone holding ANY version of the object
        (including a delete marker — version history must stay together)
        wins; else weighted free space (getZoneIdx,
        cmd/erasure-server-sets.go:195)."""
        if self.single_zone():
            return 0
        for i, z in enumerate(self.server_sets):
            if z.has_object_versions(bucket, object_name):
                return i
        idx = self.get_available_zone_idx(size * 2)  # ×2 for parity
        if idx < 0:
            raise api_errors.to_object_err(
                api_errors.InsufficientWriteQuorum(), bucket, object_name)
        return idx

    # ------------------------------------------------------------------
    # bucket ops
    # ------------------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        for z in self.server_sets:
            z.make_bucket(bucket)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        if not force:
            objs, pfx, _ = self.list_objects(bucket, max_keys=1)
            if objs or pfx:
                raise api_errors.BucketNotEmpty(bucket)
        for z in self.server_sets:
            z.delete_bucket(bucket, force=True)

    def bucket_exists(self, bucket: str) -> bool:
        return self.server_sets[0].bucket_exists(bucket)

    def get_bucket_info(self, bucket: str):
        return self.server_sets[0].get_bucket_info(bucket)

    def list_buckets(self):
        return self.server_sets[0].list_buckets()

    def heal_bucket(self, bucket: str) -> None:
        for z in self.server_sets:
            z.heal_bucket(bucket)

    # ------------------------------------------------------------------
    # object ops
    # ------------------------------------------------------------------

    def put_object(self, bucket, object_name, reader, size=-1, opts=None):
        idx = self.get_zone_idx(bucket, object_name,
                                max(size, 0) if size else 0)
        return self.server_sets[idx].put_object(bucket, object_name,
                                                reader, size, opts)

    def _first_zone_with(self, fn, bucket, object_name):
        last: Optional[Exception] = None
        for z in self.server_sets:
            try:
                return fn(z)
            except api_errors.ObjectNotFound as e:
                last = e
        raise last or api_errors.ObjectNotFound(bucket, object_name)

    def get_object(self, bucket, object_name, offset=0, length=-1,
                   opts=None):
        return self._first_zone_with(
            lambda z: z.get_object(bucket, object_name, offset, length,
                                   opts), bucket, object_name)

    def get_object_info(self, bucket, object_name, opts=None):
        return self._first_zone_with(
            lambda z: z.get_object_info(bucket, object_name, opts),
            bucket, object_name)

    def delete_object(self, bucket, object_name, version_id="",
                      versioned=False):
        self.get_bucket_info(bucket)  # missing bucket must not 204
        # a versioned delete WRITES a marker — it must land in the zone
        # holding the object's history, never blindly in zone 0
        for z in self.server_sets:
            if z.has_object_versions(bucket, object_name):
                return z.delete_object(bucket, object_name, version_id,
                                       versioned)
        if versioned and not version_id:
            # S3: versioned DELETE of a missing key still writes a marker
            idx = self.get_available_zone_idx(1 << 20)
            if idx < 0:
                raise api_errors.InsufficientWriteQuorum()
            return self.server_sets[idx].delete_object(
                bucket, object_name, version_id, versioned)
        raise api_errors.ObjectNotFound(bucket, object_name)

    def delete_objects(self, bucket, objects):
        if self.single_zone():
            self.get_bucket_info(bucket)
            return self.server_sets[0].delete_objects(bucket, objects)
        out = []
        for o in objects:
            try:
                self.delete_object(bucket, o)
                out.append(None)
            except Exception as e:  # noqa: BLE001 — per-key result list
                out.append(e)
        return out

    def heal_object(self, bucket, object_name, version_id="",
                    deep_scan=False, dry_run=False):
        return self._first_zone_with(
            lambda z: z.heal_object(bucket, object_name, version_id,
                                    deep_scan, dry_run),
            bucket, object_name)

    def update_object_metadata(self, bucket, object_name, metadata,
                               version_id=""):
        return self._first_zone_with(
            lambda z: z.update_object_metadata(bucket, object_name,
                                               metadata, version_id),
            bucket, object_name)

    # ------------------------------------------------------------------
    # multipart: session created in the chosen PUT zone; subsequent calls
    # find the zone owning the uploadID
    # ------------------------------------------------------------------

    def new_multipart_upload(self, bucket, object_name, opts=None):
        idx = self.get_zone_idx(bucket, object_name, 1 << 30)
        return self.server_sets[idx].new_multipart_upload(
            bucket, object_name, opts)

    def _zone_of_upload(self, bucket, object_name, upload_id):
        for z in self.server_sets:
            try:
                z.list_object_parts(bucket, object_name, upload_id,
                                    max_parts=1)
                return z
            except api_errors.InvalidUploadID:
                continue
        raise api_errors.InvalidUploadID(upload_id)

    def put_object_part(self, bucket, object_name, upload_id, part_number,
                        reader, size=-1):
        z = self._zone_of_upload(bucket, object_name, upload_id)
        return z.put_object_part(bucket, object_name, upload_id,
                                 part_number, reader, size)

    def list_object_parts(self, bucket, object_name, upload_id,
                          part_marker=0, max_parts=1000):
        z = self._zone_of_upload(bucket, object_name, upload_id)
        return z.list_object_parts(bucket, object_name, upload_id,
                                   part_marker, max_parts)

    def list_multipart_uploads(self, bucket, object_name=""):
        out = []
        for z in self.server_sets:
            out.extend(z.list_multipart_uploads(bucket, object_name))
        out.sort(key=lambda u: (u["object"], u["upload_id"]))
        return out

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        z = self._zone_of_upload(bucket, object_name, upload_id)
        return z.abort_multipart_upload(bucket, object_name, upload_id)

    def get_multipart_info(self, bucket, object_name, upload_id):
        z = self._zone_of_upload(bucket, object_name, upload_id)
        return z.get_multipart_info(bucket, object_name, upload_id)

    def complete_multipart_upload(self, bucket, object_name, upload_id,
                                  parts):
        z = self._zone_of_upload(bucket, object_name, upload_id)
        return z.complete_multipart_upload(bucket, object_name, upload_id,
                                           parts)

    # ------------------------------------------------------------------
    # listing
    # ------------------------------------------------------------------

    def list_objects(self, bucket, prefix="", marker="", delimiter="",
                     max_keys=1000):
        from .sets import merge_listings
        per_zone = [z.list_objects(bucket, prefix, marker, delimiter,
                                   max_keys)
                    for z in self.server_sets]
        return merge_listings(per_zone, max_keys)

    def list_object_versions(self, bucket, prefix="", marker="",
                             max_keys=1000):
        out = []
        for z in self.server_sets:
            out.extend(z.list_object_versions(bucket, prefix, marker,
                                              max_keys))
        out.sort(key=lambda o: (o.name, -o.mod_time))
        return out[:max_keys]

    def storage_info(self) -> dict:
        zones = [z.storage_info() for z in self.server_sets]
        return {"total": sum(z["total"] for z in zones),
                "free": sum(z["free"] for z in zones),
                "used": sum(z["used"] for z in zones),
                "online_disks": sum(z["online_disks"] for z in zones),
                "offline_disks": sum(z["offline_disks"] for z in zones),
                "zones": zones}

    # ------------------------------------------------------------------
    # MRF heal queue (per-zone queues, aggregated view)
    # ------------------------------------------------------------------

    def drain_mrf(self, timeout: float = 10.0) -> bool:
        # one shared deadline: N wedged zones must not stack N timeouts
        deadline = time.monotonic() + timeout
        ok = True
        for z in self.server_sets:
            ok = z.drain_mrf(max(0.0, deadline - time.monotonic())) and ok
        return ok

    def mrf_stats(self) -> dict:
        zones = [z.mrf_stats() for z in self.server_sets]
        keys = ("pending", "queued", "healed", "requeued", "failed",
                "dropped", "skipped")
        out = {k: sum(z.get(k, 0) for z in zones) for k in keys}
        out["zones"] = zones
        return out

    def close(self) -> None:
        for z in self.server_sets:
            z.close()
