"""ErasureSets — S = setCount × setDriveCount drives, object→set routing.

The reference's erasureSets layer (cmd/erasure-sets.go): each object maps
to exactly one erasure set by SipHash-2-4 of its name keyed by the
deployment ID (sipHashMod:590); bucket operations fan out to every set;
listings merge across sets. Includes the MRF ("most recently failed")
heal queue fed by degraded reads (maintainMRFList:1641, healMRFRoutine)
and the format bootstrap (waitForFormatErasure semantics,
cmd/prepare-storage.go).

The EP analog of SURVEY §2.5: set routing is static "expert" routing on
the host control plane; each set's device batches stay independent.
"""

from __future__ import annotations

import uuid as _uuid
from typing import Optional

from ..storage import errors as serr
from ..storage.datatypes import ObjectInfo, last_version_marker
from ..storage.format import (DISTRIBUTION_ALGO_V2, DISTRIBUTION_ALGO_V3,
                              FormatErasureV3, get_format_in_quorum,
                              new_format_erasure_v3)
from ..storage.xl_storage import XLStorage
from ..utils.siphash import crc_hash_mod, sip_hash_mod
from . import ErasureSetObjects, api_errors
from .background import MRFHealer
from .engine import GetOptions, PutOptions
from .nslock import NSLockMap


class ErasureSets:
    """Routes the ObjectLayer surface over `set_count` erasure sets."""

    def __init__(self, sets: list[ErasureSetObjects], deployment_id: str,
                 distribution_algo: str = DISTRIBUTION_ALGO_V3,
                 enable_mrf: bool = True,
                 format_ref: Optional[FormatErasureV3] = None,
                 slot_sources: Optional[list] = None,
                 mrf_options: Optional[dict] = None):
        self.sets = sets
        self.deployment_id = deployment_id
        self.distribution_algo = distribution_algo
        # topology reference + per-slot drive sources (root path or live
        # StorageAPI), set-major order — the reconnect/new-disk monitor's
        # map of what belongs where (reference erasure-sets endpoints)
        self.format_ref = format_ref
        self.slot_sources = slot_sources
        self._id16 = _uuid.UUID(deployment_id).bytes
        self._closed = False
        self.mrf: Optional[MRFHealer] = None
        # metacache delta feed: engines report namespace mutations up
        # through this layer; server_sets (or a test harness) points it
        # at the MetacacheManager journal (object/metacache.py)
        self.on_namespace_change = None
        for s in self.sets:
            s.on_namespace_change = self._notify_namespace
        if enable_mrf:
            self.mrf = MRFHealer(self._heal_mrf_entry, **(mrf_options or {}))
            for s in self.sets:
                # degraded READS (reconstruction/bitrot) and degraded
                # WRITES (quorum met but drives lost) both feed the MRF
                # queue (reference maintainMRFList + healMRFRoutine)
                s.on_degraded_read = self._queue_mrf_heal
                s.on_degraded_write = self._queue_mrf_heal

    @property
    def supports_sse_device(self) -> bool:
        return all(getattr(s, "supports_sse_device", False)
                   for s in self.sets)

    # ------------------------------------------------------------------
    # construction from drives (format bootstrap)
    # ------------------------------------------------------------------

    @classmethod
    def from_drives(cls, drive_roots: list[str], set_count: int,
                    set_drive_count: int, parity: int,
                    block_size: int = 1 << 22,
                    ns_lock: Optional[NSLockMap] = None,
                    **engine_kw) -> "ErasureSets":
        """Open (formatting if fresh) setCount×setDriveCount local drives
        (reference waitForFormatErasure + newErasureSets,
        cmd/prepare-storage.go / cmd/erasure-sets.go:337)."""
        # a faulty drive becomes a None slot, never a bootstrap abort
        # (reference: sets open with offline slots, reconnect monitor
        # picks them up later)
        drives: list = []
        for r in drive_roots:
            try:
                drives.append(XLStorage(r))
            except serr.StorageError:
                drives.append(None)
        return cls.from_storage(drives, set_count, set_drive_count, parity,
                                block_size=block_size, ns_lock=ns_lock,
                                sources=list(drive_roots), **engine_kw)

    @classmethod
    def from_storage(cls, drives: list, set_count: int,
                     set_drive_count: int, parity: int,
                     block_size: int = 1 << 22,
                     ns_lock: Optional[NSLockMap] = None,
                     create_format: bool = True,
                     sources: Optional[list] = None,
                     **engine_kw) -> "ErasureSets":
        """Assemble sets over arbitrary StorageAPI drives — local
        XLStorage and/or RemoteStorage (the distributed boot path,
        reference newErasureSets over storage REST clients,
        cmd/erasure-sets.go:337-430).

        create_format=False makes an unformatted cluster an error instead
        of a fresh format write (non-first nodes wait for the first node
        to format, cmd/prepare-storage.go waitForFormatErasure).
        """
        from ..storage.format import read_format_from, write_format_to
        assert len(drives) == set_count * set_drive_count
        enable_mrf = engine_kw.pop("enable_mrf", True)
        mrf_options = engine_kw.pop("mrf_options", None)
        formats: list[Optional[FormatErasureV3]] = []
        for d in drives:
            if d is None:
                formats.append(None)
                continue
            try:
                formats.append(read_format_from(d))
            except serr.StorageError:
                formats.append(None)

        if all(f is None for f in formats):
            if all(d is None for d in drives):
                raise serr.DiskNotFound("no usable drives")
            if not create_format:
                raise serr.UnformattedDisk(
                    "cluster not formatted yet (waiting for first node)")
            fresh = new_format_erasure_v3(set_count, set_drive_count)
            for i in range(set_count):
                for j in range(set_drive_count):
                    d = drives[i * set_drive_count + j]
                    if d is None:
                        continue
                    try:
                        write_format_to(d, fresh[i][j])
                        formats[i * set_drive_count + j] = \
                            read_format_from(d)
                    except serr.StorageError:
                        pass
        else:
            ref = get_format_in_quorum(formats)
            # heal drives with missing format (fresh replacements)
            for idx, f in enumerate(formats):
                if f is None and drives[idx] is not None:
                    # the slot's expected UUID is position-derived
                    si, di = idx // set_drive_count, idx % set_drive_count
                    import dataclasses
                    nf = dataclasses.replace(
                        ref, this=ref.sets[si][di])
                    try:
                        write_format_to(drives[idx], nf)
                        formats[idx] = read_format_from(drives[idx])
                    except serr.StorageError:
                        pass

        deployment_id = next(f.id for f in formats if f is not None)
        ref_sets = next(f.sets for f in formats if f is not None)

        # order drives by their position in the format's sets matrix
        by_uuid = {}
        src_by_uuid = {}
        if sources is None:
            sources = list(drives)
        for idx, (d, f) in enumerate(zip(drives, formats)):
            if d is not None and f is not None:
                by_uuid[f.this] = d
                src_by_uuid[f.this] = sources[idx]
        from ..storage.diskid_check import DiskIDCheck
        ns = ns_lock or NSLockMap()
        sets = []
        slot_sources = []
        for i in range(set_count):
            # every drive is identity-guarded: a swap/reformat behind a
            # running set reads as DiskStale, never as wrong shards
            # (cmd/xl-storage-disk-id-check.go)
            set_drives = [
                DiskIDCheck(by_uuid[ref_sets[i][j]], ref_sets[i][j])
                if ref_sets[i][j] in by_uuid else None
                for j in range(set_drive_count)]
            # per-slot source: the drive that attested the slot's UUID,
            # else the position-derived input (same heuristic the
            # format-heal above uses for fresh replacements)
            slot_sources.append([
                src_by_uuid.get(ref_sets[i][j],
                                sources[i * set_drive_count + j])
                for j in range(set_drive_count)])
            sets.append(ErasureSetObjects(
                set_drives, set_drive_count - parity, parity,
                block_size=block_size, ns_lock=ns, set_index=i,
                **engine_kw))
        fmt_ref = FormatErasureV3(id=deployment_id,
                                  sets=[list(s) for s in ref_sets])
        return cls(sets, deployment_id, enable_mrf=enable_mrf,
                   format_ref=fmt_ref, slot_sources=slot_sources,
                   mrf_options=mrf_options)

    # ------------------------------------------------------------------
    # routing
    # ------------------------------------------------------------------

    def get_hashed_set_index(self, object_name: str) -> int:
        if self.distribution_algo == DISTRIBUTION_ALGO_V2:
            return crc_hash_mod(object_name, len(self.sets))
        return sip_hash_mod(object_name, len(self.sets), self._id16)

    def get_hashed_set(self, object_name: str) -> ErasureSetObjects:
        return self.sets[self.get_hashed_set_index(object_name)]

    # ------------------------------------------------------------------
    # MRF heal queue (cmd/erasure-sets.go:1641-1711 + background-heal-ops)
    # ------------------------------------------------------------------

    def _queue_mrf_heal(self, bucket: str, object_name: str,
                        version_id: str = "") -> None:
        if self.mrf is not None:
            self.mrf.enqueue(bucket, object_name, version_id)

    def _notify_namespace(self, bucket: str, object_name: str) -> None:
        cb = self.on_namespace_change
        if cb is not None:
            cb(bucket, object_name)

    def _heal_mrf_entry(self, bucket: str, object_name: str,
                        version_id: str = ""):
        # the HealResultItem must flow back: MRFHealer retries while
        # result.missing_after > 0 (partial heal, a drive still gone)
        return self.get_hashed_set(object_name).heal_object(
            bucket, object_name, version_id)

    def drain_mrf(self, timeout: float = 10.0) -> bool:
        """Wait for queued MRF heals to COMPLETE (not just dequeue)."""
        if self.mrf is None:
            return True
        return self.mrf.drain(timeout)

    def mrf_stats(self) -> dict:
        return self.mrf.stats() if self.mrf is not None else {}

    def close(self) -> None:
        self._closed = True
        if self.mrf is not None:
            self.mrf.close()

    # ------------------------------------------------------------------
    # bucket ops (fan out to every set)
    # ------------------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        done = []
        try:
            for s in self.sets:
                s.make_bucket(bucket)
                done.append(s)
        except api_errors.BucketExists:
            raise
        except Exception:
            for s in done:  # undo partial create (reference undoMakeBucket)
                try:
                    s.delete_bucket(bucket, force=True)
                except Exception:  # noqa: BLE001
                    pass
            raise

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        self.get_bucket_info(bucket)
        if not force:
            objs, _, _ = self.list_objects(bucket, max_keys=1)
            if objs:
                raise api_errors.BucketNotEmpty(bucket)
        for s in self.sets:
            s.delete_bucket(bucket, force=True)

    def bucket_exists(self, bucket: str) -> bool:
        return self.sets[0].bucket_exists(bucket)

    def get_bucket_info(self, bucket: str):
        return self.sets[0].get_bucket_info(bucket)

    def list_buckets(self):
        return self.sets[0].list_buckets()

    def heal_bucket(self, bucket: str) -> None:
        for s in self.sets:
            s.heal_bucket(bucket)

    # ------------------------------------------------------------------
    # object ops (route by hash)
    # ------------------------------------------------------------------

    def put_object(self, bucket, object_name, reader, size=-1, opts=None):
        return self.get_hashed_set(object_name).put_object(
            bucket, object_name, reader, size, opts)

    def get_object(self, bucket, object_name, offset=0, length=-1,
                   opts=None):
        return self.get_hashed_set(object_name).get_object(
            bucket, object_name, offset, length, opts)

    def get_object_info(self, bucket, object_name, opts=None):
        return self.get_hashed_set(object_name).get_object_info(
            bucket, object_name, opts)

    def delete_object(self, bucket, object_name, version_id="",
                      versioned=False):
        return self.get_hashed_set(object_name).delete_object(
            bucket, object_name, version_id, versioned)

    def delete_objects(self, bucket, objects):
        """Bulk delete grouped by erasure set: each set's batch goes to
        its engine's one-call-per-drive path."""
        by_set: dict[int, list[int]] = {}
        for j, o in enumerate(objects):
            by_set.setdefault(self.get_hashed_set_index(o), []).append(j)
        out: list = [None] * len(objects)
        for si, idxs in by_set.items():
            errs = self.sets[si].delete_objects(
                bucket, [objects[j] for j in idxs])
            for j, e in zip(idxs, errs):
                out[j] = e
        return out

    def heal_object(self, bucket, object_name, version_id="",
                    deep_scan=False, dry_run=False):
        return self.get_hashed_set(object_name).heal_object(
            bucket, object_name, version_id, deep_scan, dry_run)

    def update_object_metadata(self, bucket, object_name, metadata,
                               version_id=""):
        return self.get_hashed_set(object_name).update_object_metadata(
            bucket, object_name, metadata, version_id)

    def transition_object(self, bucket, object_name, version_id="",
                          tier="", remote_object="", remote_version="",
                          expect_etag="", expect_mod_time=None):
        return self.get_hashed_set(object_name).transition_object(
            bucket, object_name, version_id, tier, remote_object,
            remote_version, expect_etag, expect_mod_time)

    def put_stub_version(self, bucket, object_name, info,
                         if_none_newer=False):
        return self.get_hashed_set(object_name).put_stub_version(
            bucket, object_name, info, if_none_newer)

    def has_object_versions(self, bucket, object_name) -> bool:
        return self.get_hashed_set(object_name).has_object_versions(
            bucket, object_name)

    def latest_file_info(self, bucket, object_name):
        return self.get_hashed_set(object_name).latest_file_info(
            bucket, object_name)

    def put_delete_marker(self, bucket, object_name, version_id="",
                          mod_time=None, metadata=None):
        return self.get_hashed_set(object_name).put_delete_marker(
            bucket, object_name, version_id, mod_time, metadata)

    # ------------------------------------------------------------------
    # multipart (route by object name)
    # ------------------------------------------------------------------

    def new_multipart_upload(self, bucket, object_name, opts=None,
                             upload_id=None):
        return self.get_hashed_set(object_name).new_multipart_upload(
            bucket, object_name, opts, upload_id=upload_id)

    def put_object_part(self, bucket, object_name, upload_id, part_number,
                        reader, size=-1):
        return self.get_hashed_set(object_name).put_object_part(
            bucket, object_name, upload_id, part_number, reader, size)

    def read_multipart_part(self, bucket, object_name, upload_id,
                            part_number):
        return self.get_hashed_set(object_name).read_multipart_part(
            bucket, object_name, upload_id, part_number)

    def list_object_parts(self, bucket, object_name, upload_id,
                          part_marker=0, max_parts=1000):
        return self.get_hashed_set(object_name).list_object_parts(
            bucket, object_name, upload_id, part_marker, max_parts)

    def list_multipart_uploads(self, bucket, object_name=""):
        if object_name:
            return self.get_hashed_set(object_name).list_multipart_uploads(
                bucket, object_name)
        out = []
        for s in self.sets:
            out.extend(s.list_multipart_uploads(bucket))
        out.sort(key=lambda u: (u["object"], u["upload_id"]))
        return out

    def list_all_multipart_uploads(self):
        out = []
        for s in self.sets:
            out.extend(s.list_all_multipart_uploads())
        out.sort(key=lambda u: (u["bucket"], u["object"],
                                u["upload_id"]))
        return out

    def mark_multipart_session(self, bucket, object_name, upload_id,
                               extra):
        return self.get_hashed_set(object_name).mark_multipart_session(
            bucket, object_name, upload_id, extra)

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        return self.get_hashed_set(object_name).abort_multipart_upload(
            bucket, object_name, upload_id)

    def get_multipart_info(self, bucket, object_name, upload_id):
        return self.get_hashed_set(object_name).get_multipart_info(
            bucket, object_name, upload_id)

    def complete_multipart_upload(self, bucket, object_name, upload_id,
                                  parts, version_id="", mod_time=None,
                                  if_none_newer=False):
        return self.get_hashed_set(object_name).complete_multipart_upload(
            bucket, object_name, upload_id, parts, version_id, mod_time,
            if_none_newer)

    # ------------------------------------------------------------------
    # listing (merge across sets; cmd/erasure-sets.go merge walks)
    # ------------------------------------------------------------------

    def list_objects(self, bucket: str, prefix: str = "", marker: str = "",
                     delimiter: str = "", max_keys: int = 1000
                     ) -> tuple[list[ObjectInfo], list[str], bool]:
        per_set = [s.list_objects(bucket, prefix, marker, delimiter,
                                  max_keys)
                   for s in self.sets]
        return merge_listings(per_set, max_keys)

    def list_object_versions(self, bucket, prefix="", marker="",
                             max_keys=1000, version_marker="",
                             delimiter=""):
        per_set = [s.list_object_versions(bucket, prefix, marker,
                                          max_keys, version_marker,
                                          delimiter)
                   for s in self.sets]
        return merge_version_listings(per_set, max_keys)

    def object_versions(self, bucket: str, name: str):
        """Quorum-merged versions of one object (newest first) from the
        set that owns it — the pool-local per-name read of the
        rebalance/metacache feed paths."""
        return self.get_hashed_set(name).object_versions(bucket, name)

    # ------------------------------------------------------------------
    # info / usage
    # ------------------------------------------------------------------

    def storage_info(self) -> dict:
        """Aggregate drive capacity (reference StorageInfo)."""
        total = free = online = offline = 0
        for s in self.sets:
            for d in s.disks:
                if d is None or not d.is_online():
                    offline += 1
                    continue
                try:
                    di = d.disk_info()
                    total += di.total
                    free += di.free
                    online += 1
                except serr.StorageError:
                    offline += 1
        return {"total": total, "free": free, "used": total - free,
                "online_disks": online, "offline_disks": offline,
                "sets": len(self.sets),
                "drives_per_set": len(self.sets[0].disks)}

def merge_version_listings(per_layer: list[tuple], max_keys: int
                           ) -> tuple[list[ObjectInfo], list[str], str,
                                      str, bool]:
    """Merge per-set/per-zone version pages into one `(versions,
    common_prefixes, next_key_marker, next_version_id_marker,
    is_truncated)` page — the single home of the cross-layer version
    paging rules. Duplicate (name, version_id) pairs (one object
    transiently in two pools mid-rebalance) collapse to the first
    layer's copy; order is (name asc, mod_time desc), stable within
    ties; rolled-up prefixes interleave lexically with the keys and
    each count one entry toward max_keys (S3 semantics)."""
    seen: set[tuple[str, str]] = set()
    by_name: dict[str, list[ObjectInfo]] = {}
    prefixes: set[str] = set()
    any_truncated = False
    for versions, pfx, _nkm, _nvm, trunc in per_layer:
        any_truncated = any_truncated or trunc
        prefixes.update(pfx)
        for o in versions:
            key = (o.name, o.version_id)
            if key not in seen:
                seen.add(key)
                by_name.setdefault(o.name, []).append(o)
    # one lexical entry stream: keys (carrying their version lists)
    # interleaved with rolled-up prefixes, like merge_listings
    entries = sorted([(n, False) for n in by_name]
                     + [(p, True) for p in prefixes])
    out_vers: list[ObjectInfo] = []
    out_pfx: list[str] = []
    count = 0
    truncated = any_truncated
    for name, is_pfx in entries:
        if count >= max_keys:
            truncated = True
            break
        if is_pfx:
            out_pfx.append(name)
            count += 1
            continue
        # mod time then version id, newest first — the same
        # deterministic order the engine's quorum merge uses (the
        # active-active conflict rule: two sites holding one version
        # set must page it identically, mod-time ties included)
        vers = sorted(by_name[name],
                      key=lambda o: (o.mod_time or 0, o.version_id or ""),
                      reverse=True)
        for o in vers:
            if count >= max_keys:
                truncated = True
                break
            out_vers.append(o)
            count += 1
    if truncated and (out_vers or out_pfx):
        nkm, nvm = last_version_marker(out_vers, out_pfx)
        return out_vers, out_pfx, nkm, nvm, True
    return out_vers, out_pfx, "", "", truncated


def merge_listings(per_layer: list[tuple[list[ObjectInfo], list[str], bool]],
                   max_keys: int
                   ) -> tuple[list[ObjectInfo], list[str], bool]:
    """Merge per-set/per-zone listing pages into one lexically sorted page
    (the single home of the merge-walk truncation rules)."""
    objects: dict[str, ObjectInfo] = {}
    prefixes: set[str] = set()
    any_truncated = False
    for objs, pfx, trunc in per_layer:
        for o in objs:
            objects.setdefault(o.name, o)
        prefixes.update(pfx)
        any_truncated = any_truncated or trunc
    merged = sorted([(n, False) for n in objects]
                    + [(p, True) for p in prefixes])
    out_objs: list[ObjectInfo] = []
    out_pfx: list[str] = []
    truncated = any_truncated
    for name, is_pfx in merged:
        if len(out_objs) + len(out_pfx) >= max_keys:
            truncated = True
            break
        if is_pfx:
            out_pfx.append(name)
        else:
            out_objs.append(objects[name])
    return out_objs, out_pfx, truncated
