"""ErasureObjects — object CRUD on one erasure set.

The per-set object engine (reference erasureObjects, cmd/erasure-object.go
+ cmd/erasure.go): N = k+m drives, every object's shards distributed by
hashOrder, xl.meta written to all drives, quorum-checked reads/writes,
2-phase commit through .minio.sys/tmp.

TPU-first deltas vs the reference's per-block loop:
  * The PUT hot loop aggregates up to ENCODE_BATCH_BLOCKS full blocks and
    encodes + bitrot-digests them as one fused device program
    (cmd/erasure-encode.go's block loop + cmd/bitrot-streaming.go,
    batched for the MXU/VPU); the cross-request scheduler coalesces
    concurrent streams into shared dispatches.
  * Degraded GETs read GET_BATCH_BLOCKS blocks per group and
    batch verify+reconstruct every block sharing an erasure pattern in
    one fused device dispatch (cmd/erasure-decode.go:111-211 semantics
    — see _verify_and_reconstruct_group).
  * MD5/ETag runs on a background thread overlapped with encode — the
    generalized QAT async-MD5 pattern (cmd/erasure-encode.go:113-124).
"""

from __future__ import annotations

import os
import threading
import time
import uuid as _uuid
from typing import BinaryIO, Iterator, Optional

import numpy as np

from .. import bitrot as bitrot_mod
from ..storage import errors as serr
from ..utils import crashpoint, healthtrack, knobs, stagetimer, telemetry
from ..storage.api import StorageAPI
from ..storage.datatypes import (BLOCK_SIZE_V1, RESTORE_EXPIRY_KEY,
                                 RESTORE_KEY, TRANSITION_COMPLETE,
                                 TRANSITION_STATUS_KEY,
                                 TRANSITION_TIER_KEY,
                                 TRANSITIONED_OBJECT_KEY,
                                 TRANSITIONED_VERSION_KEY, ChecksumInfo,
                                 FileInfo, ObjectInfo, is_restored,
                                 is_transitioned, last_version_marker,
                                 new_file_info, now)
from ..storage.xl_storage import (MINIO_META_BUCKET,
                                  MINIO_META_MULTIPART_BUCKET,
                                  MINIO_META_TMP_BUCKET)
from . import api_errors, bitrot_io, metadata as meta
from .codec import Codec
from .hash_reader import HashReader
from .nslock import NSLockMap

ENCODE_BATCH_BLOCKS = knobs.get_int("MINIO_TPU_ENCODE_BATCH")
GET_BATCH_BLOCKS = knobs.get_int("MINIO_TPU_GET_BATCH")


def _sse_pkg() -> int:
    """features/crypto.PKG_SIZE without a module-level crypto import
    (crypto pulls optional deps the bare engine must not require)."""
    from ..features.crypto import PKG_SIZE
    return PKG_SIZE

# Reserved bucket names an S3 client can't touch.
RESERVED_BUCKETS = (MINIO_META_BUCKET,)


class PutOptions:
    def __init__(self, metadata: Optional[dict] = None,
                 version_id: str = "", versioned: bool = False,
                 parity: Optional[int] = None,
                 mod_time: Optional[float] = None,
                 if_none_newer: bool = False,
                 sse_spec=None):
        self.metadata = dict(metadata or {})
        self.version_id = version_id
        self.versioned = versioned
        self.parity = parity
        # features/crypto.DeviceSSE for the fused cipher+RS+digest PUT
        # path: the reader then carries PLAINTEXT and the engine
        # ciphers in-batch, appending the Poly1305 tag trailer at
        # stream end (None = any cipher ran as a reader transform)
        self.sse_spec = sse_spec
        # explicit mod time: server-side copies (rebalance pool moves)
        # preserve the object's original Last-Modified instead of
        # stamping the move time
        self.mod_time = mod_time
        # replication apply of the UNVERSIONED slot: commit only when
        # no existing null version is (mod_time, version_id)-newer —
        # evaluated INSIDE the per-key write lock, so a client write
        # racing the apply can never be clobbered by an older replica
        # (PreConditionFailed otherwise; the check-then-put a caller
        # could do itself is a TOCTOU hole)
        self.if_none_newer = if_none_newer


class GetOptions:
    def __init__(self, version_id: str = ""):
        self.version_id = version_id


_GET_STREAMS = None


def _get_streams_counter():
    """Resolved once — the registry lookup takes the global metrics
    mutex, which the per-GET hot path must not contend on."""
    global _GET_STREAMS
    if _GET_STREAMS is None:
        _GET_STREAMS = telemetry.REGISTRY.counter(
            "minio_tpu_erasure_get_streams_total",
            "Object read streams served through the erasure "
            "shard-read/verify/decode path")
    return _GET_STREAMS


class ErasureObjects:
    """One erasure set over `disks` (k data + m parity)."""

    def __init__(self, disks: list[Optional[StorageAPI]],
                 data_shards: int, parity_shards: int,
                 block_size: int = BLOCK_SIZE_V1,
                 ns_lock: Optional[NSLockMap] = None,
                 bitrot_algo: bitrot_mod.BitrotAlgorithm =
                 bitrot_mod.DEFAULT_BITROT_ALGORITHM,
                 set_index: int = 0,
                 scheduler=None):
        assert len(disks) == data_shards + parity_shards
        self.disks = disks
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.block_size = block_size
        self.bitrot_algo = bitrot_algo
        self.ns = ns_lock or NSLockMap()
        self.set_index = set_index
        # optional cross-request batch former (parallel/scheduler.py)
        self.scheduler = scheduler
        self._codec_cache: dict[tuple[int, int], Codec] = {}
        # MRF hook: called (bucket, object) when a GET had to reconstruct
        # or hit bitrot — the sets layer queues a heal (reference
        # deepHealObject trigger, cmd/erasure-object.go:298-303)
        self.on_degraded_read = None
        # MRF hook: called (bucket, object, version_id) when a write
        # (PUT / delete / metadata) met quorum but some drives failed —
        # the degraded object regains full redundancy via the background
        # heal queue instead of waiting for the next scanner sweep
        # (reference maintainMRFList, cmd/erasure-sets.go:1641)
        self.on_degraded_write = None
        # metacache hook: called (bucket, object) after EVERY successful
        # namespace mutation (PUT / delete / delete marker / transition
        # / metadata update / multipart commit) — feeds the persisted
        # bucket index's delta journal (object/metacache.py). Must never
        # block: the receiver only appends to a bounded queue.
        self.on_namespace_change = None

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------

    def codec(self, k: int, m: int) -> Codec:
        key = (k, m)
        if key not in self._codec_cache:
            self._codec_cache[key] = Codec(k, m, self.block_size)
        return self._codec_cache[key]

    @property
    def supports_sse_device(self) -> bool:
        """Whether this layer can run the fused cipher+RS+digest PUT
        path (PutOptions.sse_spec): the package stream must tile the
        erasure blocks exactly, so full blocks carry whole ChaCha20
        packages through the batch former."""
        from ..features.crypto import PKG_SIZE
        return self.block_size % PKG_SIZE == 0

    def get_disks(self) -> list[Optional[StorageAPI]]:
        return list(self.disks)

    def _default_quorums(self, parity: Optional[int] = None
                         ) -> tuple[int, int, int, int]:
        """(data, parity, readQuorum, writeQuorum) for a fresh object
        (cmd/erasure-object.go:536-547)."""
        m = self.parity_shards if parity is None else parity
        k = len(self.disks) - m
        return k, m, k, meta.write_quorum_for(k, m)

    # ------------------------------------------------------------------
    # bucket ops (cmd/erasure-bucket.go)
    # ------------------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        if bucket in RESERVED_BUCKETS or not bucket:
            raise api_errors.BucketNameInvalid(bucket)
        _, errs = meta.for_each_disk(
            self.disks, lambda i, d: d.make_vol(bucket))
        write_quorum = len(self.disks) // 2 + 1
        exists = sum(1 for e in errs if isinstance(e, serr.VolumeExists))
        if exists >= write_quorum:
            raise api_errors.BucketExists(bucket)
        ok = sum(1 for e in errs
                 if e is None or isinstance(e, serr.VolumeExists))
        if ok < write_quorum:
            err = meta.reduce_write_quorum_errs(
                errs, meta.OBJECT_OP_IGNORED_ERRS + (serr.VolumeExists,),
                write_quorum)
            raise api_errors.to_object_err(
                err or api_errors.InsufficientWriteQuorum(), bucket)

    def bucket_exists(self, bucket: str) -> bool:
        try:
            self.get_bucket_info(bucket)
            return True
        except api_errors.BucketNotFound:
            return False

    def get_bucket_info(self, bucket: str):
        results, errs = meta.for_each_disk(
            self.disks, lambda i, d: d.stat_vol(bucket))
        read_quorum = len(self.disks) // 2
        err = meta.reduce_read_quorum_errs(
            errs, meta.OBJECT_OP_IGNORED_ERRS, read_quorum)
        if err is not None:
            raise api_errors.to_object_err(err, bucket)
        for r in results:
            if r is not None:
                return r
        raise api_errors.BucketNotFound(bucket)

    def list_buckets(self):
        """Quorum-merged bucket listing: a bucket counts when a majority
        of drives have its volume — a stale drive that missed a
        make_bucket (or kept a deleted one) while offline can neither
        hide nor resurrect a bucket (reference merges per-disk listings,
        cmd/erasure-sets.go ListBuckets semantics)."""
        counts: dict[str, int] = {}
        infos: dict[str, object] = {}
        answered = 0
        for d in self.disks:
            if d is None:
                continue
            try:
                vols = d.list_vols()
            except serr.StorageError:
                continue
            answered += 1
            for v in vols:
                if v.name.startswith("."):
                    continue
                counts[v.name] = counts.get(v.name, 0) + 1
                prev = infos.get(v.name)
                if prev is None or v.created < prev.created:
                    infos[v.name] = v
        if answered == 0:
            return []
        # read quorum n//2 intersects the n//2+1 write quorum: a bucket
        # created under write quorum stays listed with up to half the
        # drives unreachable (review r3: n//2+1 here could hide a
        # healthy bucket when one writer drive is down)
        quorum = min(answered, max(1, len(self.disks) // 2))
        return sorted((infos[n] for n, c in counts.items()
                       if c >= quorum), key=lambda v: v.name)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        def rm(i, d):
            try:
                d.delete_vol(bucket, force)
            except serr.VolumeNotFound:
                pass

        _, errs = meta.for_each_disk(self.disks, rm)
        write_quorum = len(self.disks) // 2 + 1
        err = meta.reduce_write_quorum_errs(
            errs, meta.OBJECT_OP_IGNORED_ERRS, write_quorum)
        if err is not None:
            raise api_errors.to_object_err(err, bucket)

    # ------------------------------------------------------------------
    # PUT (cmd/erasure-object.go:521-703 + cmd/erasure-encode.go)
    # ------------------------------------------------------------------

    def put_object(self, bucket: str, object_name: str, reader,
                   size: int = -1, opts: Optional[PutOptions] = None
                   ) -> ObjectInfo:
        with telemetry.span("engine.put_object", bucket=bucket,
                            object=object_name, size=size):
            return self._put_object(bucket, object_name, reader, size,
                                    opts)

    def _put_object(self, bucket: str, object_name: str, reader,
                    size: int = -1, opts: Optional[PutOptions] = None
                    ) -> ObjectInfo:
        opts = opts or PutOptions()
        if isinstance(reader, (bytes, bytearray)):
            import io as _io
            size = len(reader)
            reader = HashReader(_io.BytesIO(reader), size)
        elif not isinstance(reader, HashReader):
            reader = HashReader(reader, size)

        k, m, _, write_quorum = self._default_quorums(opts.parity)
        fi = new_file_info(f"{bucket}/{object_name}", k, m)
        fi.erasure.block_size = self.block_size
        fi.volume, fi.name = bucket, object_name
        fi.data_dir = str(_uuid.uuid4())
        if opts.versioned:
            fi.version_id = opts.version_id or str(_uuid.uuid4())

        shuffled = meta.shuffle_disks(self.disks, fi.erasure.distribution)
        tmp_id = str(_uuid.uuid4())
        part_path = f"{tmp_id}/{fi.data_dir}/part.1"
        codec = self.codec(k, m)
        shard_size = codec.shard_size

        writers: list[Optional[object]] = []
        for d in shuffled:
            if d is None:
                writers.append(None)
                continue
            writers.append(bitrot_io.new_bitrot_writer(
                d, MINIO_META_TMP_BUCKET, part_path, -1,
                self.bitrot_algo, shard_size))

        try:
            try:
                total = self._encode_stream(reader, codec, writers,
                                            write_quorum, bucket,
                                            object_name,
                                            sse=opts.sse_spec)
                with stagetimer.stage("put.hash_verify"):
                    reader.verify()
            finally:
                reader.close()  # stop the async hasher even on failure
            etag = opts.metadata.pop("etag", "") or reader.md5_current_hex()

            fi.size = total
            fi.mod_time = opts.mod_time if opts.mod_time else now()
            fi.metadata = dict(opts.metadata)
            fi.metadata["etag"] = etag
            fi.add_object_part(1, etag, total,
                               reader.actual_size
                               if reader.actual_size >= 0 else total)
            fi.erasure.checksums = [
                ChecksumInfo(1, self.bitrot_algo.value, b"")]

            # per-drive metadata then commit (2-phase: tmp -> final)
            with stagetimer.stage("put.lock+commit"):
                with self.ns.new_lock(
                        f"{bucket}/{object_name}").write_locked():
                    if opts.if_none_newer:
                        self._check_none_newer(bucket, object_name, fi)
                    lost = self._commit(shuffled, writers, tmp_id, fi,
                                        bucket, object_name, write_quorum)
        except Exception:
            self._cleanup_tmp(shuffled, tmp_id)
            raise
        if lost:
            # quorum met but some drives missed the write: queue an MRF
            # heal so the object converges back to full redundancy
            self._notify_degraded(bucket, object_name, fi.version_id)
        self._notify_namespace(bucket, object_name)
        return fi.to_object_info(bucket, object_name)

    def _check_none_newer(self, bucket: str, object_name: str,
                          fi: FileInfo) -> None:
        """The if_none_newer commit gate (caller holds the write
        lock): an existing version in the same slot that wins the
        deterministic (mod_time, version_id, etag) conflict rule
        aborts the commit — the replication apply's atomic
        last-writer-wins. The etag tie-break keeps two sites that
        wrote DIFFERENT bytes at the same instant convergent (a full
        tie is identical content, so either copy is fine)."""
        for cur in self._merged_versions(bucket, object_name):
            if (cur.version_id or "") != (fi.version_id or ""):
                continue
            cur_key = (cur.mod_time or 0, cur.version_id or "",
                       cur.metadata.get("etag", ""))
            new_key = (fi.mod_time or 0, fi.version_id or "",
                       fi.metadata.get("etag", ""))
            if cur_key >= new_key:
                raise api_errors.PreConditionFailed(
                    f"{bucket}/{object_name}: existing version is newer")
            return

    def _encode_stream(self, reader, codec: Codec, writers,
                       write_quorum: int, bucket: str,
                       object_name: str, sse=None) -> int:
        """The PUT hot loop: read blocks, batch-encode, batch-hash,
        fan-out framed writes. Returns total bytes.

        Two selectable forms (MINIO_TPU_PIPELINE, default on): the
        pipelined loop overlaps ingest / encode+digest / shard writes
        across a staging-buffer ring; the serial loop runs them
        strictly in sequence on this thread. Streams that fit in ONE
        encode batch stay serial even with the pipeline on — a single
        batch has nothing to overlap, so the stage hand-off would be
        pure latency.

        With `sse` (a features/crypto.DeviceSSE), the reader carries
        PLAINTEXT and the cipher fuses into the encode dispatch: full
        blocks ride the batch former as cipher+RS+digest launches, the
        Poly1305 tag trailer (computed host-side over the returned
        ciphertext) lands at stream end, and the returned total is the
        STORED size (ciphertext + trailer). Any decline or dispatch
        error drops that batch to the in-place CPU cipher — the bytes
        on disk are identical either way."""
        from ..parallel import pipeline as pl
        size = getattr(reader, "size", -1)
        if pl.ENABLED and (size < 0
                           or size > ENCODE_BATCH_BLOCKS
                           * self.block_size):
            return self._encode_stream_pipelined(reader, codec, writers,
                                                 write_quorum, sse=sse)
        return self._encode_stream_serial(reader, codec, writers,
                                          write_quorum, sse=sse)

    def _encode_stream_pipelined(self, reader, codec: Codec, writers,
                                 write_quorum: int, sse=None) -> int:
        """The PUT hot loop, overlapped (the fork's async-QAT pattern,
        cmd/erasure-encode.go:113-124, applied to the WHOLE path): a
        ring of BytePool-backed (B, k·S) staging buffers carries three
        concurrent stages —

          * this thread ingests batch N+1 straight into a pooled buffer
            (and fire-and-forgets the device dispatch for it via
            BatchScheduler.submit, so the reader never blocks on the
            device),
          * the encode stage resolves batch N's fused encode+digest
            (or runs the local CPU fallback),
          * the write stage fans batch N-1's framed shard writes out.

        Bounded stage queues + the shared buffer ring are the memory
        bound: a stalled drive backs pressure up to the reader instead
        of ballooning staging RAM. Same bytes on disk as the serial
        loop — the pad tail [block_size:k·S] of every row is re-zeroed
        on each buffer acquisition (klauspost-identical shard bytes are
        invariant by construction, not by write discipline). The stage
        threads spin up lazily on the FIRST full batch, so an
        unknown-length stream that turns out to fit one batch encodes
        and writes inline with zero pipeline overhead."""
        from ..parallel import pipeline as pl
        k, s_len = codec.k, codec.shard_size
        bs = self.block_size
        cap = ENCODE_BATCH_BLOCKS
        known_size = getattr(reader, "size", -1)
        pool = pl.staging_pool(cap * k * s_len)
        # per-stage wall seconds [ingest, encode, write]; each slot is
        # written by exactly one thread
        stage_s = [0.0, 0.0, 0.0]
        batches = 0
        t_start = time.perf_counter()

        def recycle(item) -> None:
            buf = item.get("buf")
            if buf is not None:
                item["buf"] = None
                pool.put(buf)

        def encode_stage(item):
            t0 = time.perf_counter()
            if item.get("sse_finish"):
                # stream end under SSE: encrypt the short tail (if any)
                # host-side, close the Poly1305 trailer, and re-chunk
                # ct_tail‖trailer into block-size erasure batches. Runs
                # on this FIFO stage so every prior batch has absorbed.
                with stagetimer.stage("put.encode+digest"):
                    item["rows_multi"] = self._sse_finish_rows(
                        codec, sse, item["tail"], item["sse_off"])
                stage_s[1] += time.perf_counter() - t0
                return item
            with stagetimer.stage("put.encode+digest"), \
                    telemetry.span("pipeline.encode",
                                   blocks=item["data"].shape[0]):
                fut, data = item["fut"], item["data"]
                if sse is not None:
                    item["rows"] = self._sse_encode(codec, data, item,
                                                    fut, sse)
                else:
                    # check: allow(deadline) device dispatch; scheduler close() flushes waiters
                    fused = fut.result() if fut is not None else \
                        codec.encode_and_hash_batch(data, self.bitrot_algo)
                    item["rows"] = self._unpack_fused(codec, data, fused)
            stage_s[1] += time.perf_counter() - t0
            return item

        def write_stage(item):
            t0 = time.perf_counter()
            try:
                with stagetimer.stage("put.shard_write"), \
                        telemetry.span("pipeline.shard_write"):
                    for rows, parity, dd, dp in (
                            item["rows_multi"] if "rows_multi" in item
                            else [item["rows"]]):
                        self._write_shards_batch(rows, parity, dd, dp,
                                                 writers, write_quorum)
            finally:
                recycle(item)
                stage_s[2] += time.perf_counter() - t0

        pipe = None

        def feed(data) -> None:
            """Hand the CURRENT buffer (if any) plus `data` to the
            pipeline, spinning the stage threads up on first use.
            Buffer ownership transfers to the item BEFORE submit — if
            submit raises a pending stage error, on_drop recycles the
            item's buffer and the caller's finally must not recycle it
            again (a double pool.put would hand one bytearray to two
            later streams)."""
            nonlocal batches, buf, pipe, enc_off
            if pipe is None:
                pipe = pl.StagePipeline([encode_stage, write_stage],
                                        depth=pl.DEPTH, name="put-pipe",
                                        on_drop=recycle)
            owned, buf = buf, None
            item = {"buf": owned, "data": data}
            if sse is not None:
                # per-row key/nonce word arrays ride the dispatch; the
                # bucket key carries only their shape, so concurrent
                # encrypted PUTs coalesce into one launch
                kn = sse.batch_params(enc_off, data.shape[0], bs)
                item["sse_kn"], item["sse_off"] = kn, enc_off
                enc_off += data.shape[0] * bs
                fut = (self.scheduler.submit(
                    codec, data, self.bitrot_algo,
                    sse=(kn[0], kn[1], _sse_pkg()))
                    if self.scheduler is not None else None)
            else:
                fut = (self.scheduler.submit(codec, data,
                                             self.bitrot_algo)
                       if self.scheduler is not None else None)
            item["fut"] = fut
            pipe.submit(item)
            batches += 1

        def acquire():
            t0 = time.perf_counter()
            b = pool.get(timeout=pl.POOL_TIMEOUT_S)
            stage_s[0] += time.perf_counter() - t0
            a = np.frombuffer(b, dtype=np.uint8).reshape(cap, k * s_len)
            if k * s_len > bs:
                # pooled reuse: the pad tail must READ as zeros for
                # klauspost-identical shards — enforce it here rather
                # than trusting every writer of this ring forever
                a[:, bs:] = 0
            return b, a

        total = 0
        buf = None
        enc_off = 0       # plaintext stream offset of the next sse batch
        tail_pt = b""     # short last block (plaintext) under sse
        try:
            buf, arr = acquire()
            nb = 0
            while True:
                t0 = time.perf_counter()
                with stagetimer.stage("put.read_stream"):
                    n = _read_full_into(reader, arr[nb][:bs])
                stage_s[0] += time.perf_counter() - t0
                if n == 0:
                    break
                total += n
                if n == bs:
                    nb += 1
                    if nb == cap:
                        feed(arr[:nb].reshape(nb, k, s_len))
                        nb = 0
                        if 0 <= known_size == total:
                            # exact batch multiple: EOF is certain, so
                            # don't block on a probe buffer the stream
                            # will never write into
                            break
                        buf, arr = acquire()
                else:
                    if sse is not None:
                        # short last block under SSE: it joins the tag
                        # trailer in the finish batches — the pending
                        # full rows flush below, then the finish runs
                        # after them in stage FIFO order
                        tail_pt = bytes(arr[nb][:n])
                        break
                    # short last block: its shard length differs —
                    # flush the pending full rows first, then the
                    # short block alone (split copies it out of the
                    # ring; whichever item takes the buffer recycles
                    # it)
                    with stagetimer.stage("put.split"):
                        data = codec.split(arr[nb][:n])[None, ...]
                    if pipe is None:
                        # unknown-length stream that fit one batch:
                        # encode+write inline — no stage threads
                        if nb:
                            self._encode_write(
                                codec, arr[:nb].reshape(nb, k, s_len),
                                writers, write_quorum)
                        self._encode_write(codec, data, writers,
                                           write_quorum)
                    else:
                        if nb:
                            feed(arr[:nb].reshape(nb, k, s_len))
                        feed(data)
                    nb = 0
                    break
            if nb:
                if pipe is None:
                    self._encode_write(codec,
                                       arr[:nb].reshape(nb, k, s_len),
                                       writers, write_quorum,
                                       sse=sse, sse_off=enc_off)
                    enc_off += nb * bs
                else:
                    feed(arr[:nb].reshape(nb, k, s_len))
            if sse is not None:
                if pipe is None:
                    for rows in self._sse_finish_rows(codec, sse,
                                                      tail_pt, enc_off):
                        self._write_shards_batch(*rows, writers,
                                                 write_quorum)
                else:
                    pipe.submit({"sse_finish": True, "tail": tail_pt,
                                 "sse_off": enc_off})
            if pipe is not None:
                pipe.close()    # join; re-raises the first stage error
        except BaseException:
            if pipe is not None:
                pipe.close(abort=True)
            raise
        finally:
            if buf is not None:
                pool.put(buf)
        if pipe is not None:
            wall = time.perf_counter() - t_start
            pl.STATS.record_put(wall, sum(stage_s), batches)
            stagetimer.add_overlap("put.pipeline", wall, sum(stage_s))
        if sse is not None:
            from ..features.crypto import encrypted_size
            return encrypted_size(total)   # ciphertext + tag trailer
        return total

    def _encode_stream_serial(self, reader, codec: Codec, writers,
                              write_quorum: int, sse=None) -> int:
        """The serial PUT hot loop (MINIO_TPU_PIPELINE=off).

        Copy discipline (the fork's zero-copy QAT ingest,
        cmd/erasure-encode.go:102-124, generalized): blocks are read
        straight into a padded (B, k*S) buffer so the shard split is a
        reshape VIEW, the data shards are written from that same buffer,
        and only the parity rows are newly allocated. The old path
        copied every byte 3 extra times (concat, split, stack)."""
        total = 0
        k, s_len = codec.k, codec.shard_size
        bs = self.block_size
        cap = ENCODE_BATCH_BLOCKS
        # zero-initialized: the pad tail (k*S - block_size bytes) must
        # read as zeros for klauspost-identical shard bytes, and full
        # blocks never write into it
        buf = np.zeros((cap, k * s_len), dtype=np.uint8)
        nb = 0
        enc_off = 0
        tail_pt = b""

        def flush_full(n_rows: int) -> None:
            nonlocal enc_off
            if n_rows:
                self._encode_write(codec,
                                   buf[:n_rows].reshape(n_rows, k, s_len),
                                   writers, write_quorum,
                                   sse=sse, sse_off=enc_off)
                enc_off += n_rows * bs

        while True:
            row = buf[nb]
            with stagetimer.stage("put.read_stream"):
                n = _read_full_into(reader, row[:bs])
            if n == 0:
                break
            total += n
            if n == bs:
                nb += 1
                if nb == cap:
                    flush_full(nb)
                    nb = 0
            else:
                if sse is not None:
                    # short last block under SSE joins the tag trailer
                    # in the finish batches (after flush_full below)
                    tail_pt = bytes(row[:n])
                    break
                # short last block: its shard length differs — encode
                # the pending full rows first, then it alone
                flush_full(nb)
                nb = 0
                with stagetimer.stage("put.split"):
                    data = codec.split(row[:n])[None, ...]
                self._encode_write(codec, data, writers, write_quorum)
                break
        flush_full(nb)
        if sse is not None:
            from ..features.crypto import encrypted_size
            for rows in self._sse_finish_rows(codec, sse, tail_pt,
                                              enc_off):
                self._write_shards_batch(*rows, writers, write_quorum)
            return encrypted_size(total)
        return total

    def _unpack_fused(self, codec: Codec, data: np.ndarray, fused
                      ) -> tuple[np.ndarray, np.ndarray, np.ndarray,
                                 np.ndarray]:
        """(data_rows, parity, data_digests, parity_digests) from one
        fused encode+digest result, or the local CPU fallback when the
        batch didn't ride the device (`fused` is None). data rows stay
        views of the caller's staging buffer on the CPU path."""
        if fused is not None:
            full, digests = fused
            return (full[:, :codec.k], full[:, codec.k:],
                    digests[:, :codec.k], digests[:, codec.k:])
        b_ = data.shape[0]
        parity = codec.encode_parity_batch(data)
        dd = bitrot_mod.hash_shards_batch(
            data.reshape(b_ * codec.k, -1), self.bitrot_algo
        ).reshape(b_, codec.k, -1)
        if codec.m:
            dp = bitrot_mod.hash_shards_batch(
                parity.reshape(b_ * codec.m, -1), self.bitrot_algo
            ).reshape(b_, codec.m, -1)
        else:
            dp = np.zeros((b_, 0, dd.shape[-1]), dtype=np.uint8)
        return data, parity, dd, dp

    def _encode_write(self, codec: Codec, data: np.ndarray, writers,
                      write_quorum: int, sse=None, sse_off: int = 0
                      ) -> None:
        """Encode+digest one (B, k, S) batch and fan the framed shard
        writes out — data rows go to the writers as views of `data`.
        With `sse`, the batch rows are PLAINTEXT full blocks starting
        at stream offset `sse_off` and the cipher fuses in (or falls
        back to the in-place CPU cipher)."""
        with stagetimer.stage("put.encode+digest"), \
                telemetry.span("pipeline.encode", blocks=data.shape[0]):
            if sse is not None:
                item = {"sse_kn": sse.batch_params(
                    sse_off, data.shape[0], self.block_size),
                    "sse_off": sse_off}
                fut = (self.scheduler.submit(
                    codec, data, self.bitrot_algo,
                    sse=(*item["sse_kn"], _sse_pkg()))
                    if self.scheduler is not None else None)
                data_rows, parity, dd, dp = self._sse_encode(
                    codec, data, item, fut, sse)
            else:
                # fused device encode+digest when routed there (one
                # program, one round-trip); the cross-request scheduler
                # coalesces concurrent PUT streams into shared
                # dispatches
                if self.scheduler is not None:
                    fused = self.scheduler.encode_and_hash(
                        codec, data, self.bitrot_algo)
                else:
                    fused = codec.encode_and_hash_batch(data,
                                                        self.bitrot_algo)
                data_rows, parity, dd, dp = self._unpack_fused(
                    codec, data, fused)
        with stagetimer.stage("put.shard_write"), \
                telemetry.span("pipeline.shard_write"):
            self._write_shards_batch(data_rows, parity, dd, dp, writers,
                                     write_quorum)

    def _sse_encode(self, codec: Codec, data: np.ndarray, item, fut,
                    sse) -> tuple[np.ndarray, np.ndarray, np.ndarray,
                                  np.ndarray]:
        """Resolve one SSE batch: fused device cipher+RS+digest result,
        or — on decline OR dispatch error — the in-place CPU cipher
        followed by the local encode path (byte-identical either way).
        Always absorbs the ciphertext into the Poly1305 tag trailer in
        stream order (the caller runs batches FIFO), so the tags are
        computed over the bytes actually committed — device output is
        re-authenticated host-side, never laundered."""
        bs = self.block_size
        b_ = data.shape[0]
        fused = None
        try:
            if fut is not None:
                # check: allow(deadline) device dispatch; scheduler close() flushes waiters
                fused = fut.result()
            else:
                keys, nonces = item["sse_kn"]
                fused = codec.encrypt_encode_and_hash_batch(
                    data, keys, nonces, _sse_pkg(), self.bitrot_algo)
        except Exception:
            fused = None    # dispatch error → CPU cipher fallback
        if fused is None:
            flat = data.reshape(b_, -1)
            sse.cpu_encrypt_rows(flat[:, :bs], item["sse_off"])
        rows = self._unpack_fused(codec, data, fused)
        ct = rows[0]        # (B, k, S): device output or encrypted buf
        for i in range(b_):
            sse.absorb(ct[i].reshape(-1)[:bs])
        return rows

    def _sse_finish_rows(self, codec: Codec, sse, tail_pt: bytes,
                         off: int) -> list:
        """Close an SSE stream: encrypt the short plaintext tail (CPU —
        partial blocks never ride the device), absorb it, close the tag
        trailer, and chunk ct_tail‖trailer into block-size erasure
        batches ready for _write_shards_batch. The trailer can exceed
        one block for huge objects, hence a list."""
        if tail_pt:
            arr = np.frombuffer(bytearray(tail_pt), dtype=np.uint8)
            sse.cpu_encrypt_tail(arr, off)
            sse.absorb(arr)
            stream = arr.tobytes() + sse.trailer()
        else:
            stream = sse.trailer()
        out = []
        bs = self.block_size
        for at in range(0, len(stream), bs):
            data = codec.split(stream[at:at + bs])[None, ...]
            out.append(self._unpack_fused(codec, data, None))
        return out

    def _write_shards_batch(self, data: np.ndarray, parity: np.ndarray,
                            dd: np.ndarray, dp: np.ndarray,
                            writers, write_quorum: int) -> None:
        """parallelWriter.Write, batched: writer i gets ALL B of its
        [digest‖block] frames in one call (cmd/erasure-encode.go:38-72's
        per-disk goroutine — but fanned out once per encode batch, not
        once per block: B× fewer pool tasks, and the frames are handed
        over as memoryviews of the encode output, copy-free until the
        writer's own buffer). Data and parity arrive as separate arrays
        so the data rows stay views of the read buffer."""
        B, k = data.shape[0], data.shape[1]

        def write(i: int, w) -> None:
            rows, digs, j = (data, dd, i) if i < k else \
                (parity, dp, i - k)
            t0 = time.perf_counter()
            with telemetry.span("disk.shard_write", disk=i, blocks=B):
                for bi in range(B):
                    w.write_with_digest(rows[bi, j].data,
                                        digs[bi, j].data)
            healthtrack.observe_disk(w.disk, "write",
                                     time.perf_counter() - t0)

        # quorum-ack lane: once write-quorum writers are durable, a
        # laggard past the stall grace is dropped from the fan-out
        # (and from every later batch via writers[i] = None below) —
        # its missing shard heals through MRF instead of setting p99
        _, errs = meta.for_each_disk_quorum(
            list(writers),  # type: ignore[arg-type]
            write, write_quorum, stall_s=healthtrack.write_stall_s(),
            stage="shard_write")
        for i, e in enumerate(errs):
            if e is not None:
                writers[i] = None
        live = sum(1 for w in writers if w is not None)
        if live < write_quorum:
            raise api_errors.InsufficientWriteQuorum(
                f"{live} live writers < quorum {write_quorum}")

    def _commit(self, shuffled, writers, tmp_id: str, fi: FileInfo,
                bucket: str, object_name: str, write_quorum: int) -> int:
        """2-phase commit; returns how many drives MISSED the commit
        (offline slot, dropped writer, or failed rename) — the MRF
        degraded-write signal."""
        def close_writer(i, d):
            w = writers[i]
            if w is None:
                raise serr.DiskNotFound(f"writer {i}")
            w.close()  # flushes remaining frames (empty file for 0-byte)

        # the whole commit window rides the quorum-ack lane: a drive
        # stalling at close/meta/rename must not hold the client ack
        # once quorum is durable — it is counted into `lost` below and
        # the object converges back through MRF
        stall = healthtrack.write_stall_s()
        with stagetimer.stage("put.commit.close_writers"):
            _, errs = meta.for_each_disk_quorum(shuffled, close_writer,
                                                write_quorum,
                                                stall_s=stall,
                                                stage="close")
        for i, e in enumerate(errs):
            if e is not None:
                writers[i] = None

        metas = [fi.light_copy() for _ in range(len(shuffled))]
        if not self.bitrot_algo.streaming:
            # whole-file digests are per-drive (each shard differs)
            for i, w in enumerate(writers):
                if w is not None:
                    for c in metas[i].erasure.checksums:
                        c.hash = w.digest()
        disks_for_meta = [d if writers[i] is not None else None
                          for i, d in enumerate(shuffled)]
        # shard fan-out is durable (in tmp), no metadata exists yet —
        # a crash here must leave the previous version untouched and
        # only tmp garbage for fsck to reclaim
        crashpoint.hit("put.shards.before_meta")
        with stagetimer.stage("put.commit.write_meta"):
            meta.write_unique_file_info(disks_for_meta,
                                        MINIO_META_TMP_BUCKET,
                                        tmp_id, metas, write_quorum,
                                        stall_s=stall)
        # fully staged, uncommitted: the rename fan-out is the point
        # of no return
        crashpoint.hit("put.meta.before_rename")

        def rename(i, d):
            # one hit per drive: arm :<nth> to die with n-1 drives
            # committed (torn below/at write quorum)
            crashpoint.hit("put.rename.partial", disk=i)
            d.rename_data(MINIO_META_TMP_BUCKET, tmp_id, fi.data_dir,
                          bucket, object_name)

        def renamed_late(_i: int) -> None:
            # an abandoned rename that eventually LANDS may have laid
            # an OLDER version over a commit that happened after this
            # PUT acked — re-queue the MRF check now that it settled,
            # so the drive is healed against current quorum state
            self._notify_degraded(bucket, object_name, fi.version_id)

        with stagetimer.stage("put.commit.rename"):
            _, errs = meta.for_each_disk_quorum(disks_for_meta, rename,
                                                write_quorum,
                                                stall_s=stall,
                                                stage="rename",
                                                on_settle=renamed_late)
        err = meta.reduce_write_quorum_errs(
            errs, meta.OBJECT_OP_IGNORED_ERRS, write_quorum)
        if err is not None:
            raise api_errors.to_object_err(err, bucket, object_name)
        return sum(1 for i in range(len(shuffled))
                   if disks_for_meta[i] is None or errs[i] is not None)

    def _cleanup_tmp(self, disks, tmp_id: str) -> None:
        def rm(i, d):
            try:
                d.delete_file(MINIO_META_TMP_BUCKET, tmp_id, recursive=True)
            except serr.StorageError:
                pass
        meta.for_each_disk(disks, rm)

    # ------------------------------------------------------------------
    # GET (cmd/erasure-object.go:124-323 + cmd/erasure-decode.go)
    # ------------------------------------------------------------------

    def _object_file_info(self, bucket: str, object_name: str,
                          version_id: str = ""
                          ) -> tuple[FileInfo, list[Optional[FileInfo]],
                                     list[Optional[StorageAPI]]]:
        metas, errs = meta.read_all_file_info(self.disks, bucket,
                                              object_name, version_id)
        try:
            read_quorum, _ = meta.object_quorum_from_meta(
                metas, errs, self.parity_shards)
        except (api_errors.InsufficientReadQuorum, serr.StorageError):
            err = meta.reduce_read_quorum_errs(
                errs, meta.OBJECT_OP_IGNORED_ERRS,
                len(self.disks) - self.parity_shards)
            raise api_errors.to_object_err(
                err or api_errors.InsufficientReadQuorum(),
                bucket, object_name) from None
        err = meta.reduce_read_quorum_errs(errs, meta.OBJECT_OP_IGNORED_ERRS,
                                           read_quorum)
        if err is not None:
            raise api_errors.to_object_err(err, bucket, object_name)
        fi = meta.pick_valid_file_info(metas, read_quorum)
        online, _ = meta.list_online_disks(self.disks, metas, errs)
        return fi, metas, online

    def has_object_versions(self, bucket: str, object_name: str) -> bool:
        """True when ANY version (including a delete marker) exists —
        the zone-affinity probe (reference getZoneIdx's delete-marker
        cases, cmd/erasure-server-sets.go:195-220)."""
        try:
            self._object_file_info(bucket, object_name)
            return True
        except api_errors.ObjectApiError:
            return False

    def latest_file_info(self, bucket: str, object_name: str) -> FileInfo:
        """Latest version's FileInfo INCLUDING delete markers — the
        multi-pool newest-wins read probe (get_object_info hides
        markers behind ObjectNotFound, which would let an older data
        copy in another pool shadow a newer marker here)."""
        fi, _, _ = self._object_file_info(bucket, object_name)
        return fi

    def update_object_metadata(self, bucket: str, object_name: str,
                               metadata: dict, version_id: str = ""
                               ) -> ObjectInfo:
        """Metadata-only update of an existing version in place (tags,
        user metadata) — no data rewrite, no new version (reference
        updates xl.meta via WriteMetadata on the same version id)."""
        with self.ns.new_lock(f"{bucket}/{object_name}").write_locked():
            fi, metas, online = self._object_file_info(
                bucket, object_name, version_id)
            if fi.deleted:
                raise api_errors.MethodNotAllowed(
                    f"{bucket}/{object_name} is a delete marker")
            new_meta = dict(metadata)
            new_meta["etag"] = fi.metadata.get("etag", "")

            def upd(i, d):
                m = metas[i]
                if m is None:
                    raise serr.FileNotFound(object_name)
                m.metadata = dict(new_meta)
                d.write_metadata(bucket, object_name, m)

            _, errs = meta.for_each_disk(online, upd)
            _, write_quorum = meta.object_quorum_from_meta(
                metas, [None] * len(metas), self.parity_shards)
            err = meta.reduce_write_quorum_errs(
                errs, meta.OBJECT_OP_IGNORED_ERRS, write_quorum)
            if err is not None:
                raise api_errors.to_object_err(err, bucket, object_name)
            fi.metadata = new_meta
        if any(e is not None for e in errs):
            self._notify_degraded(bucket, object_name, version_id)
        self._notify_namespace(bucket, object_name)
        return fi.to_object_info(bucket, object_name)

    def transition_object(self, bucket: str, object_name: str,
                          version_id: str = "", tier: str = "",
                          remote_object: str = "",
                          remote_version: str = "",
                          expect_etag: str = "",
                          expect_mod_time: Optional[float] = None
                          ) -> ObjectInfo:
        """Rewrite one version's xl.meta into a zero-data stub carrying
        the tier name + remote key, then free the local shards — the
        reference's TransitionObject commit (cmd/erasure-object.go):
        the caller has ALREADY verified the remote copy; local data is
        deleted only after the stub landed at write quorum, so a crash
        anywhere earlier leaves the object fully readable locally.

        Also the restore-expiry reclaim path: re-stubbing a restored
        copy passes the SAME tier/remote key back in (no re-upload) and
        this rewrite drops the x-amz-restore state.

        expect_etag/expect_mod_time pin the version's IDENTITY inside
        the write lock: for unversioned objects nothing else ties the
        uploaded remote bytes to the version being stubbed — a client
        overwrite racing the worker's remote upload must abort the
        commit (PreConditionFailed), not stub the NEW data over the OLD
        remote copy."""
        with self.ns.new_lock(f"{bucket}/{object_name}").write_locked():
            fi, metas, online = self._object_file_info(
                bucket, object_name, version_id)
            if fi.deleted:
                raise api_errors.MethodNotAllowed(
                    f"{bucket}/{object_name} is a delete marker")
            if (expect_etag
                    and fi.metadata.get("etag", "") != expect_etag) or \
                    (expect_mod_time is not None
                     and fi.mod_time != expect_mod_time):
                raise api_errors.PreConditionFailed(
                    f"{bucket}/{object_name} changed since the remote "
                    "copy was written")
            data_dir = fi.data_dir
            new_meta = dict(fi.metadata)
            new_meta[TRANSITION_STATUS_KEY] = TRANSITION_COMPLETE
            new_meta[TRANSITION_TIER_KEY] = tier
            new_meta[TRANSITIONED_OBJECT_KEY] = remote_object
            if remote_version:
                new_meta[TRANSITIONED_VERSION_KEY] = remote_version
            else:
                new_meta.pop(TRANSITIONED_VERSION_KEY, None)
            new_meta.pop(RESTORE_KEY, None)
            new_meta.pop(RESTORE_EXPIRY_KEY, None)

            def upd(i, d):
                m = metas[i]
                if m is None:
                    raise serr.FileNotFound(object_name)
                m.metadata = dict(new_meta)
                m.data_dir = ""        # zero-data stub
                d.write_metadata(bucket, object_name, m)

            _, errs = meta.for_each_disk(online, upd)
            _, write_quorum = meta.object_quorum_from_meta(
                metas, [None] * len(metas), self.parity_shards)
            err = meta.reduce_write_quorum_errs(
                errs, meta.OBJECT_OP_IGNORED_ERRS, write_quorum)
            if err is not None:
                raise api_errors.to_object_err(err, bucket, object_name)
            # the stub is durable at quorum: NOW the local shards go
            # (every drive, not just online — stale copies must not
            # resurrect the data dir)
            if data_dir:
                def rm(i, d):
                    try:
                        d.delete_file(bucket,
                                      f"{object_name}/{data_dir}",
                                      recursive=True)
                    except serr.FileNotFound:
                        pass

                meta.for_each_disk(self.disks, rm)
            fi.metadata = new_meta
            fi.data_dir = ""
        if any(e is not None for e in errs):
            self._notify_degraded(bucket, object_name, fi.version_id)
        self._notify_namespace(bucket, object_name)
        return fi.to_object_info(bucket, object_name)

    def put_stub_version(self, bucket: str, object_name: str,
                         info: ObjectInfo,
                         if_none_newer: bool = False) -> ObjectInfo:
        """Write a transitioned ZERO-DATA stub version from its
        API-facing ObjectInfo — the rebalance copy path for tiered
        objects (there are no local shards to move; only the xl.meta
        pointer travels). Identity (version id, mod time, etag, parts,
        metadata incl. the tier/remote-key pointers) is preserved; the
        erasure geometry is re-minted for THIS set, since the stored
        geometry gates read quorum and the source pool's k may not even
        fit this pool's drive count."""
        md = dict(info.user_defined or {})
        if not (md.get(TRANSITION_STATUS_KEY) == TRANSITION_COMPLETE):
            raise api_errors.InvalidObjectState(
                f"{bucket}/{object_name} is not a transitioned stub")
        k, m, _, write_quorum = self._default_quorums()
        fi = new_file_info(f"{bucket}/{object_name}", k, m)
        fi.erasure.block_size = self.block_size
        fi.volume, fi.name = bucket, object_name
        fi.data_dir = ""
        fi.version_id = info.version_id or ""
        fi.size = info.size
        fi.mod_time = info.mod_time
        md["etag"] = info.etag
        if info.content_type:
            md["content-type"] = info.content_type
        if info.content_encoding:
            md["content-encoding"] = info.content_encoding
        fi.metadata = md
        for p in (info.parts or []):
            fi.add_object_part(p.number, p.etag, p.size, p.actual_size)
        if not fi.parts:
            fi.add_object_part(1, info.etag, info.size, info.size)
        with self.ns.new_lock(f"{bucket}/{object_name}").write_locked():
            if if_none_newer:
                # the replication apply's unversioned conflict gate —
                # an older stub replica must not shadow a newer write
                self._check_none_newer(bucket, object_name, fi)
            metas = [fi.light_copy() for _ in range(len(self.disks))]
            online = meta.write_unique_file_info(
                self.disks, bucket, object_name, metas, write_quorum)
        if any(d is None for d in online):
            # quorum met but some drive missed the stub: regain full
            # redundancy through MRF like every other write verb
            self._notify_degraded(bucket, object_name, fi.version_id)
        self._notify_namespace(bucket, object_name)
        return fi.to_object_info(bucket, object_name)

    def get_object_info(self, bucket: str, object_name: str,
                        opts: Optional[GetOptions] = None) -> ObjectInfo:
        opts = opts or GetOptions()
        with self.ns.new_lock(f"{bucket}/{object_name}").read_locked():
            fi, _, _ = self._object_file_info(bucket, object_name,
                                              opts.version_id)
        if fi.deleted:
            if opts.version_id:
                return fi.to_object_info(bucket, object_name)
            raise api_errors.ObjectNotFound(bucket, object_name)
        return fi.to_object_info(bucket, object_name)

    def get_object(self, bucket: str, object_name: str,
                   offset: int = 0, length: int = -1,
                   opts: Optional[GetOptions] = None
                   ) -> tuple[ObjectInfo, Iterator[bytes]]:
        """Returns (info, chunk iterator). Reads are verified (streaming
        bitrot) and reconstructed on the fly when shards are missing."""
        opts = opts or GetOptions()
        lock = self.ns.new_lock(f"{bucket}/{object_name}")
        if not lock.get_rlock(30.0):
            raise api_errors.ObjectApiError("read lock timeout")
        try:
            fi, metas, online = self._object_file_info(
                bucket, object_name, opts.version_id)
            if fi.deleted:
                # latest is a delete marker: plain GET -> NotFound;
                # explicit version GET -> MethodNotAllowed (S3 semantics,
                # matching get_object_info)
                if opts.version_id:
                    raise api_errors.MethodNotAllowed(
                        f"{bucket}/{object_name} is a delete marker")
                raise api_errors.ObjectNotFound(bucket, object_name)
            if is_transitioned(fi.metadata) \
                    and not is_restored(fi.metadata):
                # the data lives in a remote tier and no restored local
                # copy exists: S3 InvalidObjectState until RestoreObject
                raise api_errors.InvalidObjectState(
                    f"{bucket}/{object_name} is archived in tier "
                    f"{fi.metadata.get(TRANSITION_TIER_KEY, '?')!r}; "
                    "restore it first")
            oi = fi.to_object_info(bucket, object_name)
            if length < 0:
                length = fi.size - offset
            if offset < 0 or length < 0 or offset + length > fi.size:
                if not (fi.size == 0 and offset == 0 and length <= 0):
                    raise api_errors.InvalidRange(offset, length, fi.size)
        except Exception:
            lock.unlock()
            raise

        # a drive that is present but lacks the latest copy needs heal
        # even when no shard read will fail (its shard may be parity)
        flagged = False
        if self.on_degraded_read is not None and any(
                online[i] is None and self.disks[i] is not None
                for i in range(len(online))):
            flagged = True
            try:
                self.on_degraded_read(bucket, object_name)
            except Exception:  # noqa: BLE001 — heal queueing is best-effort
                pass

        # idempotent release: the generator's finally AND the wrapper's
        # close() both funnel here — whichever runs first wins
        released = [False]

        def release() -> None:
            if not released[0]:
                released[0] = True
                lock.unlock()

        def gen() -> Iterator[bytes]:
            try:
                if fi.size == 0 or length == 0:
                    return
                # traced_iter (NOT a plain span): the span must only be
                # current while the read code runs, never across a
                # yield into the consumer — see telemetry.traced_iter
                yield from telemetry.traced_iter(
                    "engine.get_object",
                    self._read_object_stream(
                        bucket, object_name, fi, metas, online, offset,
                        length, suppress_heal_flag=flagged),
                    bucket=bucket, object=object_name, length=length)
            finally:
                release()

        return oi, _UnlockOnClose(gen(), release)

    def _read_object_stream(self, bucket, object_name, fi: FileInfo,
                            metas, online, offset: int, length: int,
                            suppress_heal_flag: bool = False
                            ) -> Iterator[bytes]:
        """Per-part block loop (getObjectWithFileInfo,
        cmd/erasure-object.go:217-323), with CROSS-PART lookahead: the
        one-group prefetcher no longer stops at a part boundary — while
        part N's last group runs fused verify+decode, part N+1's FIRST
        group is already reading on the prefetch pool (its readers are
        independent streams, so no io_lock is shared across parts)."""
        from ..parallel import pipeline as pl
        # every erasure read stream counts here — the hot-object read
        # cache's "hit serves WITHOUT erasure decode" proof is a flat
        # delta on this counter across a cached GET
        _get_streams_counter().inc()
        shuffled_disks = meta.shuffle_disks(online, fi.erasure.distribution)
        shuffled_meta = meta.shuffle_parts_metadata(metas,
                                                    fi.erasure.distribution)
        k = fi.erasure.data_blocks
        codec = self.codec(k, fi.erasure.parity_blocks)

        part_idx, part_off = fi.object_to_part_offset(offset)
        remaining = length
        plans: list[_PartReadPlan] = []
        for pi in range(part_idx, len(fi.parts)):
            if remaining <= 0:
                break
            part = fi.parts[pi]
            part_read_off = part_off if pi == part_idx else 0
            part_read_len = min(remaining, part.size - part_read_off)
            if part_read_len > 0:
                plans.append(_PartReadPlan(
                    self, bucket, object_name, fi, shuffled_disks,
                    shuffled_meta, codec, part, part_read_off,
                    part_read_len, suppress_heal_flag))
            remaining -= part_read_len
        try:
            for i, plan in enumerate(plans):
                nxt = plans[i + 1] if pl.ENABLED \
                    and i + 1 < len(plans) else None
                yield from plan.stream(next_plan=nxt)
        finally:
            for plan in plans:
                plan.close()

    def _read_part(self, bucket, object_name, fi: FileInfo, disks, smeta,
                   codec: Codec, part, offset: int, length: int,
                   suppress_heal_flag: bool = False) -> Iterator[bytes]:
        """Single-part convenience (kept for callers outside the main
        GET loop): one plan, no cross-part prefetch."""
        plan = _PartReadPlan(self, bucket, object_name, fi, disks, smeta,
                             codec, part, offset, length,
                             suppress_heal_flag)
        try:
            yield from plan.stream()
        finally:
            plan.close()

    def _read_block_shards(self, readers, codec: Codec, block_num: int,
                           shard_size: int, shard_len: int, k: int, n: int
                           ) -> tuple[list, bool]:
        """Single-block convenience (healing path): raw read +
        reconstruct-in-place."""
        shards, _digests, had_errors = self._read_block_shards_raw(
            readers, block_num, shard_size, shard_len, k, n)
        if any(shards[i] is None for i in range(k)):
            shards = codec.reconstruct(shards, data_only=True)
        return shards, had_errors

    def _verify_and_reconstruct_group(self, codec: Codec, group, k: int,
                                      n: int, readers, shard_size: int,
                                      algo: bitrot_mod.BitrotAlgorithm,
                                      io_lock: Optional[threading.Lock]
                                      = None,
                                      reader_gen: Optional[tuple]
                                      = None,
                                      benign_missing: frozenset
                                      = frozenset()) -> bool:
        """Verify deferred frame digests AND reconstruct the degraded
        blocks of a read group. Degraded blocks sharing one
        (present-mask, shard-length) pattern go through a single fused
        verify+decode device dispatch (models/pipeline.get_step); shards
        the fused program didn't cover batch-verify in one host call. A
        digest mismatch (rare) drops the corrupt shard's reader and
        re-reads the affected block with inline verification. Group
        entries are [b, off, blen, shard_len, shards, digests] lists,
        mutated in place. Returns True when any block needed
        reconstruction or had bitrot."""
        from ..ops import rs_matrix
        heal = False
        corrupt: set[int] = set()
        if io_lock is None:
            io_lock = threading.Lock()   # uncontended when no prefetch

        def drop_reader(u: int) -> None:
            """Condemn the reader a corrupt frame came from — unless a
            concurrent lookahead rebuilt the readers list since this
            group was read, in which case index u names a FRESH reader
            that never served the corrupt frame."""
            with io_lock:
                if reader_gen is None or \
                        reader_gen[0][0] == reader_gen[1]:
                    readers[u] = None

        # 1) degraded buckets: fused verify+decode on device, or
        #    missing-rows-only matmul on host
        buckets: dict[tuple[int, int], list[int]] = {}
        for gi, entry in enumerate(group):
            shards = entry[4]
            if all(shards[i] is not None for i in range(k)):
                continue
            mask = sum(1 << i for i in range(n)
                       if shards[i] is not None)
            buckets.setdefault((mask, entry[3]), []).append(gi)
        # submit EVERY bucket's fused dispatch before resolving any:
        # each bucket's grace window then overlaps CONCURRENT requests'
        # same-pattern buckets (same former key -> one fused launch)
        # instead of opening only after the previous bucket resolved
        staged: list[tuple] = []
        for (mask, shard_len), idxs in buckets.items():
            # a reconstruct forced by the READ PLAN (quarantine skip /
            # latency-hedge loser) is not damage: the shards are on
            # disk, nothing needs healing — only a miss the plan can't
            # account for flags the degraded-read heal
            if not {i for i in range(k)
                    if not (mask >> i) & 1} <= benign_missing:
                heal = True
            _dm, used, _missing = rs_matrix.missing_data_matrix(
                k, codec.m, mask)
            stacked = np.stack([
                np.stack([group[gi][4][u] for u in used])
                for gi in idxs])                       # (G', k, S)
            # fuse hashing only when digests were actually deferred;
            # inline-verified shards need just the decode matmul
            want_fused = any(group[gi][5][u] is not None
                             for gi in idxs for u in used)
            fut = None
            if want_fused and self.scheduler is not None:
                fut = self.scheduler.submit_decode(
                    codec, stacked, mask, shard_len, algo)
            staged.append((mask, shard_len, idxs, used, stacked,
                           want_fused, fut))
        for mask, shard_len, idxs, used, stacked, want_fused, fut \
                in staged:
            if fut is not None:
                try:
                    # check: allow(deadline) device dispatch; scheduler close() flushes waiters
                    fused = fut.result()
                except Exception:  # noqa: BLE001 — a shared-dispatch
                    # failure must not kill a GET the host can still
                    # serve: fall back to the local decode + step-2
                    # host verification of the deferred digests
                    fused = None
            elif want_fused:
                fused = codec.verify_and_decode_batch(
                    stacked, mask, shard_len, algo)
            else:
                fused = None
            if fused is not None:
                out, missing_idx, sdig = fused
                for row, gi in enumerate(idxs):
                    shards, digests = group[gi][4], group[gi][5]
                    bad = False
                    for col, u in enumerate(used):
                        exp = digests[u]
                        if exp is None:
                            continue
                        if sdig[row, col].tobytes() != exp:
                            shards[u] = None
                            drop_reader(u)
                            bad = True
                        else:
                            digests[u] = None  # verified on device
                    if bad:
                        corrupt.add(gi)
                    else:
                        for r_i, mi in enumerate(missing_idx):
                            shards[mi] = out[row][r_i]
            else:
                out, idxs_rows = codec.recover_stacked(
                    stacked, mask, set(range(k)))
                for row, gi in enumerate(idxs):
                    shards = group[gi][4]
                    for r_i, mi in enumerate(idxs_rows):
                        shards[mi] = out[row][r_i]

        # 2) batch-verify every shard the fused program didn't cover
        #    (healthy blocks, hedged extras, CPU-routed buckets)
        pend: dict[int, list[tuple[int, int]]] = {}
        for gi, entry in enumerate(group):
            if gi in corrupt:
                continue
            shards, digests = entry[4], entry[5]
            for i in range(n):
                if digests[i] is not None and shards[i] is not None:
                    pend.setdefault(len(shards[i]), []).append((gi, i))
        for _sl, items in pend.items():
            stacked = np.stack([group[gi][4][i] for gi, i in items])
            got = bitrot_mod.hash_shards_batch(stacked, algo)
            for row, (gi, i) in enumerate(items):
                if got[row].tobytes() != group[gi][5][i]:
                    group[gi][4][i] = None
                    drop_reader(i)
                    corrupt.add(gi)
                else:
                    group[gi][5][i] = None

        # 3) corrupt blocks (bitrot found after deferral): re-read with
        #    inline verification and host reconstruct — the corrupt
        #    reader is dead, so hedged extras replace it
        for gi in sorted(corrupt):
            heal = True
            b, _off, _blen, shard_len, _shards, _dg = group[gi]
            with io_lock:   # a GET lookahead may hold the readers
                new_shards, _digests, _he = self._read_block_shards_raw(
                    readers, b, shard_size, shard_len, k, n)
            if any(new_shards[i] is None for i in range(k)):
                new_shards = codec.reconstruct(new_shards, data_only=True)
            group[gi][4] = new_shards
            group[gi][5] = [None] * n
        return heal

    def _read_group_shards_raw(self, readers, blocks: list,
                               shard_size: int, shard_lens: list,
                               k: int, n: int,
                               collect_digests: bool = False,
                               avoid: frozenset = frozenset(),
                               benign_sink: Optional[set] = None) -> list:
        """Group form of _read_block_shards_raw: ONE pool task per
        reader streams every block of the group sequentially (the
        frames are adjacent on disk), instead of a k-way fan-out per
        block — GET_BATCH_BLOCKS× fewer pool tasks, and each shard
        file is read in order. Returns [(shards, digests, had_errors)]
        per block.

        This is THE hedged-read state machine (the "Tail at Scale"
        fix): k primaries launch, and a spare shard read races any
        primary that either FAILS (error hedge, the original behavior)
        or outlives the adaptive latency deadline from the health
        tracker (healthy p95 × K, clamped) — a drive doing 500 ms
        I/Os no longer holds the whole GET. First k wins; losers are
        condemned (their stateful streams must never serve a later
        group) and closed when their abandoned read settles.

        `avoid` holds reader indices the plan deprioritizes (slow-drive
        quarantine): they sort behind every healthy candidate and are
        touched only when nothing else can reach k. `benign_sink`
        collects indices whose shards are missing for PLAN reasons
        (avoided, or hedge-raced on latency) rather than damage — the
        verify step must not flag a heal for those."""
        from concurrent.futures import FIRST_COMPLETED
        from concurrent.futures import wait as _fwait
        nb = len(blocks)
        per_reader: list = [None] * n          # i -> [(data, dg)]*nb
        had_errors = False
        errored: set = set()

        def read_one(i: int, r) -> list:
            out = []
            t0 = time.perf_counter()
            with telemetry.span("disk.shard_read", disk=i, blocks=nb):
                for b, sl in zip(blocks, shard_lens):
                    off = b * shard_size
                    if collect_digests and isinstance(
                            r, bitrot_io.StreamingBitrotReader):
                        frames = r.read_frames(off, sl)
                        out.append((frames[0][1] if frames else b"",
                                    frames[0][0] if frames else None))
                    else:
                        out.append((r.read_at(off, sl), None))
            healthtrack.observe_disk(r.disk, "read",
                                     time.perf_counter() - t0)
            return out

        # candidate order: data rows first (their shards join without
        # a decode), parity next, avoided (suspect/probation) drives
        # last — the capacity-permitting rule by construction: they
        # re-enter only when nothing healthier can reach k
        candidates = [i for i in range(n) if readers[i] is not None]
        candidates.sort(key=lambda i: (i in avoid, 0 if i < k else 1, i))
        spares = candidates[k:]
        inflight: dict = {}

        def launch(i: int) -> None:
            inflight[meta.submit_disk_task(read_one, i, readers[i])] = i

        for i in candidates[:k]:
            launch(i)
        hedge_s = healthtrack.read_hedge_s()
        deadline = None if hedge_s is None \
            else time.monotonic() + hedge_s

        while inflight:
            got = sum(1 for p in per_reader if p is not None)
            if got >= k:
                break
            timeout = None
            if deadline is not None and spares:
                timeout = max(deadline - time.monotonic(), 0.0)
            done, _ = _fwait(set(inflight), timeout=timeout,
                             return_when=FIRST_COMPLETED)
            if not done:
                # latency hedge: every still-missing slot gets a spare
                # racing it; the deadline re-arms so a second level of
                # stalls hedges again (spares permitting)
                need = k - sum(1 for p in per_reader if p is not None)
                fresh, spares = spares[:need], spares[need:]
                for i in fresh:
                    launch(i)
                    healthtrack.note_hedge("latency")
                deadline = time.monotonic() + (hedge_s or 0.0)
                continue
            for f in done:
                i = inflight.pop(f)
                try:
                    per_reader[i] = f.result(timeout=0)
                except Exception:  # noqa: BLE001 — reader condemned
                    readers[i] = None
                    errored.add(i)
                    had_errors = True
                    if spares:
                        j = spares.pop(0)
                        launch(j)
                        healthtrack.note_hedge("error")

        got = sum(1 for p in per_reader if p is not None)
        if got >= k and inflight:
            # first-k wins: condemn the losers so no later group reads
            # their (stateful) streams, and close each one when its
            # abandoned task settles on the pool
            for f, i in inflight.items():
                loser = readers[i]
                readers[i] = None

                def _close(_f, r=loser):
                    try:
                        r.close()
                    except Exception:  # noqa: BLE001 — abandoned
                        pass
                f.add_done_callback(_close)
        if got < k:
            raise api_errors.InsufficientReadQuorum(
                f"{got} readable shards < k={k}")
        # shards missing because the PLAN skipped or out-raced their
        # reader (not because the reader failed) are benign: decode
        # reconstructs them, but nothing on disk needs healing. The
        # caller may PRE-SEED benign_sink with prior groups' benign
        # misses (a latency-condemned reader stays out for the whole
        # part) — those carry forward into this group's verdict too.
        benign = {i for i in candidates
                  if per_reader[i] is None and i not in errored}
        if benign_sink is not None:
            benign_sink.update(benign)
            # a reader that REALLY errored this group loses any benign
            # standing it carried in (avoided earlier, then pressed
            # into service and failed): that miss is damage
            benign_sink.difference_update(errored)
            benign = set(benign_sink)
        missing_data = {i for i in range(k) if per_reader[i] is None}
        if missing_data and not missing_data <= benign:
            had_errors = True

        out = []
        for bi in range(nb):
            shards: list = [None] * n
            digests: list = [None] * n
            for i in range(n):
                if per_reader[i] is not None:
                    shards[i] = np.frombuffer(per_reader[i][bi][0],
                                              dtype=np.uint8)
                    digests[i] = per_reader[i][bi][1]
            out.append((shards, digests, had_errors))
        return out

    def _read_block_shards_raw(self, readers, block_num: int,
                               shard_size: int, shard_len: int, k: int,
                               n: int, collect_digests: bool = False,
                               avoid: frozenset = frozenset(),
                               benign_sink: Optional[set] = None
                               ) -> tuple[list, list, bool]:
        """k-of-n shard reads with hedged extras on failure OR stall
        (parallelReader, cmd/erasure-decode.go:102-184). Returns
        (shards, expected_digests, had_errors): raw shards (missing
        entries None — at least k present) without reconstructing.

        With collect_digests, streaming readers skip per-frame host
        verification and return each frame's stored digest instead
        (digests[i] is None when the shard was verified at read time) —
        the deferred-verify feed for the fused device program.

        One hedged-read state machine: this is the single-block form of
        _read_group_shards_raw, so the heal/rebalance readers that call
        it ride the same adaptive hedging the GET plan does."""
        return self._read_group_shards_raw(
            readers, [block_num], shard_size, [shard_len], k, n,
            collect_digests=collect_digests, avoid=avoid,
            benign_sink=benign_sink)[0]

    # ------------------------------------------------------------------
    # DELETE (cmd/erasure-object.go:727-820)
    # ------------------------------------------------------------------

    def delete_object(self, bucket: str, object_name: str,
                      version_id: str = "", versioned: bool = False
                      ) -> ObjectInfo:
        k, m, _, write_quorum = self._default_quorums()
        with self.ns.new_lock(f"{bucket}/{object_name}").write_locked():
            if versioned and not version_id:
                # versioned delete without a version: write a delete marker
                fi = FileInfo(volume=bucket, name=object_name,
                              version_id=str(_uuid.uuid4()), deleted=True,
                              mod_time=now())
                _, errs = meta.for_each_disk(
                    self.disks,
                    lambda i, d: d.write_metadata(bucket, object_name, fi))
                err = meta.reduce_write_quorum_errs(
                    errs, meta.OBJECT_OP_IGNORED_ERRS, write_quorum)
                if err is not None:
                    raise api_errors.to_object_err(err, bucket, object_name)
                oi = fi.to_object_info(bucket, object_name)
                self._flag_degraded_delete(bucket, object_name,
                                           fi.version_id, errs)
                self._notify_namespace(bucket, object_name)
                return oi

            fi = FileInfo(volume=bucket, name=object_name,
                          version_id=version_id)

            def rm(i, d):
                d.delete_version(bucket, object_name, fi)

            _, errs = meta.for_each_disk(self.disks, rm)
            # not-found is counted (not ignored) so a missing object maps
            # to ObjectNotFound rather than a quorum failure
            err = meta.reduce_write_quorum_errs(
                errs, meta.OBJECT_OP_IGNORED_ERRS, write_quorum)
            if err is not None:
                raise api_errors.to_object_err(err, bucket, object_name)
        self._flag_degraded_delete(bucket, object_name, version_id, errs)
        self._notify_namespace(bucket, object_name)
        return ObjectInfo(bucket=bucket, name=object_name,
                          version_id=version_id)

    def put_delete_marker(self, bucket: str, object_name: str,
                          version_id: str = "",
                          mod_time: Optional[float] = None,
                          metadata: Optional[dict] = None) -> ObjectInfo:
        """Replicate a delete marker with an EXPLICIT version id and mod
        time — the rebalance/replication copy path (delete_object always
        mints fresh ids, which would break version-history fidelity when
        a marker moves between pools). `metadata` carries replication
        markers (the replica-origin key) on the marker version itself."""
        _k, _m, _, write_quorum = self._default_quorums()
        fi = FileInfo(volume=bucket, name=object_name,
                      version_id=version_id or str(_uuid.uuid4()),
                      deleted=True, mod_time=mod_time or now(),
                      metadata=dict(metadata or {}))
        with self.ns.new_lock(f"{bucket}/{object_name}").write_locked():
            _, errs = meta.for_each_disk(
                self.disks,
                lambda i, d: d.write_metadata(bucket, object_name, fi))
            err = meta.reduce_write_quorum_errs(
                errs, meta.OBJECT_OP_IGNORED_ERRS, write_quorum)
            if err is not None:
                raise api_errors.to_object_err(err, bucket, object_name)
        self._flag_degraded_delete(bucket, object_name, fi.version_id,
                                   errs)
        self._notify_namespace(bucket, object_name)
        return fi.to_object_info(bucket, object_name)

    def _notify_degraded(self, bucket: str, object_name: str,
                         version_id: str) -> None:
        """Best-effort on_degraded_write invocation — the single home of
        the guard+swallow all degraded write paths share."""
        if self.on_degraded_write is None:
            return
        try:
            self.on_degraded_write(bucket, object_name, version_id)
        except Exception:  # noqa: BLE001 — heal queueing is best-effort
            pass

    def _notify_namespace(self, bucket: str, object_name: str) -> None:
        """Best-effort on_namespace_change invocation (the
        _notify_degraded pattern): every successful namespace mutation
        reports (bucket, object) so the persisted bucket metacache can
        journal the delta. Hidden meta buckets never feed the index —
        the index's own segment writes land there."""
        if self.on_namespace_change is None or bucket.startswith("."):
            return
        try:
            self.on_namespace_change(bucket, object_name)
        except Exception:  # noqa: BLE001 — indexing is best-effort
            pass

    def _flag_degraded_delete(self, bucket: str, object_name: str,
                              version_id: str, errs) -> None:
        """Queue an MRF heal when a quorum-successful delete/marker write
        left stale state on some drive (drive gone or write failed). A
        drive answering FileNotFound is already converged — absence is
        the goal state of a delete."""
        if any(e is not None
               and not isinstance(e, serr.OBJECT_NOT_FOUND_ERRS)
               for e in errs):
            self._notify_degraded(bucket, object_name, version_id)

    def delete_objects(self, bucket: str, objects: list[str]
                       ) -> list[Optional[Exception]]:
        """Bulk delete: ONE storage call per drive for the whole batch
        (reference DeleteObjects, cmd/erasure-object.go:772 — not a loop
        of single deletes), with per-key quorum evaluation."""
        if not objects:
            return []
        import copy
        _k, _m, _, write_quorum = self._default_quorums()
        fis = [FileInfo(volume=bucket, name=o) for o in objects]
        with self.ns.new_lock(
                *[f"{bucket}/{o}" for o in objects]).write_locked():
            def bulk(i, d):
                return d.delete_versions(bucket,
                                         [copy.deepcopy(f) for f in fis])

            results, disk_errs = meta.for_each_disk(self.disks, bulk)

        out: list[Optional[Exception]] = []
        for j, o in enumerate(objects):
            per_disk: list[Optional[Exception]] = []
            for res, derr in zip(results, disk_errs):
                if derr is not None:
                    per_disk.append(derr)      # whole drive failed
                elif res is not None and j < len(res):
                    per_disk.append(res[j])
                else:
                    per_disk.append(serr.DiskNotFound("no result"))
            err = meta.reduce_write_quorum_errs(
                per_disk, meta.OBJECT_OP_IGNORED_ERRS, write_quorum)
            out.append(None if err is None
                       else api_errors.to_object_err(err, bucket, o))
            if err is None:
                # quorum-successful delete that left stale state on
                # some drive still needs the MRF pass, exactly like
                # the single-key delete path
                self._flag_degraded_delete(bucket, o, "", per_disk)
                self._notify_namespace(bucket, o)
        return out

    # ------------------------------------------------------------------
    # LIST (merge-walk across drives; cmd/erasure-sets.go:888-1081)
    # ------------------------------------------------------------------

    def list_objects(self, bucket: str, prefix: str = "", marker: str = "",
                     delimiter: str = "", max_keys: int = 1000
                     ) -> tuple[list[ObjectInfo], list[str], bool]:
        """Returns (objects, common_prefixes, is_truncated)."""
        self.get_bucket_info(bucket)  # existence + quorum check

        def read_latest(name: str):
            try:
                fi = self._read_one(bucket, name)
            except api_errors.ObjectApiError:
                return None
            if fi.deleted:
                return None
            return fi.to_object_info(bucket, name)

        return paginate_objects(self._merged_names(bucket, prefix, marker),
                                read_latest, prefix, marker, delimiter,
                                max_keys)

    def list_object_versions(self, bucket: str, prefix: str = "",
                             marker: str = "", max_keys: int = 1000,
                             version_marker: str = "",
                             delimiter: str = ""
                             ) -> tuple[list[ObjectInfo], list[str],
                                        str, str, bool]:
        """One page of the bucket's version history: (versions,
        common_prefixes, next_key_marker, next_version_id_marker,
        is_truncated) — the page shape lives in paginate_versions, the
        SAME loop the metacache index serve runs.

        `version_marker` resumes AFTER that version of `marker` (S3
        version-id-marker semantics); an unknown version id falls back
        to the key's whole version list, which can only over-return,
        never skip. A delimiter rolls keys up into CommonPrefixes like
        the reference's ListObjectVersions."""
        self.get_bucket_info(bucket)
        names = self._merged_names(bucket, prefix, marker,
                                   inclusive=bool(version_marker))
        return paginate_versions(
            names, lambda n: self.object_versions(bucket, n),
            prefix, marker, version_marker, delimiter, max_keys)

    def object_versions(self, bucket: str, name: str) -> list[ObjectInfo]:
        """Quorum-merged versions of ONE object as API ObjectInfos,
        newest first — the per-name unit of list_object_versions, the
        metacache refresh read, and the pool-local read the rebalance
        feed path uses."""
        return [fi.to_object_info(bucket, name)
                for fi in self._merged_versions(bucket, name)]

    def _merged_versions(self, bucket: str, name: str) -> list[FileInfo]:
        """Quorum-merge the per-drive xl.meta version journals of one
        object: a version counts only when >= read-quorum drives agree
        on it (version id + mod time + kind) — a stale drive that missed
        writes (or kept deleted versions) while offline cannot distort
        the history. The reference merges per-drive FileInfo under
        quorum the same way (readAllFileInfo + pickValidFileInfo,
        cmd/erasure-metadata-utils.go:118). Versions sort newest-first
        like the reference journal order."""
        results, _errs = meta.for_each_disk(
            self.disks, lambda i, d: d.read_versions(bucket, name))
        counts: dict[tuple, int] = {}
        picks: dict[tuple, FileInfo] = {}
        for vers in results:
            if vers is None:
                continue
            for fi in vers:
                key = (fi.version_id, fi.mod_time, fi.deleted)
                counts[key] = counts.get(key, 0) + 1
                picks.setdefault(key, fi)
        read_quorum = self.data_shards
        merged = [picks[key] for key, c in counts.items()
                  if c >= read_quorum]
        # deterministic newest-first order: mod time, then version id —
        # the active-active conflict rule. Two sites that hold the same
        # version SET (concurrent writers replicated both ways) must
        # list them identically, including mod-time ties, or the
        # convergence contract of the replication plane breaks.
        merged.sort(key=lambda fi: (fi.mod_time or 0, fi.version_id or ""),
                    reverse=True)
        return merged

    def _merged_names(self, bucket: str, prefix: str,
                      marker: str = "",
                      inclusive: bool = False) -> Iterator[str]:
        """Lazy lexical merge-walk of object names across drives (the
        reference's startMergeWalks/lexicallySortedEntry,
        cmd/erasure-sets.go:888-1081): each drive streams its own sorted
        walk, a heap merge dedupes, and nothing is materialized — a
        100k-key bucket costs one page, not one set.

        Yields names > marker (>= marker when `inclusive` — the
        version-marker resume re-enters the marker key itself) matching
        prefix, in order, until the caller stops."""
        import heapq

        # narrow the walk to the deepest directory of the prefix
        dir_part = prefix.rsplit("/", 1)[0] if "/" in prefix else ""
        # drive walks yield strictly > their marker; shortening the
        # marker by one char re-admits the marker name itself (plus a
        # few predecessors the caller filters out)
        walk_marker = marker[:-1] if (inclusive and marker) else marker

        def drive_names(d) -> Iterator[str]:
            try:
                for fi in d.walk(bucket, dir_part, walk_marker):
                    yield fi.name
            except serr.StorageError:
                return              # drive died mid-walk: its names drop

        iters = []
        live = 0
        for d in self.disks:
            if d is None:
                continue
            iters.append(drive_names(d))
            live += 1
            if live >= 3:  # reference asks 3 disks per set
                break
        last = None
        for name in heapq.merge(*iters):
            if name == last:
                continue
            last = name
            if name.startswith(prefix):
                yield name
            elif name > prefix:
                return              # sorted: nothing later can match

    def _read_one(self, bucket: str, object_name: str) -> FileInfo:
        fi, _, _ = self._object_file_info(bucket, object_name)
        return fi


def paginate_objects(names, read_latest, prefix: str, marker: str,
                     delimiter: str, max_keys: int
                     ) -> tuple[list[ObjectInfo], list[str], bool]:
    """The single home of the object-listing page shape: delimiter
    grouping, marker skips, and max_keys truncation over a sorted
    prefix-matching name stream. Both the merge-walk path
    (ErasureObjects.list_objects) and the metacache index serve run
    THIS loop, so index-served pages are shape-identical to the oracle
    by construction.

    `read_latest(name)` returns the listable ObjectInfo or None (no
    quorum, or the latest version is a delete marker — either way the
    name does not count toward the page)."""
    objects: list[ObjectInfo] = []
    prefixes: list[str] = []
    seen_prefix: set[str] = set()
    truncated = False
    for name in names:
        if marker and name <= marker:
            continue
        if delimiter:
            rest = name[len(prefix):]
            di = rest.find(delimiter)
            if di >= 0:
                p = prefix + rest[:di + len(delimiter)]
                if marker and p <= marker:
                    continue  # prefix page already returned
                if p not in seen_prefix:
                    seen_prefix.add(p)
                    prefixes.append(p)
                    if len(objects) + len(prefixes) >= max_keys + 1:
                        truncated = True
                        prefixes = prefixes[:max_keys - len(objects)]
                        break
                continue
        oi = read_latest(name)
        if oi is None:
            continue
        objects.append(oi)
        if len(objects) + len(prefixes) >= max_keys + 1:
            truncated = True
            objects = objects[:max_keys - len(prefixes)]
            break
    return objects, prefixes, truncated


def paginate_versions(names, versions_of, prefix: str, marker: str,
                      version_marker: str, delimiter: str, max_keys: int
                      ) -> tuple[list[ObjectInfo], list[str], str, str,
                                 bool]:
    """The single home of the versions-listing page shape: delimiter
    grouping (CommonPrefixes, like the reference's ListObjectVersions),
    key+version-id marker resume, and max_keys truncation over a sorted
    prefix-matching name stream. Both the merge-walk path
    (ErasureObjects.list_object_versions) and the metacache index serve
    run THIS loop, so index-served pages are shape-identical to the
    oracle by construction.

    Returns (versions, common_prefixes, next_key_marker,
    next_version_id_marker, is_truncated). Versions and prefixes each
    count one entry toward max_keys (S3 semantics). A page boundary may
    fall INSIDE one key's version list — the markers make the cut
    explicit and resumable; a cut at a rolled-up prefix sets
    next_key_marker to the prefix itself (keys under it sort after it,
    and the `p <= marker` skip on resume collapses them straight back
    into the already-returned prefix entry). `versions_of(name)`
    returns the key's quorum-merged versions, newest first."""
    out: list[ObjectInfo] = []
    prefixes: list[str] = []
    seen_prefix: set[str] = set()
    if max_keys <= 0:
        return [], [], "", "", False
    for name in names:
        if marker:
            if name < marker or (not version_marker and name == marker):
                continue
        if delimiter:
            rest = name[len(prefix):]
            di = rest.find(delimiter)
            if di >= 0:
                p = prefix + rest[:di + len(delimiter)]
                if marker and p <= marker:
                    continue  # prefix page already returned
                if p not in seen_prefix:
                    seen_prefix.add(p)
                    if len(out) + len(prefixes) >= max_keys:
                        # overflow entry actually seen: provably
                        # truncated, the cut falls BEFORE this prefix
                        nkm, nvm = _last_marker(out, prefixes)
                        return out, prefixes, nkm, nvm, True
                    prefixes.append(p)
                continue
        vers = versions_of(name)
        if version_marker and name == marker:
            # "null" is the wire form of the empty (pre-versioning)
            # version id (xmlgen emits it, clients echo it back)
            vm = "" if version_marker == "null" else version_marker
            idx = next((i for i, v in enumerate(vers)
                        if v.version_id == vm), None)
            if idx is not None:
                vers = vers[idx + 1:]
        for oi in vers:
            if len(out) + len(prefixes) >= max_keys:
                # A null version id rides as the "null" sentinel — an
                # empty marker would read as NO marker on resume and
                # skip the key's remaining versions
                nkm, nvm = _last_marker(out, prefixes)
                return out, prefixes, nkm, nvm, True
            out.append(oi)
    return out, prefixes, "", "", False


# the single home of the page-cut marker rule (shared with
# sets.merge_version_listings and the FS/gateway single_version_page)
_last_marker = last_version_marker


class _UnlockOnClose:
    """GET stream wrapper whose close() releases the namespace read
    lock even when the stream was NEVER started — closing (or dropping)
    an unstarted generator skips its ``finally``, so a consumer that
    errors before reading the first chunk (a failed tier upload, an
    aborted proxy) would otherwise leak the read lock and wedge every
    later write-locked op on the object."""

    def __init__(self, gen, release):
        self._gen = gen
        self._release = release

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)

    def close(self) -> None:
        try:
            self._gen.close()
        finally:
            self._release()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass


class _PartReadPlan:
    """One part's GET read state: stateful bitrot readers, the
    precomputed group walk, and the one-group lookahead — factored out
    of the per-part loop so the prefetcher can cross PART boundaries
    (engine._read_object_stream primes part N+1's first group while
    part N's last group verifies/decodes).

    Every reader I/O (group reads, hedged re-reads, the corrupt-block
    re-reads inside verify) serializes on the per-part ``io_lock``: the
    bitrot readers are stateful streams shared with the lookahead
    thread. ``reader_gen`` counts in-place rebuilds of the readers list
    so a verify verdict formed against the OLD readers can't condemn a
    fresh one by index. Parts never share readers, so cross-part
    prefetch needs no cross-part locking."""

    def __init__(self, eng: "ErasureObjects", bucket: str,
                 object_name: str, fi: FileInfo, disks, smeta,
                 codec: Codec, part, offset: int, length: int,
                 suppress_heal_flag: bool = False):
        self.eng = eng
        self.bucket, self.object_name = bucket, object_name
        self.fi, self.disks, self.smeta = fi, disks, smeta
        self.codec, self.part = codec, part
        self.offset, self.length = offset, length
        self.suppress_heal_flag = suppress_heal_flag
        self.n = len(disks)
        self.k = fi.erasure.data_blocks
        self.shard_size = fi.erasure.shard_size()
        self.till = fi.erasure.shard_file_offset(offset, length,
                                                 part.size)
        self.path = f"{object_name}/{fi.data_dir}/part.{part.number}"
        self.readers: Optional[list] = None
        self.part_algo = None
        self.defer_verify = False
        self.avoid: frozenset = frozenset()
        # indices whose shards went missing for PLAN reasons in ANY
        # earlier group (quarantine skip / latency-hedge loser): a
        # condemned-for-latency reader stays out for the whole part,
        # and later groups must keep treating its absence as benign —
        # not as damage to heal (cleared on a quorum-loss rebuild,
        # which mints fresh readers)
        self.benign_hist: set = set()
        self.io_lock = threading.Lock()
        self.reader_gen = [0]
        self.heal_required = False
        self._pending = None           # live lookahead future
        self._primed = False           # _pending holds OUR group 0

        # blocks are read in groups so a degraded part reconstructs many
        # blocks per device call instead of one matmul per block; the
        # group walk is precomputed so the one-group-lookahead
        # prefetcher can issue group N+1's reads while group N runs
        # fused verify+decode and is joined/yielded
        self.specs: list[tuple[list, list]] = []
        bn = offset // fi.erasure.block_size
        end_block = (offset + length - 1) // fi.erasure.block_size
        while bn <= end_block:
            group_end = min(bn + GET_BATCH_BLOCKS - 1, end_block)
            blocks = list(range(bn, group_end + 1))
            geoms = []
            for b in blocks:
                block_off = b * fi.erasure.block_size
                block_len = min(fi.erasure.block_size,
                                part.size - block_off)
                geoms.append((b, block_off, block_len,
                              -(-block_len // self.k)))
            self.specs.append((blocks, geoms))
            bn = group_end + 1

    def _make_readers(self) -> list:
        out: list[Optional[object]] = [None] * self.n
        for i, d in enumerate(self.disks):
            if d is None or self.smeta[i] is None:
                continue
            csum = self.smeta[i].erasure.get_checksum_info(
                self.part.number)
            algo = (bitrot_mod.BitrotAlgorithm.from_string(
                csum.algorithm) if csum else self.eng.bitrot_algo)
            out[i] = bitrot_io.new_bitrot_reader(
                d, self.bucket, self.path, self.till, algo,
                csum.hash if csum else b"", self.shard_size)
        return out

    def _ensure_readers(self) -> None:
        if self.readers is not None:
            return
        self.readers = self._make_readers()
        # slow-drive quarantine: suspect/probation drives fall to the
        # BACK of the candidate order (excluded from primaries and
        # hedge targets) — but only capacity-permitting: with fewer
        # than k healthy readers the plan keeps everyone in play
        if healthtrack.quarantine_enabled():
            sus = {i for i, r in enumerate(self.readers)
                   if r is not None
                   and healthtrack.is_suspect_disk(r.disk)}
            if sus and sum(1 for r in self.readers
                           if r is not None) - len(sus) >= self.k:
                self.avoid = frozenset(sus)
        # device-routed groups defer per-frame bitrot verification into
        # the fused verify+decode program (one dispatch hashes AND
        # reconstructs — cmd/erasure-decode.go:111-150's inseparable
        # verify-then-decode, device form); small/CPU groups verify
        # inline at read time as before. The digest comparison must use
        # the algorithm the frames were WRITTEN with (per-shard
        # csum.algorithm — it may differ from the server's current
        # bitrot config), so deferral needs every reader on one
        # streaming device-kernel algorithm.
        algos = {r.algo for r in self.readers if r is not None}
        self.part_algo = algos.pop() if len(algos) == 1 else None
        self.defer_verify = (
            self.part_algo is not None and self.part_algo.streaming
            and self.codec._device_hash_kernel(self.part_algo)
            is not None
            and self.codec._route(GET_BATCH_BLOCKS * self.k
                                  * self.shard_size) == "device")

    def read_group(self, blocks: list, geoms: list
                   ) -> tuple[list, bool, float, frozenset]:
        """One group's raw shard reads, with the quorum-loss →
        per-block-hedged-read degradation unchanged; returns
        (per-block reads, degraded, read seconds, benign-missing
        reader indices — plan-caused misses the verify step must not
        flag a heal for)."""
        t0 = time.perf_counter()
        degraded = False
        # pre-seeded with earlier groups' plan-caused misses: a reader
        # condemned by a latency hedge in group 1 stays benign-missing
        # for every later group of this part
        benign: set = set(self.benign_hist)
        with self.io_lock, telemetry.span("pipeline.read_group",
                                          blocks=len(blocks)):
            readers = self.readers
            try:
                reads = self.eng._read_group_shards_raw(
                    readers, blocks, self.shard_size,
                    [g[3] for g in geoms], self.k, self.n,
                    collect_digests=self.defer_verify,
                    avoid=self.avoid, benign_sink=benign)
                self.benign_hist = set(benign)
            except api_errors.InsufficientReadQuorum:
                # group-granular hedging can lose quorum where
                # block-granular recovery still succeeds (distinct
                # readers corrupted at distinct blocks): rebuild
                # the readers the group attempt burned and degrade
                # to per-block hedged reads
                for r in readers:
                    if r is not None:
                        r.close()
                readers[:] = self._make_readers()
                self.reader_gen[0] += 1
                degraded = True
                benign.clear()      # recovery mode: flag everything
                self.benign_hist = set()
                reads = [self.eng._read_block_shards_raw(
                    readers, g[0], self.shard_size, g[3], self.k,
                    self.n, collect_digests=self.defer_verify)
                    for g in geoms]
        return reads, degraded, time.perf_counter() - t0, \
            frozenset(benign)

    def _submit(self, spec) -> object:
        """Queue one group's reads on the prefetch pool, carrying the
        caller's span context so the reads attach to the request tree."""
        from ..parallel import pipeline as pl
        cctx = telemetry.propagating_context()
        if cctx is not None:
            return pl.PREFETCH_POOL.submit(cctx.run, self.read_group,
                                           *spec)
        return pl.PREFETCH_POOL.submit(self.read_group, *spec)

    def prime(self) -> None:
        """Issue this part's FIRST group read on the prefetch pool —
        called by the PREVIOUS part when it reaches its last group, so
        the drive I/O of part N+1 overlaps part N's verify+decode."""
        if self._pending is not None or self._primed or not self.specs:
            return
        self._ensure_readers()
        self._pending = self._submit(self.specs[0])
        self._primed = True

    def stream(self, next_plan: Optional["_PartReadPlan"] = None
               ) -> Iterator[bytes]:
        from ..parallel import pipeline as pl
        self._ensure_readers()
        readers = self.readers
        k, n = self.k, self.n
        offset, length = self.offset, self.length
        for si, (blocks, geoms) in enumerate(self.specs):
            group = []
            with stagetimer.stage("get.read_shards"):
                lookahead = self._pending
                self._pending = None
                if lookahead is not None and lookahead.cancel():
                    # still queued behind other streams' prefetch
                    # tasks: reading inline is strictly faster than
                    # waiting for a task that hasn't started
                    lookahead = None
                    self._primed = False
                if lookahead is not None:
                    t0 = time.perf_counter()
                    # the task runs read_group: its shard reads ride
                    # the hedged state machine, so the deadline lives
                    # inside the read itself
                    # check: allow(deadline) task body IS the hedged reader
                    reads, degraded, read_s, benign = lookahead.result()
                    pl.STATS.record_get_group(
                        True, time.perf_counter() - t0, read_s)
                else:
                    reads, degraded, _, benign = self.read_group(blocks,
                                                                 geoms)
                    pl.STATS.record_get_group(False)
            # readers-list generation THIS group's frames came from
            # (the N+1 lookahead may rebuild the list mid-verify)
            gen_at_read = self.reader_gen[0]
            self.heal_required = self.heal_required or degraded
            # issue the NEXT group's reads on the drive pool before
            # this group's verify+decode — decode overlaps drive
            # I/O, bounded to ONE group of lookahead staging; at the
            # LAST group the lookahead crosses into the next part
            if pl.ENABLED and si + 1 < len(self.specs):
                self._pending = self._submit(self.specs[si + 1])
            elif si + 1 == len(self.specs) and next_plan is not None:
                next_plan.prime()
            for (b, block_off, block_len, shard_len), \
                    (shards, digests, had_errors) in zip(geoms, reads):
                self.heal_required = self.heal_required or had_errors
                group.append([b, block_off, block_len, shard_len,
                              shards, digests])
            with stagetimer.stage("get.verify+decode"), \
                    telemetry.span("pipeline.verify_decode",
                                   blocks=len(blocks)):
                if self.eng._verify_and_reconstruct_group(
                        self.codec, group, k, n, readers,
                        self.shard_size,
                        self.part_algo or self.eng.bitrot_algo,
                        io_lock=self.io_lock,
                        reader_gen=(self.reader_gen, gen_at_read),
                        benign_missing=benign):
                    self.heal_required = True
            with stagetimer.stage("get.join"):
                out = []
                for b, block_off, block_len, shard_len, shards, _dg \
                        in group:
                    data = np.concatenate([s[:shard_len]
                                           for s in shards[:k]])
                    begin = max(offset - block_off, 0)
                    end = min(offset + length - block_off, block_len)
                    # slice the view FIRST: tobytes on the full block
                    # then slicing again was two payload copies
                    out.append(data[begin:end].tobytes())
            yield from out
        if self.heal_required and not self.suppress_heal_flag \
                and self.eng.on_degraded_read is not None:
            try:
                self.eng.on_degraded_read(self.bucket, self.object_name)
            except Exception:  # noqa: BLE001 — heal is best-effort
                pass

    def close(self) -> None:
        """Settle any in-flight lookahead, then close the readers (an
        abandoned generator must not leave a pool thread racing closed
        streams)."""
        if self._pending is not None and not self._pending.cancel():
            try:
                # check: allow(deadline) task body IS the hedged reader
                self._pending.result()
            except BaseException:  # noqa: BLE001 — abandoned read
                pass
        self._pending = None
        if self.readers is not None:
            for r in self.readers:
                if r is not None:
                    r.close()
            self.readers = None


def _read_full(reader, n: int) -> bytes:
    """io.ReadFull semantics: exactly n bytes unless EOF."""
    buf = b""
    while len(buf) < n:
        chunk = reader.read(n - len(buf))
        if not chunk:
            break
        buf += chunk
    return buf


def _read_full_into(reader, view: np.ndarray) -> int:
    """io.ReadFull into a caller buffer: fills `view` (a uint8 array
    slice) unless EOF; returns bytes read. Uses the reader's zero-copy
    readinto_full when it has one (HashReader), else falls back to
    read()+copy (chunked-signature readers, plain streams)."""
    fn = getattr(reader, "readinto_full", None)
    if fn is not None:
        return fn(memoryview(view))  # type: ignore[arg-type]
    n = len(view)
    got = 0
    while got < n:
        chunk = reader.read(n - got)
        if not chunk:
            break
        ln = len(chunk)
        view[got:got + ln] = np.frombuffer(chunk, dtype=np.uint8)
        got += ln
    return got
