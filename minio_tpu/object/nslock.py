"""Namespace locking — per-(bucket, object) RW locks.

Local mode of the reference's nsLockMap (cmd/namespace-lock.go:57-66,
localLockInstance): an in-process map of timed RW mutexes keyed by
namespace path, with reference counting so idle entries are reclaimed
(pkg/lsync LRWMutex semantics). The distributed mode (dsync quorum
locks) plugs in behind the same RWLocker interface
(minio_tpu/distributed/dsync.py).
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class _TimedRWLock:
    """Writer-preferring RW lock with acquisition timeout (pkg/lsync
    LRWMutex behavior)."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0
        self.refs = 0

    def acquire_read(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._writer or self._writers_waiting:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    if self._writer or self._writers_waiting:
                        return False
            self._readers += 1
            return True

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cond.wait(remaining):
                        if self._writer or self._readers:
                            return False
                self._writer = True
                return True
            finally:
                self._writers_waiting -= 1

    def release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()


class NSLockMap:
    """Map of namespace path -> RW lock (reference nsLockMap)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._locks: dict[str, _TimedRWLock] = {}

    def new_lock(self, *paths: str) -> "NSLock":
        return NSLock(self, [p for p in paths if p])

    def _get(self, path: str) -> _TimedRWLock:
        with self._mu:
            lk = self._locks.get(path)
            if lk is None:
                lk = _TimedRWLock()
                self._locks[path] = lk
            lk.refs += 1
            return lk

    def _put(self, path: str, lk: _TimedRWLock) -> None:
        with self._mu:
            lk.refs -= 1
            if lk.refs == 0:
                self._locks.pop(path, None)


class NSLock:
    """RWLocker over one or more namespace paths (cmd/namespace-lock.go:38:
    GetLock/GetRLock/Unlock/RUnlock). Multi-path locks acquire in sorted
    order to avoid deadlock (the reference sorts volume lists too)."""

    def __init__(self, ns: NSLockMap, paths: list[str]):
        self._ns = ns
        self._paths = sorted(set(paths))
        self._held: list[tuple[str, _TimedRWLock]] = []

    def get_lock(self, timeout: float = 30.0) -> bool:
        return self._acquire(write=True, timeout=timeout)

    def get_rlock(self, timeout: float = 30.0) -> bool:
        return self._acquire(write=False, timeout=timeout)

    def _acquire(self, write: bool, timeout: float) -> bool:
        acquired: list[tuple[str, _TimedRWLock]] = []
        for p in self._paths:
            lk = self._ns._get(p)
            ok = (lk.acquire_write(timeout) if write
                  else lk.acquire_read(timeout))
            if not ok:
                self._ns._put(p, lk)
                for q, ql in reversed(acquired):
                    (ql.release_write() if write else ql.release_read())
                    self._ns._put(q, ql)
                return False
            acquired.append((p, lk))
        self._held = acquired
        self._write = write
        return True

    def unlock(self) -> None:
        for p, lk in reversed(self._held):
            (lk.release_write() if self._write else lk.release_read())
            self._ns._put(p, lk)
        self._held = []

    runlock = unlock

    # context-manager sugar for the engine
    def write_locked(self, timeout: float = 30.0):
        return _LockCtx(self, True, timeout)

    def read_locked(self, timeout: float = 30.0):
        return _LockCtx(self, False, timeout)


class _LockCtx:
    def __init__(self, lock: NSLock, write: bool, timeout: float):
        self._lock, self._write, self._timeout = lock, write, timeout

    def __enter__(self):
        ok = (self._lock.get_lock(self._timeout) if self._write
              else self._lock.get_rlock(self._timeout))
        if not ok:
            from . import api_errors
            raise api_errors.ObjectApiError("lock acquisition timed out")
        return self._lock

    def __exit__(self, *exc):
        self._lock.unlock()
        return False
