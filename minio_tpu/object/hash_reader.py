"""HashReader — content hashing wrapped around the PUT stream.

The analog of the reference's pkg/hash.Reader (pkg/hash/reader.go):
tees MD5 (the ETag) and optionally SHA256 over the client payload while
the engine consumes it, and verifies client expectations at EOF.

The fork's QAT pattern (pkg/hash/reader.go:189-206: pick a HW engine when
one is free, overlap the digest with encode) generalizes here to a
background hashing thread: blocks are queued to the hasher while the
erasure encode + shard writes proceed — digest latency hides behind the
device pipeline exactly like the fork's async Accel_write_data/MD5Sum
(cmd/erasure-encode.go:113-124).
"""

from __future__ import annotations

import hashlib
import os
import queue
import threading
import time
from typing import BinaryIO, Optional

from . import api_errors
from ..utils import stagetimer

# Overlapping the digest with encode+write only pays when there is a
# second core to run it on; on a single-core host the queue handoff is
# pure overhead.
_DEFAULT_ASYNC = (os.cpu_count() or 1) > 1


class HashReader:
    def __init__(self, stream: BinaryIO, size: int = -1,
                 md5_hex: str = "", sha256_hex: str = "",
                 actual_size: int = -1,
                 async_hash: Optional[bool] = None):
        if async_hash is None:
            async_hash = _DEFAULT_ASYNC
        self._stream = stream
        self.size = size
        self.actual_size = actual_size if actual_size >= 0 else size
        self._want_md5 = md5_hex
        self._want_sha256 = sha256_hex
        self._md5 = hashlib.md5()
        self._sha256 = hashlib.sha256() if sha256_hex else None
        self.bytes_read = 0

        self._async = async_hash
        self._q: Optional[queue.Queue] = None
        self._worker: Optional[threading.Thread] = None
        if async_hash:
            self._q = queue.Queue(maxsize=8)
            self._worker = threading.Thread(target=self._hash_loop,
                                            daemon=True)
            self._worker.start()

    def _hash_loop(self) -> None:
        assert self._q is not None
        while True:
            chunk = self._q.get()
            if chunk is None:
                return
            self._update(chunk)

    def _update(self, chunk) -> None:
        if stagetimer.ENABLED:
            t0 = time.perf_counter()
            self._md5.update(chunk)
            if self._sha256 is not None:
                self._sha256.update(chunk)
            stagetimer.add("put.md5+sha256", time.perf_counter() - t0)
            return
        self._md5.update(chunk)
        if self._sha256 is not None:
            self._sha256.update(chunk)

    def read(self, n: int = -1) -> bytes:
        if self.size >= 0:
            remaining = self.size - self.bytes_read
            if remaining <= 0:
                return b""
            if n is None or n < 0 or n > remaining:
                n = remaining
        chunk = self._stream.read(n) if n != -1 else self._stream.read()
        if chunk:
            self.bytes_read += len(chunk)
            if self._q is not None:
                self._q.put(chunk)
            else:
                self._update(chunk)
        return chunk

    def readinto_full(self, mv: memoryview) -> int:
        """Fill `mv` completely unless EOF; hashes the filled prefix.
        The zero-copy seam of the PUT hot loop: bytes land once in the
        caller's encode buffer (the fork's Accel_get_next_buff pattern,
        cmd/erasure-encode.go:104)."""
        want = len(mv)
        if self.size >= 0:
            remaining = self.size - self.bytes_read
            if remaining <= 0:
                return 0
            if want > remaining:
                mv = mv[:remaining]
                want = remaining
        stream = self._stream
        readinto = getattr(stream, "readinto", None)
        got = 0
        while got < want:
            if readinto is not None:
                n = readinto(mv[got:])
                if not n:
                    break
                got += n
            else:
                chunk = stream.read(want - got)
                if not chunk:
                    break
                mv[got:got + len(chunk)] = chunk
                got += len(chunk)
        if got:
            self.bytes_read += got
            if self._q is not None:
                # async hashing must own a stable copy — the caller
                # reuses the buffer for the next block
                self._q.put(bytes(mv[:got]))
            else:
                self._update(mv[:got])
        return got

    def _drain(self) -> None:
        if self._q is not None and self._worker is not None:
            self._q.put(None)
            self._worker.join()
            self._q = None
            self._worker = None

    def close(self) -> None:
        """Stop the background hasher — MUST be called on abandoned
        uploads or the worker thread leaks."""
        self._drain()

    def md5_current_hex(self) -> str:
        """Digest so far (reference MD5CurrentHexString) — call after the
        stream is fully consumed for the final ETag."""
        self._drain()
        return self._md5.hexdigest()

    def verify(self) -> None:
        """At EOF: enforce declared size and client-expected digests
        (reference hash.Reader EOF verification)."""
        self._drain()
        if self.size >= 0 and self.bytes_read != self.size:
            raise api_errors.IncompleteBody(
                f"read {self.bytes_read} of declared {self.size}")
        if self._want_md5 and self._md5.hexdigest() != self._want_md5:
            raise api_errors.InvalidETag(
                f"md5 mismatch: {self._md5.hexdigest()} != {self._want_md5}")
        if (self._want_sha256 and self._sha256 is not None
                and self._sha256.hexdigest() != self._want_sha256):
            raise api_errors.SignatureDoesNotMatch("content sha256 mismatch")
