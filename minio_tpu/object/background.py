"""Background plane: disk reconnect/new-disk heal + data-usage crawler.

The reference runs these from serverMain (cmd/server-main.go:487-493):
  * monitorLocalDisksAndHeal (cmd/background-newdisks-heal-ops.go) +
    connectDisks/monitorAndConnectEndpoints (cmd/erasure-sets.go:200-281):
    dead drive slots are re-probed, returning drives re-admitted after a
    format check, fresh (wiped/replaced) drives formatted for their slot
    and then swept — every object they should hold is healed onto them
    (healErasureSet, cmd/global-heal.go).
  * the data crawler (cmd/data-crawler.go:61-157): walks every bucket,
    accumulates per-bucket object counts/bytes (feeding quota + admin
    DataUsageInfo), and applies per-object actions (lifecycle expiry
    rides these hooks, cmd/data-crawler.go:629-713).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Callable, Optional

from ..storage import errors as serr
from ..storage.format import read_format_from, write_format_to
from ..storage.xl_storage import MINIO_META_BUCKET, XLStorage
from . import api_errors
from .sets import ErasureSets

DATA_USAGE_OBJECT = "datausage/usage.json"


class DiskMonitor:
    """Re-admit returning drives; format + sweep-heal fresh ones."""

    def __init__(self, sets: ErasureSets, interval: float = 10.0):
        self.sets = sets
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.healed_slots: list[tuple[int, int]] = []   # for tests/admin

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "DiskMonitor":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.scan_once()
            except Exception:  # noqa: BLE001 — keep monitoring
                pass

    # -- one scan ----------------------------------------------------------

    def scan_once(self) -> int:
        """Probe every slot; returns how many drives were (re)admitted."""
        if self.sets.format_ref is None or self.sets.slot_sources is None:
            return 0
        admitted = 0
        for i, eng in enumerate(self.sets.sets):
            for j in range(len(eng.disks)):
                if self._probe_slot(i, j):
                    admitted += 1
        return admitted

    def _probe_slot(self, i: int, j: int) -> bool:
        from ..storage.diskid_check import DiskIDCheck
        eng = self.sets.sets[i]
        cur = eng.disks[j]
        want_uuid = self.sets.format_ref.sets[i][j]

        def unwrap(d):
            return getattr(d, "inner", d)

        def fmt_of(d):
            """format, or None (fresh), or 'err' (unreachable)."""
            try:
                return read_format_from(d)
            except (serr.UnformattedDisk, serr.FileNotFound,
                    serr.VolumeNotFound, serr.CorruptedFormat):
                return None
            except serr.StorageError:
                return "err"

        if cur is not None:
            fmt = fmt_of(unwrap(cur))
            if fmt not in (None, "err") and fmt.this == want_uuid \
                    and fmt.id == self.sets.deployment_id:
                return False         # healthy and in place
            if fmt == "err" and not isinstance(unwrap(cur), XLStorage):
                return False         # remote hiccup: transport re-probes

        # slot is dead, wiped, or replaced: (re)open from its source
        src = self.sets.slot_sources[i][j]
        if isinstance(src, str):
            try:
                drive = XLStorage(src)
            except serr.StorageError:
                return False
        else:
            drive = unwrap(src) if src is not None else unwrap(cur)
        if drive is None:
            return False

        fmt = fmt_of(drive)
        if fmt == "err":
            return False             # unreachable/IO error: try later

        if fmt is not None:
            if fmt.this != want_uuid or fmt.id != self.sets.deployment_id:
                return False         # foreign drive: never adopt
            if cur is not None and unwrap(cur) is drive:
                return False
            eng.disks[j] = DiskIDCheck(drive, want_uuid)
            return True

        # fresh/wiped drive: format it for this slot, admit, sweep-heal
        # (reference HealFormat + healErasureSet)
        nf = dataclasses.replace(self.sets.format_ref, this=want_uuid)
        try:
            write_format_to(drive, nf)
        except serr.StorageError:
            return False
        eng.disks[j] = DiskIDCheck(drive, want_uuid)
        self.healed_slots.append((i, j))
        try:
            self.heal_set_sweep(i)
        except Exception:  # noqa: BLE001 — MRF/next sweep will retry
            pass
        return True

    def heal_set_sweep(self, set_index: int) -> int:
        """Heal every bucket + object of one set (healErasureSet,
        cmd/global-heal.go). Returns objects healed."""
        eng = self.sets.sets[set_index]
        healed = 0
        for vol in eng.list_buckets():
            try:
                eng.heal_bucket(vol.name)
            except api_errors.ObjectApiError:
                continue
            for name in eng._merged_names(vol.name, ""):
                try:
                    eng.heal_object(vol.name, name)
                    healed += 1
                except api_errors.ObjectApiError:
                    continue
        return healed


class HealScanner:
    """Bloom-hinted background heal (the consumer that makes the
    data-update tracker load-bearing — reference data-update-tracker
    feeds the heal crawl the same way): each pass heals only objects
    the tracker says could have changed since the last COMPLETED pass,
    pruning unchanged buckets outright. False positives cost a redundant
    heal check; false negatives cannot happen (the tracker answers
    "changed" whenever its history can't prove otherwise)."""

    def __init__(self, object_layer, tracker, interval: float = 300.0,
                 peer_snapshots: Optional[Callable] = None):
        self.obj = object_layer
        self.tracker = tracker
        self.interval = interval
        # cluster fan-in: callable returning one rotated tracker
        # snapshot per peer (mutations through OTHER nodes' S3
        # endpoints mark THEIR trackers; the scanner must see them all
        # or it would prune objects peers changed — heal false
        # negatives)
        self.peer_snapshots = peer_snapshots
        self._peer_covered: dict[int, int] = {}
        self.last_cycle = 0          # 0 = never ran: full first pass
        self.healed = 0
        self.skipped_buckets = 0
        self.scanned = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HealScanner":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.scan_once()
            except Exception:  # noqa: BLE001 — keep scanning
                pass

    def scan_once(self) -> int:
        """One hinted heal pass; returns objects heal-checked."""
        from .update_tracker import TrackerSnapshot
        # everything marked from here on belongs to the NEXT pass
        pass_cycle = self.tracker.advance_cycle() - 1
        # the last completed pass covered every cycle <= last_cycle, so
        # this pass needs mutations from the cycles AFTER it (asking
        # since=last_cycle would re-heal the previous pass's changes on
        # every subsequent pass, forever)
        since = self.last_cycle + 1

        snaps: list[tuple[int, Optional[TrackerSnapshot]]] = []
        degraded = False
        if self.peer_snapshots is not None:
            for idx, raw in enumerate(self.peer_snapshots()):
                if raw:
                    snaps.append((idx, TrackerSnapshot(raw)))
                else:
                    # unreachable peer: its mutation window is unknown,
                    # so this pass cannot prune anything
                    degraded = True
                    snaps.append((idx, None))
        full = not self.last_cycle or degraded

        def changed(b: str, o: str = "") -> bool:
            if full:
                return True
            if self.tracker.changed_since(since, b, o):
                return True
            return any(
                s.changed_since(self._peer_covered.get(idx, 0) + 1,
                                b, o)
                for idx, s in snaps if s is not None)

        checked = 0
        for vol in self.obj.list_buckets():
            b = vol.name
            if not changed(b):
                self.skipped_buckets += 1
                continue
            marker = ""
            while True:
                try:
                    objs, _, trunc = self.obj.list_objects(
                        b, "", marker, "", 1000)
                except api_errors.ObjectApiError:
                    break
                for oi in objs:
                    if not changed(b, oi.name):
                        continue
                    self.scanned += 1
                    checked += 1
                    try:
                        res = self.obj.heal_object(b, oi.name)
                        if getattr(res, "disks_healed", 0):
                            self.healed += res.disks_healed
                    except api_errors.ObjectApiError:
                        pass
                if not trunc or not objs:
                    break
                marker = objs[-1].name
        self.last_cycle = pass_cycle
        # every reachable peer's rotated window was covered this pass
        # (pruned or scanned under its hints)
        for idx, s in snaps:
            if s is not None:
                self._peer_covered[idx] = s.cycle - 1
        return checked


class DataUsageCrawler:
    """Periodic bucket/object scan feeding usage accounting and
    per-object actions (lifecycle enforcement plugs in via `actions`)."""

    def __init__(self, object_layer, interval: float = 60.0,
                 actions: Optional[list[Callable]] = None,
                 bucket_actions: Optional[list[Callable]] = None,
                 persist: bool = True):
        self.obj = object_layer
        self.interval = interval
        # each action: fn(bucket: str, info: ObjectInfo) -> None
        self.actions = list(actions or [])
        # each bucket action: fn(bucket: str) -> None, once per scan
        # (stale-multipart abort, bucket-level lifecycle work)
        self.bucket_actions = list(bucket_actions or [])
        self.persist = persist
        self.usage: dict = {"buckets": {}, "objects_total": 0,
                            "size_total": 0, "last_update": 0.0}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "DataUsageCrawler":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.scan_once()
            except Exception:  # noqa: BLE001 — keep crawling
                pass

    def scan_once(self) -> dict:
        buckets: dict[str, dict] = {}
        for vol in self.obj.list_buckets():
            b = vol.name
            for baction in self.bucket_actions:
                try:
                    baction(b)
                except Exception:  # noqa: BLE001 — per-bucket
                    pass
            count = size = 0
            marker = ""
            while True:
                try:
                    objs, _, trunc = self.obj.list_objects(
                        b, "", marker, "", 1000)
                except api_errors.ObjectApiError:
                    break
                for oi in objs:
                    count += 1
                    size += oi.size
                    for action in self.actions:
                        try:
                            action(b, oi)
                        except Exception:  # noqa: BLE001 — per-object
                            pass
                if not trunc or not objs:
                    break
                marker = objs[-1].name
            buckets[b] = {"objects": count, "size": size}
        self.usage = {
            "buckets": buckets,
            "objects_total": sum(v["objects"] for v in buckets.values()),
            "size_total": sum(v["size"] for v in buckets.values()),
            "last_update": time.time(),
        }
        if self.persist:
            try:
                self.obj.put_object(MINIO_META_BUCKET, DATA_USAGE_OBJECT,
                                    json.dumps(self.usage).encode())
            except api_errors.ObjectApiError:
                pass
        return self.usage

    def bucket_usage(self, bucket: str) -> Optional[int]:
        """Cached bytes for a bucket; None before the first scan."""
        if not self.usage["last_update"]:
            return None
        info = self.usage["buckets"].get(bucket)
        return int(info["size"]) if info else 0

    @classmethod
    def load_snapshot(cls, object_layer) -> Optional[dict]:
        try:
            _, stream = object_layer.get_object(MINIO_META_BUCKET,
                                                DATA_USAGE_OBJECT)
            return json.loads(b"".join(stream).decode())
        except (api_errors.ObjectApiError, ValueError):
            return None
