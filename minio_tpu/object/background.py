"""Background plane: MRF heal queue, disk reconnect/new-disk heal,
data-usage crawler.

The reference runs these from serverMain (cmd/server-main.go:487-493):
  * the MRF ("most recently failed") heal queue
    (cmd/background-heal-ops.go + maintainMRFList,
    cmd/erasure-sets.go:1641): writes that succeeded at quorum but lost
    some drives, and reads that had to reconstruct, enqueue the object
    for an immediate background heal — degraded objects regain full
    redundancy without waiting for the next scanner sweep.
  * monitorLocalDisksAndHeal (cmd/background-newdisks-heal-ops.go) +
    connectDisks/monitorAndConnectEndpoints (cmd/erasure-sets.go:200-281):
    dead drive slots are re-probed, returning drives re-admitted after a
    format check, fresh (wiped/replaced) drives formatted for their slot
    and then swept — every object they should hold is healed onto them
    (healErasureSet, cmd/global-heal.go).
  * the data crawler (cmd/data-crawler.go:61-157): walks every bucket,
    accumulates per-bucket object counts/bytes (feeding quota + admin
    DataUsageInfo), and applies per-object actions (lifecycle expiry
    rides these hooks, cmd/data-crawler.go:629-713).
"""

from __future__ import annotations

import dataclasses
import heapq
import json
import threading
import time
from typing import TYPE_CHECKING, Callable, Optional

from ..storage import errors as serr
from ..utils import backoff_delay, crashpoint, eventlog, knobs, lockcheck
from ..storage.format import read_format_from, write_format_to
from ..storage.xl_storage import MINIO_META_BUCKET, XLStorage
from . import api_errors

if TYPE_CHECKING:  # sets.py imports MRFHealer — avoid the cycle at runtime
    from .sets import ErasureSets

DATA_USAGE_OBJECT = "datausage/usage.json"

# MRF knobs (documented in README "Fault model & self-healing"). The
# retry window must OUTLAST the drive-recovery cadence (DiskMonitor
# re-probes every MINIO_TPU_DISK_PROBE_S=10 s, the transport health
# probe backs off to MINIO_TPU_PEER_PROBE_S=30 s) — with these
# defaults the schedule spans ~40 s before giving up, so a drive blip
# heals through MRF instead of always falling to the scanner.
MRF_QUEUE_SIZE = knobs.get_int("MINIO_TPU_MRF_QUEUE_SIZE")
MRF_MAX_RETRIES = knobs.get_int("MINIO_TPU_MRF_MAX_RETRIES")
MRF_BACKOFF_BASE = knobs.get_float("MINIO_TPU_MRF_BACKOFF_BASE")
MRF_BACKOFF_MAX = knobs.get_float("MINIO_TPU_MRF_BACKOFF_MAX")


def paged_list_objects(obj, bucket: str):
    """The scanners' shared merge-walk fallback: every listable object
    in one bucket, paged through list_objects (1000/page)."""
    marker = ""
    while True:
        try:
            objs, _, trunc = obj.list_objects(bucket, "", marker, "",
                                              1000)
        except api_errors.ObjectApiError:
            return
        yield from objs
        if not trunc or not objs:
            return
        marker = objs[-1].name


class MRFHealer:
    """Bounded background heal queue with retry + exponential backoff.

    Fed by the engine's degraded-read AND degraded-write hooks: an
    object written (or read) with fewer than N healthy drives enqueues
    `(bucket, object, version)` and a daemon drains entries through
    `heal_fn` immediately — the reference's healMRFRoutine
    (cmd/background-heal-ops.go) rather than waiting for the scanner.

    * entries dedup on (bucket, object, version) while queued/in-flight;
    * a failed heal requeues with capped exponential backoff up to
      `max_retries`, then counts as `failed` (the scanner's sweep is the
      backstop);
    * the queue is bounded: overflow drops the entry (`dropped` stat) —
      losing an MRF hint is safe, losing memory under a fault storm is
      not.
    """

    def __init__(self, heal_fn: Callable[[str, str, str], object],
                 maxsize: Optional[int] = None,
                 max_retries: Optional[int] = None,
                 backoff_base: Optional[float] = None,
                 backoff_max: Optional[float] = None):
        self.heal_fn = heal_fn
        # None means "env default"; explicit zeros are honored
        # (max_retries=0 = heal once, backoff_base=0 = retry instantly)
        self.maxsize = MRF_QUEUE_SIZE if maxsize is None else maxsize
        self.max_retries = (MRF_MAX_RETRIES if max_retries is None
                            else max_retries)
        self.backoff_base = (MRF_BACKOFF_BASE if backoff_base is None
                             else backoff_base)
        self.backoff_max = (MRF_BACKOFF_MAX if backoff_max is None
                            else backoff_max)
        self._cond = lockcheck.condition("mrf.queue")
        self._heap: list[tuple] = []   # (ready_at, seq, b, o, v, attempt)
        self._seq = 0
        # keys currently queued in the heap (dedup)
        self._pending: set[tuple[str, str, str]] = set()
        # keys whose heal is RUNNING -> re-arm flag: a hint arriving
        # mid-heal (object re-degraded) requeues a fresh entry when the
        # running heal finishes, instead of being silently dropped
        self._inflight: dict[tuple[str, str, str], bool] = {}
        self._closed = False
        # stats (admin `mrf` endpoint / metrics)
        self.queued = 0
        self.healed = 0
        self.requeued = 0
        self.failed = 0
        self.dropped = 0
        self.skipped = 0               # object vanished before heal
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # -- producer ----------------------------------------------------------

    def enqueue(self, bucket: str, object_name: str,
                version_id: str = "") -> bool:
        key = (bucket, object_name, version_id)
        with self._cond:
            if self._closed or key in self._pending:
                return False
            if key in self._inflight:
                # heal already running on possibly-stale state: re-arm
                # so it requeues once finished (the hint is preserved)
                self._inflight[key] = True
                return True
            pushed = self._push(key, 0)
            depth = len(self._heap)
        if pushed:
            eventlog.emit("mrf.enqueue", queued=depth)
        return pushed

    def _push(self, key: tuple, attempt: int,
              delay: float = 0.0) -> bool:
        """Queue (or requeue) an entry; caller holds the lock."""
        if len(self._heap) >= self.maxsize:
            self.dropped += 1
            return False
        self._pending.add(key)
        self._seq += 1
        heapq.heappush(self._heap, (time.monotonic() + delay, self._seq,
                                    key[0], key[1], key[2], attempt))
        if attempt == 0:
            self.queued += 1
        else:
            self.requeued += 1
        # notify_all: drain() waiters share this condition — waking only
        # the FIFO-head waiter could wake a drainer instead of the
        # consumer loop and leave the new entry sitting unprocessed
        self._cond.notify_all()
        return True

    # -- consumer ----------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._closed and (
                        not self._heap
                        or self._heap[0][0] > time.monotonic()):
                    if not self._heap:
                        self._cond.wait()     # idle: block until notify
                    else:
                        self._cond.wait(max(
                            self._heap[0][0] - time.monotonic(), 0.001))
                if self._closed:
                    return
                _, _, bucket, obj, vid, attempt = heapq.heappop(self._heap)
                key = (bucket, obj, vid)
                self._pending.discard(key)
                self._inflight[key] = False
            done = True
            try:
                # dequeued, not yet healed: a crash loses only the
                # retry (the object itself is intact; fsck/scanner
                # re-finds the degradation)
                crashpoint.hit("mrf.drain.before_heal")
                res = self.heal_fn(bucket, obj, vid)
                if getattr(res, "missing_after", 0):
                    # partial heal: copies are STILL missing (a target
                    # drive stayed offline) — retry, don't count healed
                    done = self._retry(key, attempt)
                else:
                    with self._cond:
                        self.healed += 1
            except (api_errors.ObjectNotFound, api_errors.BucketNotFound,
                    api_errors.VersionNotFound):
                with self._cond:
                    self.skipped += 1   # deleted since: converged
            except Exception:  # noqa: BLE001 — background heal best-effort
                done = self._retry(key, attempt)
            finally:
                with self._cond:
                    rearm = self._inflight.pop(key, False)
                    if done and rearm and not self._closed:
                        # the object re-degraded while this heal ran:
                        # fresh entry so the new damage is covered
                        self._push(key, 0)
                    self._cond.notify_all()
                if done:
                    eventlog.emit("mrf.drain", healed=self.healed,
                                  failed=self.failed)

    def _retry(self, key: tuple, attempt: int) -> bool:
        """Requeue with backoff; True when the entry is finished
        (retries exhausted)."""
        attempt += 1
        if attempt > self.max_retries:
            with self._cond:
                self.failed += 1
            return True
        backoff = backoff_delay(self.backoff_base, self.backoff_max,
                                attempt - 1)
        with self._cond:
            if self._closed:
                return True
            return not self._push(key, attempt, delay=backoff)

    def kick(self) -> int:
        """Make every queued entry ready NOW, collapsing pending retry
        backoffs — called when a drive is re-admitted so its objects
        heal immediately instead of waiting out the fixed retry window
        (DiskMonitor re-admission hook). Returns entries re-armed."""
        with self._cond:
            if not self._heap:
                return 0
            now = time.monotonic()
            self._heap = [(min(ready, now), seq, b, o, v, attempt)
                          for ready, seq, b, o, v, attempt in self._heap]
            heapq.heapify(self._heap)
            self._cond.notify_all()
            return len(self._heap)

    # -- observability / lifecycle ----------------------------------------

    def pending(self) -> int:
        with self._cond:
            return len(self._heap) + len(self._inflight)

    def stats(self) -> dict:
        with self._cond:
            return {"pending": len(self._heap) + len(self._inflight),
                    "queued": self.queued, "healed": self.healed,
                    "requeued": self.requeued, "failed": self.failed,
                    "dropped": self.dropped, "skipped": self.skipped}

    def drain(self, timeout: float = 10.0) -> bool:
        """Wait for every queued entry to finish (healed, skipped, or
        retries exhausted). Returns False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._heap or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closed:
                    return not (self._heap or self._inflight)
                self._cond.wait(remaining)
        return True

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


class _ScanLoop:
    """Shared lifecycle + failure bookkeeping of the background scan
    loops: run scan_once() every `interval` seconds on a daemon thread,
    counting failures instead of swallowing them silently — a wedged
    background plane must be observable (`errors`, `consecutive_errors`,
    `last_error`; exported as minio_*_consecutive_errors gauges)."""

    interval: float

    def _init_loop(self) -> None:
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.errors = 0
        self.consecutive_errors = 0
        self.last_error = ""

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()

    def scan_once(self):  # pragma: no cover — subclasses implement
        raise NotImplementedError

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.scan_once()
                self.consecutive_errors = 0
            except Exception as e:  # noqa: BLE001 — keep scanning
                self.errors += 1
                self.consecutive_errors += 1
                self.last_error = repr(e)


class DiskMonitor(_ScanLoop):
    """Re-admit returning drives; format + sweep-heal fresh ones; walk
    slow (gray-failing) drives through quarantine.

    Covers every POOL of the cluster, including pools appended after
    boot: ``add_pool`` registers a new pool's drives with the running
    monitor (topology online-expansion follow-up), so a drive that dies
    in a post-boot pool heals exactly like a boot-time one.

    Health states (the gray-failure plane): beyond online/offline, a
    drive whose tracked read/write latency stays past the quarantine
    threshold turns **suspect** — excluded from read plans and hedge
    targets (capacity-permitting) while still written-and-MRF'd. After
    ``MINIO_TPU_QUAR_PROBATION_S`` it enters **probation**: each scan
    runs a timed direct probe, and ``MINIO_TPU_QUAR_PROBES``
    consecutive healthy probes earn a heal-verified re-admission
    (sweep-heal the set, flip back to ok, kick MRF). A slow probe
    re-convicts straight back to suspect."""

    def __init__(self, sets: "ErasureSets",
                 interval: Optional[float] = None):
        self.pools: list["ErasureSets"] = [sets]
        self.interval = knobs.get_float("MINIO_TPU_DISK_PROBE_S") \
            if interval is None else interval
        self.healed_slots: list[tuple[int, int]] = []   # for tests/admin
        # quarantine bookkeeping for admin/tests, bounded: a drive
        # flapping every scan for the life of the process must not
        # grow this without limit
        from collections import deque
        self.quarantine_events: "deque[tuple[str, str]]" = deque(
            maxlen=1000)
        self._init_loop()

    @property
    def sets(self) -> "ErasureSets":
        """First (boot-time) pool — the pre-multi-pool API surface."""
        return self.pools[0]

    def add_pool(self, sets: "ErasureSets") -> None:
        """Register a pool appended after boot (ClusterNode.add_pool)
        so its drive slots are probed from the next scan on."""
        if sets not in self.pools:
            self.pools.append(sets)

    # -- one scan ----------------------------------------------------------

    def scan_once(self) -> int:
        """Probe every slot of every pool; returns drives (re)admitted."""
        admitted = 0
        for pool in list(self.pools):
            admitted += self._scan_pool(pool)
            self._scan_pool_health(pool)
        return admitted

    def _scan_pool(self, pool: "ErasureSets") -> int:
        if pool.format_ref is None or pool.slot_sources is None:
            return 0
        admitted = 0
        for i, eng in enumerate(pool.sets):
            for j in range(len(eng.disks)):
                if self._probe_slot(pool, i, j):
                    admitted += 1
        if admitted and pool.mrf is not None:
            # a returning drive makes queued MRF heals winnable NOW:
            # collapse their retry backoffs instead of waiting them out
            pool.mrf.kick()
        return admitted

    def _probe_slot(self, pool: "ErasureSets", i: int, j: int) -> bool:
        from ..storage.diskid_check import DiskIDCheck
        eng = pool.sets[i]
        cur = eng.disks[j]
        want_uuid = pool.format_ref.sets[i][j]

        def unwrap(d):
            return getattr(d, "inner", d)

        def fmt_of(d):
            """format, or None (fresh), or 'err' (unreachable)."""
            try:
                return read_format_from(d)
            except (serr.UnformattedDisk, serr.FileNotFound,
                    serr.VolumeNotFound, serr.CorruptedFormat):
                return None
            except serr.StorageError:
                return "err"

        if cur is not None:
            fmt = fmt_of(unwrap(cur))
            if fmt not in (None, "err") and fmt.this == want_uuid \
                    and fmt.id == pool.deployment_id:
                return False         # healthy and in place
            if fmt == "err" and not isinstance(unwrap(cur), XLStorage):
                return False         # remote hiccup: transport re-probes

        # slot is dead, wiped, or replaced: (re)open from its source
        src = pool.slot_sources[i][j]
        if isinstance(src, str):
            try:
                drive = XLStorage(src)
            except serr.StorageError:
                return False
        else:
            drive = unwrap(src) if src is not None else unwrap(cur)
        if drive is None:
            return False

        fmt = fmt_of(drive)
        if fmt == "err":
            return False             # unreachable/IO error: try later

        if fmt is not None:
            if fmt.this != want_uuid or fmt.id != pool.deployment_id:
                return False         # foreign drive: never adopt
            if cur is not None and unwrap(cur) is drive:
                return False
            eng.disks[j] = DiskIDCheck(drive, want_uuid)
            return True

        # fresh/wiped drive: format it for this slot, admit, sweep-heal
        # (reference HealFormat + healErasureSet)
        nf = dataclasses.replace(pool.format_ref, this=want_uuid)
        try:
            write_format_to(drive, nf)
        except serr.StorageError:
            return False
        eng.disks[j] = DiskIDCheck(drive, want_uuid)
        self.healed_slots.append((i, j))
        try:
            self.heal_set_sweep(i, pool)
        except Exception:  # noqa: BLE001 — MRF/next sweep will retry
            pass
        return True

    # -- slow-drive quarantine (the gray-failure plane) --------------------

    def _scan_pool_health(self, pool: "ErasureSets") -> None:
        """One health-evaluation pass: convict slow drives, advance
        suspects to probation, probe probationers, re-admit after
        enough healthy probes + a heal-verify sweep."""
        from ..utils import healthtrack
        if not healthtrack.quarantine_enabled():
            return
        tr = healthtrack.TRACKER
        for si, eng in enumerate(pool.sets):
            for d in eng.disks:
                if d is None:
                    continue
                key = healthtrack.disk_key(d)
                state = tr.state_of("drive", key)
                if state == healthtrack.STATE_OK:
                    if tr.should_quarantine("drive", key):
                        tr.set_state("drive", key,
                                     healthtrack.STATE_SUSPECT,
                                     event="suspect")
                        self.quarantine_events.append((key, "suspect"))
                        eventlog.emit("drive.suspect", drive=key,
                                      set=si)
                    continue
                if state == healthtrack.STATE_SUSPECT and \
                        tr.state_age("drive", key) >= knobs.get_float(
                            "MINIO_TPU_QUAR_PROBATION_S"):
                    tr.set_state("drive", key,
                                 healthtrack.STATE_PROBATION,
                                 event="probation")
                    self.quarantine_events.append((key, "probation"))
                    eventlog.emit("drive.probation", drive=key, set=si)
                    state = healthtrack.STATE_PROBATION
                if state != healthtrack.STATE_PROBATION:
                    continue
                dur, ok = self._probe_drive(d)
                tr.observe("drive", key, "probe", dur)
                passed = ok and dur <= tr.quarantine_threshold(
                    "drive", key)
                probes_ok = tr.note_probe("drive", key, passed)
                if not passed:
                    # still slow: re-convicted straight back to
                    # suspect (note_probe reset state + dwell)
                    self.quarantine_events.append((key, "reconvict"))
                    eventlog.emit("drive.reconvict", drive=key, set=si)
                    continue
                if probes_ok >= \
                        knobs.get_int("MINIO_TPU_QUAR_PROBES"):
                    # heal-verified re-admission: the drive took every
                    # write while quarantined only as MRF hints — sweep
                    # the set so its copies are provably whole BEFORE
                    # read plans trust it again
                    try:
                        self.heal_set_sweep(si, pool)
                    except Exception:  # noqa: BLE001 — MRF backstop
                        pass
                    # drop the pre-recovery latency evidence: the
                    # drive took no reads while convicted, so the old
                    # slow samples would re-convict it on the very
                    # next scan (perpetual flap + full sweep each
                    # cycle); re-admission starts a fresh record
                    tr.clear_samples("drive", key)
                    tr.set_state("drive", key, healthtrack.STATE_OK,
                                 event="readmit")
                    self.quarantine_events.append((key, "readmit"))
                    eventlog.emit("drive.readmit", drive=key, set=si)
                    if pool.mrf is not None:
                        pool.mrf.kick()

    @staticmethod
    def _probe_drive(d) -> tuple[float, bool]:
        """One timed direct probe against the drive (goes through the
        full wrapper chain, so injected stalls are felt)."""
        t0 = time.perf_counter()
        try:
            d.disk_info()
            ok = True
        except serr.StorageError:
            ok = False
        return time.perf_counter() - t0, ok

    def heal_set_sweep(self, set_index: int,
                       pool: Optional["ErasureSets"] = None) -> int:
        """Heal every bucket + object of one set (healErasureSet,
        cmd/global-heal.go). Returns objects healed."""
        eng = (pool or self.sets).sets[set_index]
        healed = 0
        for vol in eng.list_buckets():
            try:
                eng.heal_bucket(vol.name)
            except api_errors.ObjectApiError:
                continue
            for name in eng._merged_names(vol.name, ""):
                try:
                    eng.heal_object(vol.name, name)
                    healed += 1
                except api_errors.ObjectApiError:
                    continue
        return healed


class HealScanner(_ScanLoop):
    """Bloom-hinted background heal (the consumer that makes the
    data-update tracker load-bearing — reference data-update-tracker
    feeds the heal crawl the same way): each pass heals only objects
    the tracker says could have changed since the last COMPLETED pass,
    pruning unchanged buckets outright. False positives cost a redundant
    heal check; false negatives cannot happen (the tracker answers
    "changed" whenever its history can't prove otherwise)."""

    def __init__(self, object_layer, tracker, interval: float = 300.0,
                 peer_snapshots: Optional[Callable] = None):
        self.obj = object_layer
        self.tracker = tracker
        self.interval = interval
        # cluster fan-in: callable returning one rotated tracker
        # snapshot per peer (mutations through OTHER nodes' S3
        # endpoints mark THEIR trackers; the scanner must see them all
        # or it would prune objects peers changed — heal false
        # negatives)
        self.peer_snapshots = peer_snapshots
        self._peer_covered: dict[int, int] = {}
        self.last_cycle = 0          # 0 = never ran: full first pass
        self.healed = 0
        self.skipped_buckets = 0
        self.scanned = 0
        self._init_loop()

    def scan_once(self) -> int:
        """One hinted heal pass; returns objects heal-checked."""
        from .update_tracker import TrackerSnapshot
        # everything marked from here on belongs to the NEXT pass
        pass_cycle = self.tracker.advance_cycle() - 1
        # the last completed pass covered every cycle <= last_cycle, so
        # this pass needs mutations from the cycles AFTER it (asking
        # since=last_cycle would re-heal the previous pass's changes on
        # every subsequent pass, forever)
        since = self.last_cycle + 1

        snaps: list[tuple[int, Optional[TrackerSnapshot]]] = []
        degraded = False
        if self.peer_snapshots is not None:
            for idx, raw in enumerate(self.peer_snapshots()):
                if raw:
                    snaps.append((idx, TrackerSnapshot(raw)))
                else:
                    # unreachable peer: its mutation window is unknown,
                    # so this pass cannot prune anything
                    degraded = True
                    snaps.append((idx, None))
        full = not self.last_cycle or degraded

        def changed(b: str, o: str = "") -> bool:
            if full:
                return True
            if self.tracker.changed_since(since, b, o):
                return True
            return any(
                s.changed_since(self._peer_covered.get(idx, 0) + 1,
                                b, o)
                for idx, s in snaps if s is not None)

        checked = 0
        mc = getattr(self.obj, "metacache", None)
        for vol in self.obj.list_buckets():
            b = vol.name
            if not changed(b):
                self.skipped_buckets += 1
                continue
            for oi in self._bucket_objects(mc, b):
                if not changed(b, oi.name):
                    continue
                self.scanned += 1
                checked += 1
                try:
                    res = self.obj.heal_object(b, oi.name)
                    if getattr(res, "disks_healed", 0):
                        self.healed += res.disks_healed
                except api_errors.ObjectApiError:
                    pass
        self._heal_metacache_segments(mc)
        self.last_cycle = pass_cycle
        # every reachable peer's rotated window was covered this pass
        # (pruned or scanned under its hints)
        for idx, s in snaps:
            if s is not None:
                self._peer_covered[idx] = s.cycle - 1
        return checked

    def _bucket_objects(self, mc, bucket: str):
        """One bucket's listable objects: the metacache namespace feed
        when available (no walk), else the paged merge-walk."""
        from .metacache import walks_counter
        feed = mc.namespace_feed(bucket, consumer="heal") \
            if mc is not None else None
        if feed is not None:
            yield from feed
            return
        walks_counter().inc(consumer="heal", source="merge")
        yield from paged_list_objects(self.obj, bucket)

    def _heal_metacache_segments(self, mc) -> int:
        """Sweep-heal the index's own manifest/segment objects: they
        are ordinary erasure-coded objects, but live under the hidden
        meta bucket the regular bucket walk never visits — without this
        a replaced drive would never regain its index shards."""
        if mc is None:
            return 0
        healed = 0
        for key in mc.segment_objects():
            try:
                self.obj.heal_object(MINIO_META_BUCKET, key)
                healed += 1
            except api_errors.ObjectApiError:
                continue
        return healed


class DataUsageCrawler(_ScanLoop):
    """Periodic bucket/object scan feeding usage accounting and
    per-object actions (lifecycle enforcement plugs in via `actions`)."""

    def __init__(self, object_layer, interval: float = 60.0,
                 actions: Optional[list[Callable]] = None,
                 bucket_actions: Optional[list[Callable]] = None,
                 persist: bool = True):
        self.obj = object_layer
        self.interval = interval
        # each action: fn(bucket: str, info: ObjectInfo) -> None
        self.actions = list(actions or [])
        # each bucket action: fn(bucket: str) -> None, once per scan
        # (stale-multipart abort, bucket-level lifecycle work)
        self.bucket_actions = list(bucket_actions or [])
        self.persist = persist
        self.usage: dict = {"buckets": {}, "objects_total": 0,
                            "size_total": 0, "last_update": 0.0}
        self._init_loop()

    def scan_once(self) -> dict:
        from .metacache import walks_counter
        mc = getattr(self.obj, "metacache", None)
        buckets: dict[str, dict] = {}
        for vol in self.obj.list_buckets():
            b = vol.name
            for baction in self.bucket_actions:
                try:
                    baction(b)
                except Exception:  # noqa: BLE001 — per-bucket
                    pass
            count = size = 0
            feed = mc.namespace_feed(b, consumer="crawler") \
                if mc is not None else None
            if feed is None:
                walks_counter().inc(consumer="crawler", source="merge")
                feed = paged_list_objects(self.obj, b)
            for oi in feed:
                count += 1
                size += oi.size
                for action in self.actions:
                    try:
                        action(b, oi)
                    except Exception:  # noqa: BLE001 — per-object
                        pass
            buckets[b] = {"objects": count, "size": size}
        self.usage = {
            "buckets": buckets,
            "objects_total": sum(v["objects"] for v in buckets.values()),
            "size_total": sum(v["size"] for v in buckets.values()),
            "last_update": time.time(),
        }
        if self.persist:
            try:
                self.obj.put_object(MINIO_META_BUCKET, DATA_USAGE_OBJECT,
                                    json.dumps(self.usage).encode())
            except api_errors.ObjectApiError:
                pass
        return self.usage

    def bucket_usage(self, bucket: str) -> Optional[int]:
        """Cached bytes for a bucket; None before the first scan."""
        if not self.usage["last_update"]:
            return None
        info = self.usage["buckets"].get(bucket)
        return int(info["size"]) if info else 0

    @classmethod
    def load_snapshot(cls, object_layer) -> Optional[dict]:
        try:
            _, stream = object_layer.get_object(MINIO_META_BUCKET,
                                                DATA_USAGE_OBJECT)
            return json.loads(b"".join(stream).decode())
        except (api_errors.ObjectApiError, ValueError):
            return None
