"""Object engine: erasure-coded object CRUD + multipart + healing on one
erasure set (reference layers L4a/L5, SURVEY §2.1-2.2)."""

from . import api_errors  # noqa: F401
from .codec import Codec  # noqa: F401
from .engine import ErasureObjects, GetOptions, PutOptions  # noqa: F401
from .hash_reader import HashReader  # noqa: F401
from .healing import HealMixin, HealResultItem  # noqa: F401
from .multipart import CompletePart, MultipartMixin, PartInfo  # noqa: F401
from .nslock import NSLock, NSLockMap  # noqa: F401


class ErasureSetObjects(MultipartMixin, HealMixin):
    """The full per-set object engine: CRUD + multipart + heal."""
