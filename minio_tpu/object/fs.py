"""FS backend — single-drive, non-erasure ObjectLayer.

The reference's fs-v1 (cmd/fs-v1.go + fs-v1-helpers/metadata/multipart):
objects live as PLAIN FILES under <root>/<bucket>/<object> (the tree is
usable by any tool), with per-object metadata in
.minio.sys/buckets/<bucket>/<object>/fs.json and multipart staging under
.minio.sys/multipart. Selected for single-drive deployments
(newObjectLayer, cmd/server-main.go:524-532). No versioning, no erasure,
no heal — the ObjectLayer surface stays identical so every handler works
unchanged.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
import uuid as _uuid
from typing import Iterator, Optional

from ..storage.datatypes import ObjectInfo, ObjectPartInfo, VolInfo, single_version_page
from . import api_errors
from .engine import GetOptions, PutOptions, _read_full
from .hash_reader import HashReader
from .nslock import NSLockMap

META_DIR = ".minio.sys"
BUCKET_META = os.path.join(META_DIR, "buckets")
MULTIPART_DIR = os.path.join(META_DIR, "multipart")
CHUNK = 1 << 20


class FSObjects:
    """ObjectLayer over one directory tree."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(os.path.join(self.root, BUCKET_META), exist_ok=True)
        os.makedirs(os.path.join(self.root, MULTIPART_DIR), exist_ok=True)
        self.ns = NSLockMap()
        self._mu = threading.Lock()

    # -- paths -------------------------------------------------------------

    def _bucket_dir(self, bucket: str) -> str:
        # META_DIR is a legal internal bucket (config/IAM/bucket-metadata
        # ride the ObjectLayer exactly like the erasure backend)
        if bucket != META_DIR and (
                not bucket or bucket.startswith(".") or "/" in bucket):
            raise api_errors.BucketNameInvalid(bucket)
        return os.path.join(self.root, bucket)

    def _obj_path(self, bucket: str, key: str) -> str:
        p = os.path.normpath(os.path.join(self._bucket_dir(bucket), key))
        if not p.startswith(self._bucket_dir(bucket) + os.sep):
            raise api_errors.ObjectNameInvalid(key)
        return p

    def _meta_path(self, bucket: str, key: str) -> str:
        return os.path.join(self.root, BUCKET_META, bucket, key,
                            "fs.json")

    def _load_meta(self, bucket: str, key: str) -> dict:
        try:
            with open(self._meta_path(bucket, key)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _save_meta(self, bucket: str, key: str, meta: dict) -> None:
        p = self._meta_path(bucket, key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, p)

    def _drop_meta(self, bucket: str, key: str) -> None:
        try:
            os.remove(self._meta_path(bucket, key))
        except OSError:
            pass
        # prune empty metadata dirs
        d = os.path.dirname(self._meta_path(bucket, key))
        while d != os.path.join(self.root, BUCKET_META):
            try:
                os.rmdir(d)
            except OSError:
                break
            d = os.path.dirname(d)

    # -- buckets -----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        d = self._bucket_dir(bucket)
        if os.path.isdir(d):
            raise api_errors.BucketExists(bucket)
        os.makedirs(d)
        os.makedirs(os.path.join(self.root, BUCKET_META, bucket),
                    exist_ok=True)

    def bucket_exists(self, bucket: str) -> bool:
        return os.path.isdir(self._bucket_dir(bucket))

    def get_bucket_info(self, bucket: str) -> VolInfo:
        d = self._bucket_dir(bucket)
        if not os.path.isdir(d):
            raise api_errors.BucketNotFound(bucket)
        return VolInfo(bucket, os.stat(d).st_mtime)

    def list_buckets(self) -> list[VolInfo]:
        out = []
        for e in sorted(os.listdir(self.root)):
            if e.startswith("."):
                continue
            full = os.path.join(self.root, e)
            if os.path.isdir(full):
                out.append(VolInfo(e, os.stat(full).st_mtime))
        return out

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        d = self._bucket_dir(bucket)
        if not os.path.isdir(d):
            raise api_errors.BucketNotFound(bucket)
        if not force and any(
                files for _, _, files in os.walk(d)):
            raise api_errors.BucketNotEmpty(bucket)
        shutil.rmtree(d, ignore_errors=True)
        shutil.rmtree(os.path.join(self.root, BUCKET_META, bucket),
                      ignore_errors=True)

    def heal_bucket(self, bucket: str) -> None:
        self.get_bucket_info(bucket)

    # -- objects -----------------------------------------------------------

    def put_object(self, bucket: str, key: str, reader, size: int = -1,
                   opts: Optional[PutOptions] = None) -> ObjectInfo:
        opts = opts or PutOptions()
        self.get_bucket_info(bucket)
        if isinstance(reader, (bytes, bytearray)):
            import io as _io
            size = len(reader)
            reader = HashReader(_io.BytesIO(reader), size)
        elif not isinstance(reader, HashReader):
            reader = HashReader(reader, size)
        path = self._obj_path(bucket, key)
        with self.ns.new_lock(f"{bucket}/{key}").write_locked():
            tmp = os.path.join(self.root, META_DIR,
                               f"tmp-{_uuid.uuid4()}")
            total = 0
            try:
                with open(tmp, "wb") as f:
                    while True:
                        chunk = reader.read(CHUNK)
                        if not chunk:
                            break
                        f.write(chunk)
                        total += len(chunk)
                reader.verify()
            except Exception:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
            finally:
                reader.close()
            etag = opts.metadata.pop("etag", "") or \
                reader.md5_current_hex()
            os.makedirs(os.path.dirname(path), exist_ok=True)
            os.replace(tmp, path)
            meta = {"etag": etag, "metadata": dict(opts.metadata),
                    "size": total, "mod_time": time.time()}
            self._save_meta(bucket, key, meta)
        return self._info(bucket, key, meta)

    def _info(self, bucket: str, key: str, meta: dict) -> ObjectInfo:
        md = dict(meta.get("metadata", {}))
        parts = [ObjectPartInfo(number=p["number"], etag=p.get("etag", ""),
                                size=p.get("size", 0),
                                actual_size=p.get("actual_size",
                                                  p.get("size", 0)))
                 for p in meta.get("parts", [])]
        return ObjectInfo(
            bucket=bucket, name=key, mod_time=meta.get("mod_time", 0.0),
            size=meta.get("size", 0),
            actual_size=int(md.get("X-Minio-Internal-actual-size",
                                   meta.get("size", 0))),
            etag=meta.get("etag", ""),
            content_type=md.get("content-type", ""),
            content_encoding=md.get("content-encoding", ""),
            parts=parts,
            user_defined={k: v for k, v in md.items()
                          if k not in ("content-type",
                                       "content-encoding")})

    def get_object_info(self, bucket: str, key: str,
                        opts: Optional[GetOptions] = None) -> ObjectInfo:
        self.get_bucket_info(bucket)
        path = self._obj_path(bucket, key)
        if not os.path.isfile(path):
            raise api_errors.ObjectNotFound(bucket, key)
        meta = self._load_meta(bucket, key)
        if "size" not in meta:
            st = os.stat(path)
            meta = {"etag": "", "metadata": {}, "size": st.st_size,
                    "mod_time": st.st_mtime}
        return self._info(bucket, key, meta)

    def get_object(self, bucket: str, key: str, offset: int = 0,
                   length: int = -1,
                   opts: Optional[GetOptions] = None
                   ) -> tuple[ObjectInfo, Iterator[bytes]]:
        info = self.get_object_info(bucket, key, opts)
        if length < 0:
            length = info.size - offset
        if offset < 0 or offset + length > info.size:
            if not (info.size == 0 and offset == 0 and length <= 0):
                raise api_errors.InvalidRange(offset, length, info.size)
        path = self._obj_path(bucket, key)

        def gen() -> Iterator[bytes]:
            remaining = length
            with open(path, "rb") as f:
                f.seek(offset)
                while remaining > 0:
                    chunk = f.read(min(CHUNK, remaining))
                    if not chunk:
                        return
                    remaining -= len(chunk)
                    yield chunk

        return info, gen()

    def delete_object(self, bucket: str, key: str, version_id: str = "",
                      versioned: bool = False) -> ObjectInfo:
        self.get_bucket_info(bucket)
        path = self._obj_path(bucket, key)
        with self.ns.new_lock(f"{bucket}/{key}").write_locked():
            if not os.path.isfile(path):
                raise api_errors.ObjectNotFound(bucket, key)
            os.remove(path)
            self._drop_meta(bucket, key)
            # prune empty parent dirs up to the bucket root
            d = os.path.dirname(path)
            while d != self._bucket_dir(bucket):
                try:
                    os.rmdir(d)
                except OSError:
                    break
                d = os.path.dirname(d)
        return ObjectInfo(bucket=bucket, name=key)

    def delete_objects(self, bucket: str, objects: list[str]
                       ) -> list[Optional[Exception]]:
        out: list[Optional[Exception]] = []
        for o in objects:
            try:
                self.delete_object(bucket, o)
                out.append(None)
            except Exception as e:  # noqa: BLE001 — per-key result
                out.append(e)
        return out

    def update_object_metadata(self, bucket: str, key: str,
                               metadata: dict, version_id: str = ""
                               ) -> ObjectInfo:
        with self.ns.new_lock(f"{bucket}/{key}").write_locked():
            info = self.get_object_info(bucket, key)
            meta = self._load_meta(bucket, key)
            new_md = dict(metadata)
            new_md.pop("etag", None)
            meta["metadata"] = new_md
            self._save_meta(bucket, key, meta)
        return self.get_object_info(bucket, key)

    def has_object_versions(self, bucket: str, key: str) -> bool:
        try:
            self.get_object_info(bucket, key)
            return True
        except api_errors.ObjectApiError:
            return False

    def heal_object(self, bucket: str, key: str, version_id: str = "",
                    deep_scan: bool = False, dry_run: bool = False):
        self.get_object_info(bucket, key)   # existence check only
        from .healing import HealResultItem
        return HealResultItem(bucket=bucket, object=key, disks_total=1)

    # -- listing -----------------------------------------------------------

    def _walk_names(self, bucket: str, prefix: str,
                    marker: str) -> Iterator[str]:
        bdir = self._bucket_dir(bucket)

        def rec(rel: str) -> Iterator[str]:
            full = os.path.join(bdir, rel) if rel else bdir
            try:
                entries = sorted(os.listdir(full))
            except OSError:
                return
            for e in entries:
                sub = f"{rel}/{e}" if rel else e
                fp = os.path.join(full, e)
                if os.path.isdir(fp):
                    yield from rec(sub)
                elif (not marker or sub > marker):
                    yield sub

        for name in rec(""):
            if name.startswith(prefix):
                yield name
            elif name > prefix:
                return

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "", delimiter: str = "",
                     max_keys: int = 1000
                     ) -> tuple[list[ObjectInfo], list[str], bool]:
        self.get_bucket_info(bucket)
        objects: list[ObjectInfo] = []
        prefixes: list[str] = []
        seen: set[str] = set()
        truncated = False
        for name in self._walk_names(bucket, prefix, marker):
            if marker and name <= marker:
                continue
            if delimiter:
                rest = name[len(prefix):]
                di = rest.find(delimiter)
                if di >= 0:
                    p = prefix + rest[:di + len(delimiter)]
                    if (not marker or p > marker) and p not in seen:
                        seen.add(p)
                        prefixes.append(p)
                        if len(objects) + len(prefixes) > max_keys:
                            truncated = True
                            prefixes.pop()
                            break
                    continue
            try:
                objects.append(self.get_object_info(bucket, name))
            except api_errors.ObjectApiError:
                continue
            if len(objects) + len(prefixes) > max_keys:
                truncated = True
                objects.pop()
                break
        return objects, prefixes, truncated

    def list_object_versions(self, bucket: str, prefix: str = "",
                             marker: str = "", max_keys: int = 1000,
                             version_marker: str = "",
                             delimiter: str = ""
                             ) -> tuple[list[ObjectInfo], list[str],
                                        str, str, bool]:
        """FS backend is unversioned: one "version" per key, paged on
        the key marker alone (the erasure layer's 5-tuple contract);
        the delimiter rolls up through the regular listing."""
        objs, pfx, trunc = self.list_objects(bucket, prefix, marker,
                                             delimiter, max_keys)
        return single_version_page(objs, trunc, pfx)

    # -- multipart ---------------------------------------------------------

    def _upload_dir(self, upload_id: str) -> str:
        return os.path.join(self.root, MULTIPART_DIR, upload_id)

    def new_multipart_upload(self, bucket: str, key: str,
                             opts: Optional[PutOptions] = None) -> str:
        self.get_bucket_info(bucket)
        upload_id = str(_uuid.uuid4())
        d = self._upload_dir(upload_id)
        os.makedirs(d)
        with open(os.path.join(d, "upload.json"), "w") as f:
            json.dump({"bucket": bucket, "key": key,
                       "metadata": dict((opts or PutOptions()).metadata),
                       "started": time.time()}, f)
        return upload_id

    def _upload_info(self, bucket: str, key: str,
                     upload_id: str) -> dict:
        try:
            with open(os.path.join(self._upload_dir(upload_id),
                                   "upload.json")) as f:
                info = json.load(f)
        except (OSError, ValueError):
            raise api_errors.InvalidUploadID(upload_id) from None
        if info.get("bucket") != bucket or info.get("key") != key:
            raise api_errors.InvalidUploadID(upload_id)
        return info

    def get_multipart_info(self, bucket: str, key: str,
                           upload_id: str) -> dict:
        return dict(self._upload_info(bucket, key, upload_id).get(
            "metadata", {}))

    def put_object_part(self, bucket: str, key: str, upload_id: str,
                        part_number: int, reader, size: int = -1):
        self._upload_info(bucket, key, upload_id)
        if isinstance(reader, (bytes, bytearray)):
            import io as _io
            size = len(reader)
            reader = HashReader(_io.BytesIO(reader), size)
        elif not isinstance(reader, HashReader):
            reader = HashReader(reader, size)
        p = os.path.join(self._upload_dir(upload_id),
                         f"part.{part_number}")
        total = 0
        with open(p, "wb") as f:
            while True:
                chunk = reader.read(CHUNK)
                if not chunk:
                    break
                f.write(chunk)
                total += len(chunk)
        reader.verify()
        etag = reader.md5_current_hex()
        reader.close()
        with open(p + ".json", "w") as f:
            json.dump({"etag": etag, "size": total,
                       "actual_size": reader.actual_size
                       if reader.actual_size >= 0 else total}, f)
        return ObjectPartInfo(number=part_number, etag=etag, size=total,
                              actual_size=reader.actual_size
                              if reader.actual_size >= 0 else total)

    def list_object_parts(self, bucket: str, key: str, upload_id: str,
                          part_marker: int = 0, max_parts: int = 1000
                          ) -> list[ObjectPartInfo]:
        self._upload_info(bucket, key, upload_id)
        d = self._upload_dir(upload_id)
        out = []
        for e in sorted(os.listdir(d)):
            if e.startswith("part.") and e.endswith(".json"):
                n = int(e.split(".")[1])
                if n <= part_marker:
                    continue
                with open(os.path.join(d, e)) as f:
                    pi = json.load(f)
                out.append(ObjectPartInfo(number=n, etag=pi["etag"],
                                          size=pi["size"],
                                          actual_size=pi["actual_size"]))
        out.sort(key=lambda p: p.number)
        return out[:max_parts]

    def list_multipart_uploads(self, bucket: str, key: str = ""
                               ) -> list[dict]:
        base = os.path.join(self.root, MULTIPART_DIR)
        out = []
        for uid in sorted(os.listdir(base)):
            try:
                with open(os.path.join(base, uid, "upload.json")) as f:
                    info = json.load(f)
            except (OSError, ValueError):
                continue
            if info.get("bucket") != bucket:
                continue
            if key and info.get("key") != key:
                continue
            out.append({"object": info["key"], "upload_id": uid,
                        "initiated": info.get("started", 0.0)})
        return out

    def abort_multipart_upload(self, bucket: str, key: str,
                               upload_id: str) -> None:
        self._upload_info(bucket, key, upload_id)
        shutil.rmtree(self._upload_dir(upload_id), ignore_errors=True)

    def complete_multipart_upload(self, bucket: str, key: str,
                                  upload_id: str, parts) -> ObjectInfo:
        info = self._upload_info(bucket, key, upload_id)
        d = self._upload_dir(upload_id)
        md5s = []
        total = 0
        stored = {p.number: p for p in self.list_object_parts(
            bucket, key, upload_id)}
        for i, cp in enumerate(parts):
            sp = stored.get(cp.part_number)
            if sp is None or sp.etag != cp.etag.strip('"'):
                raise api_errors.InvalidPart(cp.part_number)
            if i < len(parts) - 1 and sp.size < 5 * (1 << 20):
                raise api_errors.PartTooSmall(cp.part_number)
            md5s.append(bytes.fromhex(sp.etag))
            total += sp.size
        path = self._obj_path(bucket, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = os.path.join(self.root, META_DIR, f"tmp-{_uuid.uuid4()}")
        with open(tmp, "wb") as out:
            for cp in parts:
                with open(os.path.join(d, f"part.{cp.part_number}"),
                          "rb") as f:
                    shutil.copyfileobj(f, out, CHUNK)
        os.replace(tmp, path)
        etag = (hashlib.md5(b"".join(md5s)).hexdigest()
                + f"-{len(parts)}")
        meta = {"etag": etag, "metadata": info.get("metadata", {}),
                "size": total, "mod_time": time.time(),
                "parts": [{"number": cp.part_number,
                           "etag": stored[cp.part_number].etag,
                           "size": stored[cp.part_number].size,
                           "actual_size":
                               stored[cp.part_number].actual_size}
                          for cp in parts]}
        self._save_meta(bucket, key, meta)
        shutil.rmtree(d, ignore_errors=True)
        return self._info(bucket, key, meta)

    # -- info --------------------------------------------------------------

    def storage_info(self) -> dict:
        st = os.statvfs(self.root)
        total = st.f_blocks * st.f_frsize
        free = st.f_bavail * st.f_frsize
        return {"total": total, "free": free, "used": total - free,
                "online_disks": 1, "offline_disks": 0, "sets": 0,
                "drives_per_set": 1, "backend": "FS"}

    def close(self) -> None:
        pass
