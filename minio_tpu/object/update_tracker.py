"""Data-update tracker: bloom-filtered "what changed since cycle N"
hints for the crawler/heal plane (reference cmd/data-update-tracker.go:
63-103 — every object mutation marks a bloom filter; each crawl cycle
rotates the current filter into a bounded history, and a scanner asks
"could this path have changed since my last cycle?" to skip unchanged
work; false positives only cost a rescan, never correctness).

numpy bit-array bloom with double hashing (two independent sha256-based
hashes combined k times — standard Kirsch-Mitzenmacher), persisted
atomically so the history survives restart.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Optional

import numpy as np

M_BITS = 1 << 20          # 128 KiB per filter
K_HASHES = 7              # ~1e-4 fp at ~10k entries, fine to 100k
MAX_HISTORY = 16          # cycles kept (reference dataUpdateTrackerHistory)


def _hashes(path: str) -> list[int]:
    d = hashlib.sha256(path.encode()).digest()
    h1 = int.from_bytes(d[:8], "big")
    h2 = int.from_bytes(d[8:16], "big") | 1
    return [(h1 + i * h2) % M_BITS for i in range(K_HASHES)]


class _Bloom:
    def __init__(self, bits: Optional[np.ndarray] = None):
        self.bits = bits if bits is not None else np.zeros(
            M_BITS // 8, dtype=np.uint8)

    def add(self, path: str) -> None:
        for h in _hashes(path):
            self.bits[h >> 3] |= np.uint8(1 << (h & 7))

    def contains(self, path: str) -> bool:
        return all(self.bits[h >> 3] & (1 << (h & 7))
                   for h in _hashes(path))

    @property
    def empty(self) -> bool:
        return not self.bits.any()


class DataUpdateTracker:
    """Current-cycle filter + rotated history, persisted to one file."""

    def __init__(self, path: str = ""):
        self.path = path
        self._mu = threading.Lock()
        self.cycle = 1
        self._current = _Bloom()
        self._history: dict[int, _Bloom] = {}   # cycle -> filter
        if path:
            self._load()

    # -- mutation side (object write path) ---------------------------------

    def mark(self, bucket: str, object_name: str = "") -> None:
        """Record a mutation. Both the full path and the bucket alone
        are marked, so scanners can prune whole buckets."""
        with self._mu:
            self._current.add(bucket)
            if object_name:
                self._current.add(f"{bucket}/{object_name}")

    # -- scanner side ------------------------------------------------------

    def current_cycle(self) -> int:
        return self.cycle

    def advance_cycle(self) -> int:
        """Rotate the current filter into history and start a fresh
        cycle (the crawler calls this once per full scan). Returns the
        NEW cycle number."""
        with self._mu:
            self._history[self.cycle] = self._current
            self._current = _Bloom()
            self.cycle += 1
            for c in sorted(self._history):
                if c < self.cycle - MAX_HISTORY:
                    del self._history[c]
            self._persist_locked()
            return self.cycle

    def changed_since(self, cycle: int, bucket: str,
                      object_name: str = "") -> bool:
        """Could this path have been mutated at/after `cycle`? True on
        any bloom hit in the relevant cycles or when the history no
        longer reaches back that far (unknown => assume changed)."""
        path = f"{bucket}/{object_name}" if object_name else bucket
        with self._mu:
            if cycle < self.cycle - MAX_HISTORY or cycle < 1:
                return True            # history gone: must rescan
            if self._current.contains(path):
                return True
            return any(self._history[c].contains(path)
                       for c in self._history if c >= cycle)

    # -- cluster fan-in (peer plane) ---------------------------------------

    def rotate_snapshot(self) -> dict:
        """Advance the cycle and export every retained filter — the
        peer-RPC payload the leader's HealScanner pulls each pass so
        mutations through OTHER nodes' S3 endpoints are never missed
        (each process tracks only its own funnel)."""
        import base64
        self.advance_cycle()
        with self._mu:
            return {"cycle": self.cycle,
                    "filters": {str(c): base64.b64encode(
                        f.bits.tobytes()).decode()
                        for c, f in self._history.items()
                        if not f.empty}}


    # -- persistence -------------------------------------------------------

    def _persist_locked(self) -> None:
        if not self.path:
            return
        import base64
        blob = {
            "cycle": self.cycle,
            "history": {str(c): base64.b64encode(
                f.bits.tobytes()).decode()
                for c, f in self._history.items() if not f.empty},
        }
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        # shared commit recipe: fsync barriers ride MINIO_TPU_FSYNC
        from ..utils import atomicfile
        atomicfile.write_atomic(self.path, json.dumps(blob).encode())

    def flush(self) -> None:
        with self._mu:
            self._persist_locked()

    def _load(self) -> None:
        import base64
        try:
            with open(self.path) as f:
                blob = json.load(f)
        except (OSError, ValueError):
            return
        if not isinstance(blob, dict):
            return      # torn write truncated to a non-dict JSON prefix
        self.cycle = int(blob.get("cycle", 1))
        for c, b64 in blob.get("history", {}).items():
            bits = np.frombuffer(base64.b64decode(b64),
                                 dtype=np.uint8).copy()
            if bits.size == M_BITS // 8:
                self._history[int(c)] = _Bloom(bits)


class TrackerSnapshot:
    """Query wrapper over a rotate_snapshot() payload (possibly from a
    remote node). Decodes filters lazily, once."""

    def __init__(self, snap: dict):
        self.cycle = int(snap.get("cycle", 1))
        self._raw = dict(snap.get("filters", {}))
        self._filters: dict[int, _Bloom] = {}

    def _filter(self, c: int) -> Optional[_Bloom]:
        if c not in self._filters:
            import base64
            raw = self._raw.get(str(c))
            if raw is None:
                return None
            bits = np.frombuffer(base64.b64decode(raw),
                                 dtype=np.uint8).copy()
            self._filters[c] = _Bloom(bits) \
                if bits.size == M_BITS // 8 else _Bloom()
        return self._filters[c]

    def changed_since(self, cycle: int, bucket: str,
                      object_name: str = "") -> bool:
        path = f"{bucket}/{object_name}" if object_name else bucket
        if cycle < self.cycle - MAX_HISTORY or cycle < 1:
            return True                # history gone: assume changed
        for c in range(max(cycle, 1), self.cycle):
            f = self._filter(c)
            if f is not None and f.contains(path):
                return True
        return False
