"""ObjectLayer-level errors (the reference's typed object-API errors,
cmd/object-api-errors.go), produced by quorum reduction over per-drive
StorageErrors (to_object_err == the reference's toObjectErr)."""

from __future__ import annotations

from ..storage import errors as storage_errors


class ObjectApiError(Exception):
    pass


class BucketNotFound(ObjectApiError):
    def __init__(self, bucket: str = ""):
        super().__init__(f"bucket not found: {bucket}")
        self.bucket = bucket


class BucketNotEmpty(ObjectApiError):
    def __init__(self, bucket: str = ""):
        super().__init__(f"bucket not empty: {bucket}")
        self.bucket = bucket


class BucketExists(ObjectApiError):
    def __init__(self, bucket: str = ""):
        super().__init__(f"bucket exists: {bucket}")
        self.bucket = bucket


class BucketNameInvalid(ObjectApiError):
    def __init__(self, bucket: str = ""):
        super().__init__(f"invalid bucket name: {bucket}")
        self.bucket = bucket


class ObjectNotFound(ObjectApiError):
    def __init__(self, bucket: str = "", object: str = ""):
        super().__init__(f"object not found: {bucket}/{object}")
        self.bucket, self.object = bucket, object


class VersionNotFound(ObjectApiError):
    def __init__(self, bucket: str = "", object: str = "",
                 version_id: str = ""):
        super().__init__(
            f"version not found: {bucket}/{object} ({version_id})")
        self.bucket, self.object, self.version_id = bucket, object, version_id


class ObjectNameInvalid(ObjectApiError):
    def __init__(self, bucket: str = "", object: str = ""):
        super().__init__(f"invalid object name: {bucket}/{object}")
        self.bucket, self.object = bucket, object


class ObjectExistsAsDirectory(ObjectApiError):
    pass


class InvalidUploadID(ObjectApiError):
    def __init__(self, upload_id: str = ""):
        super().__init__(f"invalid upload id: {upload_id}")
        self.upload_id = upload_id


class InvalidPart(ObjectApiError):
    def __init__(self, part_number: int = 0, exp: str = "", got: str = ""):
        super().__init__(
            f"invalid part {part_number}: expected etag {exp}, got {got}")
        self.part_number = part_number


class PartTooSmall(ObjectApiError):
    def __init__(self, part_number: int = 0, part_size: int = 0):
        super().__init__(f"part {part_number} too small: {part_size}")
        self.part_number, self.part_size = part_number, part_size


class InsufficientReadQuorum(ObjectApiError):
    """Not enough live drives to read (errErasureReadQuorum)."""


class InsufficientWriteQuorum(ObjectApiError):
    """Not enough live drives to write (errErasureWriteQuorum)."""


class HealFailed(ObjectApiError):
    """A heal attempt made no progress (target drives offline or every
    repair write failed) — the object is still degraded; retry later
    (MRF backoff now, scanner sweep as the backstop). An ObjectApiError
    so per-object heal-sweep handlers skip it instead of aborting."""


class InvalidRange(ObjectApiError):
    def __init__(self, start: int = 0, length: int = 0, size: int = 0):
        super().__init__(f"invalid range {start}+{length} of {size}")
        self.start, self.length, self.size = start, length, size


class IncompleteBody(ObjectApiError):
    pass


class EntityTooLarge(ObjectApiError):
    pass


class EntityTooSmall(ObjectApiError):
    pass


class PreConditionFailed(ObjectApiError):
    pass


class NotImplementedError_(ObjectApiError):
    pass


class InvalidETag(ObjectApiError):
    pass


class MethodNotAllowed(ObjectApiError):
    """e.g. GET on a delete marker."""


class InvalidObjectState(ObjectApiError):
    """GET on a transitioned (tiered) object with no restored local
    copy — the client must POST ?restore first (S3 InvalidObjectState,
    the GLACIER-retrieval semantics applied to remote tiers)."""


class TierNotFound(ObjectApiError):
    """A lifecycle rule or restore referenced a tier name that is not
    in the cluster's tier configuration."""


class SignatureDoesNotMatch(ObjectApiError):
    pass


class ObjectTooLarge(EntityTooLarge):
    pass


def to_object_err(err: Exception, bucket: str = "",
                  object: str = "") -> Exception:
    """Map a per-drive/quorum StorageError to the object-level error the
    API returns (reference toObjectErr, cmd/object-api-errors.go:34-112)."""
    if isinstance(err, ObjectApiError):
        return err
    if isinstance(err, storage_errors.VolumeNotFound):
        return BucketNotFound(bucket)
    if isinstance(err, storage_errors.VolumeNotEmpty):
        return BucketNotEmpty(bucket)
    if isinstance(err, storage_errors.VolumeExists):
        return BucketExists(bucket)
    if isinstance(err, storage_errors.FileVersionNotFound):
        return VersionNotFound(bucket, object)
    if isinstance(err, (storage_errors.FileNotFound,
                        storage_errors.PathNotFound)):
        return ObjectNotFound(bucket, object)
    if isinstance(err, storage_errors.FileNameTooLong):
        return ObjectNameInvalid(bucket, object)
    if isinstance(err, storage_errors.DiskFull):
        return InsufficientWriteQuorum()
    return err
