"""Topology plane: versioned pool placement map ("placement epochs").

Upstream MinIO freezes the pool list at boot; decommission
(cmd/erasure-server-pool-decom.go) bolts a persisted "pool is
draining" state onto it so the router can exclude a pool from new
writes while a background walker moves its data off. CRUSH-style
systems (Ceph) solve the same problem with placement *epochs*: every
topology change bumps a monotonically increasing version, the change
is durable before it takes effect, and data migration happens in the
background against the previous epoch's placement.

This module is that state machine for :class:`ErasureServerSets`:

  * every pool ("zone"/"server set") carries one of three states —

      ``active``     reads + new writes
      ``draining``   reads only; a rebalancer is moving its data off
      ``suspended``  reads only; writes excluded (maintenance), no drain

  * the whole map is one JSON document with an ``epoch`` counter,
    persisted in the hidden config bucket (``.minio.sys``) of EVERY
    pool — any subset of pools that survives a restart can recover the
    newest map (highest epoch wins, the same dual-read rule the data
    path uses mid-migration);

  * transitions go through :meth:`TopologyMap.set_state`, which bumps
    the epoch; callers persist via :class:`TopologyStore` BEFORE acting
    on the new map, so a crash mid-transition replays, never forgets.

The data-path consequences (write routing excludes non-active pools,
reads scan every pool newest-wins) live in ``server_sets.py``; the
background migration lives in ``rebalance.py``.
"""

from __future__ import annotations

import json
import threading
import time
from typing import TYPE_CHECKING, Optional

from ..storage.xl_storage import MINIO_META_BUCKET
from ..utils import atomicfile, crashpoint, regfence
from . import api_errors

if TYPE_CHECKING:  # pragma: no cover — typing only
    from .server_sets import ErasureServerSets

POOL_ACTIVE = "active"
POOL_DRAINING = "draining"
POOL_SUSPENDED = "suspended"
POOL_STATES = (POOL_ACTIVE, POOL_DRAINING, POOL_SUSPENDED)

# the persisted map + per-pool rebalance checkpoints live under this
# prefix of the hidden config bucket; the rebalancer must never migrate
# them (they are deliberately written to every pool)
TOPOLOGY_PREFIX = "topology/"
TOPOLOGY_OBJECT = TOPOLOGY_PREFIX + "pools.json"


class TopologyError(api_errors.ObjectApiError):
    """Invalid topology transition (unknown pool, last active pool)."""


class TopologyMap:
    """The versioned pool-state map. Thread-safe; every mutation bumps
    ``epoch`` so observers (and the persisted doc) can order maps."""

    def __init__(self, n_pools: int, epoch: int = 0,
                 states: Optional[list[str]] = None):
        self._mu = threading.Lock()
        self.epoch = epoch
        if states is None:
            states = [POOL_ACTIVE] * n_pools
        # reopened with a different pool count than the persisted doc:
        # extra live pools default to active (expansion), surplus doc
        # entries drop (pool physically removed after its drain)
        states = list(states[:n_pools])
        states += [POOL_ACTIVE] * (n_pools - len(states))
        self.states = states
        self.updated = time.time()
        # lineage fencing (split-brain detection): every epoch commit
        # chains a hash of (parent lineage, epoch, writer) — equal
        # epochs from divergent histories are a detectable fork
        self.writer = ""
        self.parent_lineage = ""
        self.lineage = ""

    # -- queries -----------------------------------------------------------

    def __len__(self) -> int:
        return len(self.states)

    def state(self, idx: int) -> str:
        with self._mu:
            if idx < 0 or idx >= len(self.states):
                raise TopologyError(f"no pool {idx}")
            return self.states[idx]

    def can_write(self, idx: int) -> bool:
        with self._mu:
            return self.states[idx] == POOL_ACTIVE

    def write_pools(self) -> list[int]:
        """Pool indices eligible for NEW writes."""
        with self._mu:
            return [i for i, s in enumerate(self.states)
                    if s == POOL_ACTIVE]

    def draining_pools(self) -> list[int]:
        with self._mu:
            return [i for i, s in enumerate(self.states)
                    if s == POOL_DRAINING]

    # -- transitions -------------------------------------------------------

    def set_state(self, idx: int, state: str) -> int:
        """Transition pool `idx`; returns the new epoch. Refuses to
        demote the LAST active pool — a cluster with no write target
        would fail every PUT with no way back through the data path."""
        if state not in POOL_STATES:
            raise TopologyError(f"unknown pool state {state!r}")
        with self._mu:
            if idx < 0 or idx >= len(self.states):
                raise TopologyError(f"no pool {idx}")
            if state != POOL_ACTIVE and \
                    all(s != POOL_ACTIVE or i == idx
                        for i, s in enumerate(self.states)):
                raise TopologyError(
                    f"pool {idx} is the last active pool; "
                    "add capacity before draining it")
            if self.states[idx] == state:
                return self.epoch
            self.states[idx] = state
            self.epoch += 1
            self.updated = time.time()
            self._advance_lineage()
            return self.epoch

    def add_pool(self, state: str = POOL_ACTIVE) -> int:
        """Register one appended pool (online expansion); returns the
        new epoch."""
        with self._mu:
            self.states.append(state)
            self.epoch += 1
            self.updated = time.time()
            self._advance_lineage()
            return self.epoch

    def _advance_lineage(self) -> None:
        """Chain the fencing hash for the epoch just committed (caller
        holds ``_mu``)."""
        self.parent_lineage = self.lineage
        self.writer = regfence.default_writer()
        self.lineage = regfence.lineage(self.parent_lineage,
                                        self.epoch, self.writer)

    # -- (de)serialization -------------------------------------------------

    def to_dict(self) -> dict:
        with self._mu:
            return {"epoch": self.epoch, "pools": list(self.states),
                    "updated": self.updated, "writer": self.writer,
                    "parent_lineage": self.parent_lineage,
                    "lineage": self.lineage}

    @classmethod
    def from_dict(cls, doc: dict, n_pools: int) -> "TopologyMap":
        states = [s if s in POOL_STATES else POOL_ACTIVE
                  for s in doc.get("pools", [])]
        tm = cls(n_pools, epoch=int(doc.get("epoch", 0)), states=states)
        tm.updated = float(doc.get("updated", time.time()))
        tm.writer = str(doc.get("writer", ""))
        tm.parent_lineage = str(doc.get("parent_lineage", ""))
        tm.lineage = str(doc.get("lineage", ""))
        return tm


class TopologyStore:
    """Durability for the map: one JSON object in the hidden config
    bucket of every pool.

    * ``save`` writes the doc to EVERY pool (each write is itself
      erasure-coded at write quorum inside that pool) — at least one
      copy must land or the transition is rejected;
    * ``load`` reads from every pool and keeps the highest epoch —
      pools that missed an update (offline during the transition)
      converge on the next save.
    """

    @staticmethod
    def save(server_sets: "ErasureServerSets", tmap: TopologyMap) -> int:
        payload = json.dumps(tmap.to_dict()).encode()
        landed = 0
        last: Optional[Exception] = None
        for z in server_sets.server_sets:
            try:
                # one hit per pool (arm :<nth>): pools left disagreeing
                # on the epoch must converge on load (highest wins)
                crashpoint.hit("topology.save.pool")
                z.put_object(MINIO_META_BUCKET, TOPOLOGY_OBJECT, payload)
                landed += 1
            except Exception as e:  # noqa: BLE001 — per-pool durability
                last = e
        need = regfence.write_quorum(len(server_sets.server_sets))
        if landed < need:
            # refusing a minority-side epoch bump: a partitioned node
            # must not commit a registry version most pools never saw
            raise TopologyError(
                f"topology epoch {tmap.epoch} persisted to {landed} of "
                f"{len(server_sets.server_sets)} pool(s), need {need}: "
                f"{last!r}")
        return landed

    @staticmethod
    def load(server_sets: "ErasureServerSets") -> Optional[TopologyMap]:
        docs: list[dict] = []
        for z in server_sets.server_sets:
            try:
                _, stream = z.get_object(MINIO_META_BUCKET,
                                         TOPOLOGY_OBJECT)
                doc = atomicfile.load_json_doc(b"".join(stream))
            except api_errors.ObjectApiError:
                continue
            if doc is None:     # torn/truncated copy: other pools win
                continue
            docs.append(doc)
        # deterministic winner across pool copies; same-epoch docs with
        # different lineage are a FORK — fsck surfaces + repairs it,
        # load never coin-flips (pick_best ranks identically everywhere)
        best = regfence.pick_best(docs)
        if best is None:
            return None
        return TopologyMap.from_dict(best, len(server_sets.server_sets))
