"""Object healing (reference cmd/erasure-healing.go).

heal_object: find drives whose copy of an object is missing, stale, or
bitrot-corrupt; rebuild exactly the missing shards from the healthy ones
and commit them to the outdated drives via the same tmp→rename 2-phase
commit as PUT (healObject, cmd/erasure-healing.go:220-489).

TPU-first: reconstruction uses the *recover matrix* — decode and
re-encode collapsed into one GF(2⁸) matmul producing only the lost shard
rows (the device form of erasure-lowlevel-heal.go's decode→pipe→encode).
Blocks are read in groups of HEAL_BATCH_BLOCKS and every block sharing
an erasure pattern rebuilds in one stacked, device-routed matmul
(codec.recover_stacked).
"""

from __future__ import annotations

import copy
import uuid as _uuid
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .. import bitrot as bitrot_mod
from ..storage import errors as serr
from ..storage.api import StorageAPI
from ..storage.datatypes import FileInfo, is_restored, is_transitioned
from ..storage.xl_storage import MINIO_META_TMP_BUCKET
from ..utils import knobs
from . import api_errors, bitrot_io, metadata as meta
from .engine import ErasureObjects

HEAL_BATCH_BLOCKS = knobs.get_int("MINIO_TPU_HEAL_BATCH")


@dataclass
class HealResultItem:
    """Summary of one heal operation (madmin HealResultItem shape)."""
    bucket: str = ""
    object: str = ""
    version_id: str = ""
    disks_total: int = 0
    disks_healed: int = 0
    missing_before: int = 0
    missing_after: int = 0
    healed_drives: list[str] = field(default_factory=list)
    dangling_removed: bool = False


class HealMixin(ErasureObjects):
    def heal_bucket(self, bucket: str) -> None:
        """Create the bucket volume on drives that miss it
        (healBucket, cmd/erasure-healing.go)."""
        def mk(i, d):
            try:
                d.stat_vol(bucket)
            except serr.VolumeNotFound:
                d.make_vol(bucket)

        _, errs = meta.for_each_disk(self.disks, mk)
        err = meta.reduce_write_quorum_errs(
            errs, meta.OBJECT_OP_IGNORED_ERRS, len(self.disks) // 2 + 1)
        if err is not None:
            raise api_errors.to_object_err(err, bucket)

    def heal_object(self, bucket: str, object_name: str,
                    version_id: str = "", deep_scan: bool = False,
                    dry_run: bool = False) -> HealResultItem:
        with self.ns.new_lock(f"{bucket}/{object_name}").write_locked():
            return self._heal_object(bucket, object_name, version_id,
                                     deep_scan, dry_run)

    def _heal_object(self, bucket, object_name, version_id, deep_scan,
                     dry_run) -> HealResultItem:
        res = HealResultItem(bucket=bucket, object=object_name,
                             version_id=version_id,
                             disks_total=len(self.disks))
        metas, errs = meta.read_all_file_info(self.disks, bucket,
                                              object_name, version_id)
        # quorum geometry of the latest copy
        try:
            read_quorum, write_quorum = meta.object_quorum_from_meta(
                metas, errs, self.parity_shards)
        except (api_errors.InsufficientReadQuorum, serr.StorageError):
            # maybe dangling (too few copies to ever reconstruct):
            n_meta = sum(1 for fi in metas if fi is not None)
            if 0 < n_meta < len(self.disks) - self.parity_shards:
                self._remove_dangling(bucket, object_name, version_id)
                res.dangling_removed = True
                return res
            raise api_errors.to_object_err(
                api_errors.InsufficientReadQuorum(), bucket,
                object_name) from None

        fi = meta.pick_valid_file_info(metas, read_quorum)
        if fi.deleted or (is_transitioned(fi.metadata)
                          and not is_restored(fi.metadata)):
            # delete markers AND transitioned zero-data stubs need only
            # metadata replication (a stub's data lives in the remote
            # tier — there are no local shards to rebuild)
            missing = [i for i, m in enumerate(metas)
                       if m is None or m.mod_time != fi.mod_time]
            res.missing_before = len(missing)
            if not dry_run and missing:
                for i in missing:
                    d = self.disks[i]
                    if d is None:
                        continue
                    try:
                        d.write_metadata(bucket, object_name,
                                         copy.deepcopy(fi))
                        res.disks_healed += 1
                    except serr.StorageError:
                        pass
                if res.disks_healed == 0:
                    # nothing replicated: fail like the data path does
                    # ('heal wrote no shards') so the MRF queue retries
                    # instead of counting an offline drive as healed
                    raise api_errors.HealFailed(
                        f"{bucket}/{object_name}: "
                        "heal wrote no delete markers")
            res.missing_after = res.missing_before - res.disks_healed
            return res

        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        shuffled = meta.shuffle_disks(self.disks, fi.erasure.distribution)
        smeta = meta.shuffle_parts_metadata(metas, fi.erasure.distribution)

        # classify each shard-slot: healthy (latest meta + parts verify) or
        # outdated (reference disksWithAllParts,
        # cmd/erasure-healing-common.go:158)
        healthy: list[Optional[StorageAPI]] = [None] * len(shuffled)
        outdated: list[Optional[StorageAPI]] = [None] * len(shuffled)
        for i, d in enumerate(shuffled):
            if d is None:
                continue
            fi_i = smeta[i]
            if fi_i is None or fi_i.mod_time != fi.mod_time or \
                    fi_i.data_dir != fi.data_dir:
                outdated[i] = d
                continue
            try:
                d.check_parts(bucket, object_name, fi_i)
                if deep_scan:
                    d.verify_file(bucket, object_name, fi_i)
                healthy[i] = d
            except serr.StorageError:
                outdated[i] = d

        n_healthy = sum(1 for d in healthy if d is not None)
        res.missing_before = len(shuffled) - n_healthy
        if n_healthy < k:
            raise api_errors.InsufficientReadQuorum(
                f"heal: only {n_healthy} healthy shards < k={k}")
        to_heal = [i for i in range(len(shuffled))
                   if outdated[i] is not None]

        # metadata-only divergence: a drive that missed an in-place
        # update_object_metadata (tags/user metadata) still matches on
        # mod_time/data_dir, so the shard classification calls it
        # healthy — converge its xl.meta to the metadata a STRICT
        # majority of healthy copies agree on (quorum metadata writes
        # always leave a majority; an ambiguous split heals nothing)
        meta_stale: list[int] = []
        want_meta: Optional[dict] = None
        fingerprints = [tuple(sorted(smeta[i].metadata.items()))
                        if healthy[i] is not None else None
                        for i in range(len(shuffled))]
        counts: dict[tuple, int] = {}
        for fp in fingerprints:
            if fp is not None:
                counts[fp] = counts.get(fp, 0) + 1
        if len(counts) > 1:
            top = max(counts, key=counts.get)
            if counts[top] > n_healthy // 2:
                want_meta = dict(top)
                meta_stale = [i for i in range(len(shuffled))
                              if fingerprints[i] is not None
                              and fingerprints[i] != top]
                # fi's fingerprint ignores metadata, so the quorum pick
                # may BE a stale copy — rebuilt drives must get the
                # majority metadata, not the stale dict
                fi.metadata = dict(want_meta)
        res.missing_before += len(meta_stale)

        if dry_run:
            res.missing_after = res.missing_before
            return res

        for i in meta_stale:
            f = copy.deepcopy(smeta[i])
            f.metadata = dict(want_meta)
            try:
                shuffled[i].write_metadata(bucket, object_name, f)
                res.disks_healed += 1
            except serr.StorageError:
                pass

        if not to_heal:
            res.missing_after = res.missing_before - res.disks_healed
            if res.missing_after > 0:
                # copies missing on offline slots (or a stale-metadata
                # write failed): nothing more repairable THIS attempt —
                # fail so MRF retries instead of counting a no-op healed
                raise api_errors.HealFailed(
                    f"{bucket}/{object_name}: {res.missing_after} "
                    "copies still missing, no healable drive online")
            return res

        tmp_id = str(_uuid.uuid4())
        codec = self.codec(k, m)
        try:
            written = self._reconstruct_shards(
                bucket, object_name, fi, healthy, smeta, to_heal,
                shuffled, tmp_id, codec)
            # write healed xl.meta + rename into place — only on drives
            # whose shard files were fully written (a writer that failed
            # mid-stream must not get committing metadata)
            heal_fi = copy.deepcopy(fi)
            for i in to_heal:
                d = shuffled[i]
                if d is None or i not in written:
                    continue
                f = copy.deepcopy(heal_fi)
                f.erasure.index = i + 1
                try:
                    # a wiped drive may have lost the bucket dir itself —
                    # recreate it before renaming in (reference heals the
                    # bucket before the object, cmd/erasure-healing.go
                    # healBucket)
                    try:
                        d.make_vol(bucket)
                    except serr.VolumeExists:
                        pass
                    d.write_metadata(MINIO_META_TMP_BUCKET, tmp_id, f)
                    d.rename_data(MINIO_META_TMP_BUCKET, tmp_id,
                                  fi.data_dir, bucket, object_name)
                    res.disks_healed += 1
                    res.healed_drives.append(str(d))
                except serr.StorageError:
                    pass
        finally:
            self._cleanup_tmp(shuffled, tmp_id)

        if res.disks_healed == 0:
            # nothing was actually repaired: surface it so callers (MRF
            # queue, admin heal) retry instead of counting it healed —
            # the reference heals with write quorum 1, so zero successes
            # is a failure (cmd/erasure-lowlevel-heal.go:28). Raised as
            # an ObjectApiError so per-object sweep handlers skip, not
            # abort, the pass.
            raise api_errors.HealFailed(
                f"{bucket}/{object_name}: heal wrote no shards")
        res.missing_after = res.missing_before - res.disks_healed
        return res

    def _reconstruct_shards(self, bucket, object_name, fi: FileInfo,
                            healthy, smeta, to_heal, shuffled, tmp_id,
                            codec) -> set[int]:
        """Per part: batched recover-matrix matmul over all blocks,
        streaming results into bitrot writers for the outdated drives.
        Returns the indices whose shard files were fully written — a
        writer that errors (drive died again mid-heal) is dropped, not
        fatal (the reference heals with write quorum 1,
        cmd/erasure-lowlevel-heal.go:28)."""
        n = len(shuffled)
        k = fi.erasure.data_blocks
        shard_size = fi.erasure.shard_size()
        written = set(to_heal)

        def drop(i: int, writers: dict) -> None:
            written.discard(i)
            w = writers.pop(i, None)
            if w is not None:
                try:
                    w.close()
                except serr.StorageError:
                    pass

        for part in fi.parts:
            if part.size == 0:
                # empty part: just create the empty framed file
                for i in to_heal:
                    d = shuffled[i]
                    if d is not None and i in written:
                        try:
                            w = bitrot_io.new_bitrot_writer(
                                d, MINIO_META_TMP_BUCKET,
                                f"{tmp_id}/{fi.data_dir}/part.{part.number}",
                                -1, self.bitrot_algo, shard_size)
                            w.close()
                        except serr.StorageError:
                            written.discard(i)
                continue
            path = f"{object_name}/{fi.data_dir}/part.{part.number}"
            till = fi.erasure.shard_file_offset(0, part.size, part.size)
            readers: list[Optional[object]] = [None] * n
            for i, d in enumerate(healthy):
                if d is None:
                    continue
                csum = smeta[i].erasure.get_checksum_info(part.number)
                algo = (bitrot_mod.BitrotAlgorithm.from_string(
                    csum.algorithm) if csum else self.bitrot_algo)
                readers[i] = bitrot_io.new_bitrot_reader(
                    d, bucket, path, till, algo,
                    csum.hash if csum else b"", shard_size)
            writers: dict[int, object] = {}
            for i in to_heal:
                d = shuffled[i]
                if d is None or i not in written:
                    continue
                try:
                    writers[i] = bitrot_io.new_bitrot_writer(
                        d, MINIO_META_TMP_BUCKET,
                        f"{tmp_id}/{fi.data_dir}/part.{part.number}",
                        -1, self.bitrot_algo, shard_size)
                except serr.StorageError:
                    written.discard(i)

            from ..ops import rs_matrix
            # device-routed heals defer survivor verification into the
            # fused verify+recover+rehash program (pipeline.heal_step);
            # CPU-routed heals verify inline at read time as before.
            # Deferral needs every reader on ONE streaming device-kernel
            # algorithm (the frames' own algorithm, which may differ
            # from the server's current bitrot config).
            algos = {r.algo for r in readers if r is not None}
            part_algo = algos.pop() if len(algos) == 1 else None
            defer_verify = (
                part_algo is not None and part_algo.streaming
                and codec._device_hash_kernel(part_algo) is not None
                and codec._route(HEAL_BATCH_BLOCKS * k * shard_size)
                == "device")
            verify_algo = part_algo or self.bitrot_algo
            n_blocks = -(-part.size // fi.erasure.block_size)
            bn = 0
            while bn < n_blocks:
                ge = min(bn + HEAL_BATCH_BLOCKS, n_blocks)
                group = []
                for b in range(bn, ge):
                    block_len = min(fi.erasure.block_size,
                                    part.size - b * fi.erasure.block_size)
                    shard_len = -(-block_len // k)
                    shards, digests, _ = self._read_block_shards_raw(
                        readers, b, shard_size, shard_len, k, n,
                        collect_digests=defer_verify)
                    group.append((b, shard_len, shards, digests))
                # rebuild exactly the writer rows, batched per erasure
                # pattern: many blocks -> ONE fused device program
                # (verify survivors + recover rows + digest the rebuilt
                # shards for their new bitrot frames), or one host
                # recover matmul when CPU-routed
                rebuilt: dict[int, dict[int, tuple]] = {}
                buckets: dict[tuple[int, int], list[int]] = {}
                for gi, (_b, sl, shards, _dg) in enumerate(group):
                    mask = sum(1 << i for i in range(n)
                               if shards[i] is not None)
                    buckets.setdefault((mask, sl), []).append(gi)
                # submit every bucket's fused dispatch before resolving
                # any: each bucket's grace window then overlaps
                # same-pattern buckets from concurrent heals/GETs on
                # the shared former (same key -> one fused launch)
                staged: list[tuple] = []
                for (mask, sl), gis in buckets.items():
                    _, used, _missing = rs_matrix.recover_matrix(
                        k, self.parity_shards, mask)
                    stacked = np.stack([
                        np.stack([group[gi][2][u] for u in used])
                        for gi in gis])
                    # fuse hashing only when digests were deferred;
                    # inline-verified survivors need just the matmul
                    want_fused = any(group[gi][3][u] is not None
                                     for gi in gis for u in used)
                    fut = None
                    if want_fused and self.scheduler is not None:
                        fut = self.scheduler.submit_recover(
                            codec, stacked, mask, set(writers.keys()),
                            sl, verify_algo)
                    staged.append((mask, sl, gis, used, stacked,
                                   want_fused, fut))
                for mask, sl, gis, used, stacked, want_fused, fut \
                        in staged:
                    if fut is not None:
                        try:
                            # check: allow(deadline) device dispatch; scheduler close() flushes waiters
                            fused = fut.result()
                        except Exception:  # noqa: BLE001 — a shared-
                            # dispatch failure must not kill a heal the
                            # host can finish: the declined branch
                            # below keeps the deferred digests set, so
                            # the host batch verify still covers them
                            fused = None
                    elif want_fused:
                        fused = codec.verify_and_recover_batch(
                            stacked, mask, set(writers.keys()), sl,
                            verify_algo)
                    else:
                        fused = None
                    if fused is not None:
                        out, idxs, sdig, odig = fused
                        for row_i, gi in enumerate(gis):
                            digests = group[gi][3]
                            bad = False
                            for col, u in enumerate(used):
                                exp = digests[u]
                                if exp is None:
                                    continue
                                if sdig[row_i, col].tobytes() != exp:
                                    readers[u] = None
                                    group[gi][2][u] = None
                                    bad = True
                                else:
                                    digests[u] = None  # verified
                            if bad:
                                rebuilt[gi] = None  # host rebuild below
                            else:
                                rebuilt[gi] = {
                                    idx: (out[row_i][r],
                                          odig[row_i][r].tobytes())
                                    for r, idx in enumerate(idxs)}
                    else:
                        # deferred digests stay set: the host batch
                        # verify below still covers these survivors —
                        # a declined fused bucket must NOT skip
                        # verification (else bitrot would be laundered
                        # into freshly-digested healed shards)
                        out, idxs = codec.recover_stacked(
                            stacked, mask, set(writers.keys()))
                        for row_i, gi in enumerate(gis):
                            rebuilt[gi] = {idx: (out[row_i][r], None)
                                           for r, idx in enumerate(idxs)}

                # host batch verify of every survivor the fused program
                # didn't cover (declined buckets, hedged extras)
                pend: dict[int, list[tuple[int, int]]] = {}
                for gi, (_b, _sl, shards, digests) in enumerate(group):
                    for i in range(n):
                        if digests[i] is not None and \
                                shards[i] is not None:
                            pend.setdefault(
                                len(shards[i]), []).append((gi, i))
                for _sl, items in pend.items():
                    stacked = np.stack(
                        [group[gi][2][i] for gi, i in items])
                    got = bitrot_mod.hash_shards_batch(stacked,
                                                       verify_algo)
                    for row, (gi, i) in enumerate(items):
                        if got[row].tobytes() != group[gi][3][i]:
                            readers[i] = None
                            group[gi][2][i] = None
                            rebuilt[gi] = None  # host rebuild below
                        else:
                            group[gi][3][i] = None

                # corrupt survivor found after deferral: re-read the
                # block with inline verification and rebuild on host
                for gi in range(len(group)):
                    if rebuilt.get(gi, {}) is None:
                        rebuilt[gi] = self._host_rebuild_block(
                            readers, codec, group[gi][0], shard_size,
                            group[gi][1], k, n, set(writers.keys()))

                for gi, (_b, shard_len, shards, _dg) in enumerate(group):
                    rows = rebuilt.get(gi, {})
                    for i, w in list(writers.items()):
                        src, dg = rows.get(i, (None, None))
                        if src is None and shards[i] is not None:
                            src = shards[i]   # shard readable elsewhere
                        if src is None:
                            drop(i, writers)
                            continue
                        try:
                            block = np.ascontiguousarray(
                                src[:shard_len]).tobytes()
                            # a precomputed frame digest is only valid
                            # when the writer frames use the same
                            # algorithm it was computed with
                            if dg is not None and \
                                    self.bitrot_algo.streaming and \
                                    verify_algo == self.bitrot_algo:
                                w.write_with_digest(block, dg)
                            else:
                                w.write(block)
                        except serr.StorageError:
                            drop(i, writers)
                bn = ge
            for r in readers:
                if r is not None:
                    r.close()
            for i, w in list(writers.items()):
                try:
                    w.close()
                except serr.StorageError:
                    drop(i, writers)
        return written

    def _host_rebuild_block(self, readers, codec, block_num: int,
                            shard_size: int, shard_len: int, k: int,
                            n: int, rows: set[int]) -> dict:
        """Rare path after a deferred-verify digest mismatch: the corrupt
        reader is already dead, so re-read the block with inline
        verification and rebuild the requested rows on host. Returns
        {shard_idx: (array, None)} (no precomputed frame digest)."""
        shards, _digests, _he = self._read_block_shards_raw(
            readers, block_num, shard_size, shard_len, k, n)
        full = codec.reconstruct(shards, rows=set(rows))
        return {i: (full[i], None) for i in rows
                if i < len(full) and full[i] is not None}

    def _remove_dangling(self, bucket, object_name, version_id) -> None:
        """Too few copies survive to ever reconstruct: purge the remnants
        (reference dangling-object GC, cmd/erasure-healing.go:311-325)."""
        fi = FileInfo(volume=bucket, name=object_name,
                      version_id=version_id)

        def rm(i, d):
            try:
                d.delete_version(bucket, object_name, fi)
            except serr.StorageError:
                pass

        meta.for_each_disk(self.disks, rm)
