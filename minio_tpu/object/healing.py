"""Object healing (reference cmd/erasure-healing.go).

heal_object: find drives whose copy of an object is missing, stale, or
bitrot-corrupt; rebuild exactly the missing shards from the healthy ones
and commit them to the outdated drives via the same tmp→rename 2-phase
commit as PUT (healObject, cmd/erasure-healing.go:220-489).

TPU-first: reconstruction uses the *recover matrix* — decode and
re-encode collapsed into one GF(2⁸) matmul producing only the lost shard
rows (the device form of erasure-lowlevel-heal.go's decode→pipe→encode).
Blocks are read in groups of HEAL_BATCH_BLOCKS and every block sharing
an erasure pattern rebuilds in one stacked, device-routed matmul
(codec.recover_stacked).
"""

from __future__ import annotations

import copy
import uuid as _uuid
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .. import bitrot as bitrot_mod
from ..storage import errors as serr
from ..storage.api import StorageAPI
from ..storage.datatypes import FileInfo
from ..storage.xl_storage import MINIO_META_TMP_BUCKET
from . import api_errors, bitrot_io, metadata as meta
from .engine import ErasureObjects

import os

HEAL_BATCH_BLOCKS = int(os.environ.get("MINIO_TPU_HEAL_BATCH", "8"))


@dataclass
class HealResultItem:
    """Summary of one heal operation (madmin HealResultItem shape)."""
    bucket: str = ""
    object: str = ""
    version_id: str = ""
    disks_total: int = 0
    disks_healed: int = 0
    missing_before: int = 0
    missing_after: int = 0
    healed_drives: list[str] = field(default_factory=list)
    dangling_removed: bool = False


class HealMixin(ErasureObjects):
    def heal_bucket(self, bucket: str) -> None:
        """Create the bucket volume on drives that miss it
        (healBucket, cmd/erasure-healing.go)."""
        def mk(i, d):
            try:
                d.stat_vol(bucket)
            except serr.VolumeNotFound:
                d.make_vol(bucket)

        _, errs = meta.for_each_disk(self.disks, mk)
        err = meta.reduce_write_quorum_errs(
            errs, meta.OBJECT_OP_IGNORED_ERRS, len(self.disks) // 2 + 1)
        if err is not None:
            raise api_errors.to_object_err(err, bucket)

    def heal_object(self, bucket: str, object_name: str,
                    version_id: str = "", deep_scan: bool = False,
                    dry_run: bool = False) -> HealResultItem:
        with self.ns.new_lock(f"{bucket}/{object_name}").write_locked():
            return self._heal_object(bucket, object_name, version_id,
                                     deep_scan, dry_run)

    def _heal_object(self, bucket, object_name, version_id, deep_scan,
                     dry_run) -> HealResultItem:
        res = HealResultItem(bucket=bucket, object=object_name,
                             version_id=version_id,
                             disks_total=len(self.disks))
        metas, errs = meta.read_all_file_info(self.disks, bucket,
                                              object_name, version_id)
        # quorum geometry of the latest copy
        try:
            read_quorum, write_quorum = meta.object_quorum_from_meta(
                metas, errs, self.parity_shards)
        except (api_errors.InsufficientReadQuorum, serr.StorageError):
            # maybe dangling (too few copies to ever reconstruct):
            n_meta = sum(1 for fi in metas if fi is not None)
            if 0 < n_meta < len(self.disks) - self.parity_shards:
                self._remove_dangling(bucket, object_name, version_id)
                res.dangling_removed = True
                return res
            raise api_errors.to_object_err(
                api_errors.InsufficientReadQuorum(), bucket,
                object_name) from None

        fi = meta.pick_valid_file_info(metas, read_quorum)
        if fi.deleted:
            # delete markers need only metadata replication
            missing = [i for i, m in enumerate(metas)
                       if m is None or m.mod_time != fi.mod_time]
            res.missing_before = len(missing)
            if not dry_run and missing:
                for i in missing:
                    d = self.disks[i]
                    if d is None:
                        continue
                    try:
                        d.write_metadata(bucket, object_name,
                                         copy.deepcopy(fi))
                        res.disks_healed += 1
                    except serr.StorageError:
                        pass
            res.missing_after = sum(
                1 for i in missing
                if self.disks[i] is None)
            return res

        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        shuffled = meta.shuffle_disks(self.disks, fi.erasure.distribution)
        smeta = meta.shuffle_parts_metadata(metas, fi.erasure.distribution)

        # classify each shard-slot: healthy (latest meta + parts verify) or
        # outdated (reference disksWithAllParts,
        # cmd/erasure-healing-common.go:158)
        healthy: list[Optional[StorageAPI]] = [None] * len(shuffled)
        outdated: list[Optional[StorageAPI]] = [None] * len(shuffled)
        for i, d in enumerate(shuffled):
            if d is None:
                continue
            fi_i = smeta[i]
            if fi_i is None or fi_i.mod_time != fi.mod_time or \
                    fi_i.data_dir != fi.data_dir:
                outdated[i] = d
                continue
            try:
                d.check_parts(bucket, object_name, fi_i)
                if deep_scan:
                    d.verify_file(bucket, object_name, fi_i)
                healthy[i] = d
            except serr.StorageError:
                outdated[i] = d

        n_healthy = sum(1 for d in healthy if d is not None)
        res.missing_before = len(shuffled) - n_healthy
        if n_healthy < k:
            raise api_errors.InsufficientReadQuorum(
                f"heal: only {n_healthy} healthy shards < k={k}")
        to_heal = [i for i in range(len(shuffled))
                   if outdated[i] is not None]
        if not to_heal or dry_run:
            res.missing_after = res.missing_before
            return res

        tmp_id = str(_uuid.uuid4())
        codec = self.codec(k, m)
        try:
            written = self._reconstruct_shards(
                bucket, object_name, fi, healthy, smeta, to_heal,
                shuffled, tmp_id, codec)
            # write healed xl.meta + rename into place — only on drives
            # whose shard files were fully written (a writer that failed
            # mid-stream must not get committing metadata)
            heal_fi = copy.deepcopy(fi)
            for i in to_heal:
                d = shuffled[i]
                if d is None or i not in written:
                    continue
                f = copy.deepcopy(heal_fi)
                f.erasure.index = i + 1
                try:
                    # a wiped drive may have lost the bucket dir itself —
                    # recreate it before renaming in (reference heals the
                    # bucket before the object, cmd/erasure-healing.go
                    # healBucket)
                    try:
                        d.make_vol(bucket)
                    except serr.VolumeExists:
                        pass
                    d.write_metadata(MINIO_META_TMP_BUCKET, tmp_id, f)
                    d.rename_data(MINIO_META_TMP_BUCKET, tmp_id,
                                  fi.data_dir, bucket, object_name)
                    res.disks_healed += 1
                    res.healed_drives.append(str(d))
                except serr.StorageError:
                    pass
        finally:
            self._cleanup_tmp(shuffled, tmp_id)

        if res.disks_healed == 0:
            # nothing was actually repaired: surface it so callers (MRF
            # queue, admin heal) retry instead of counting it healed —
            # the reference heals with write quorum 1, so zero successes
            # is a failure (cmd/erasure-lowlevel-heal.go:28)
            raise api_errors.to_object_err(
                serr.DiskNotFound("heal wrote no shards"),
                bucket, object_name)
        res.missing_after = res.missing_before - res.disks_healed
        return res

    def _reconstruct_shards(self, bucket, object_name, fi: FileInfo,
                            healthy, smeta, to_heal, shuffled, tmp_id,
                            codec) -> set[int]:
        """Per part: batched recover-matrix matmul over all blocks,
        streaming results into bitrot writers for the outdated drives.
        Returns the indices whose shard files were fully written — a
        writer that errors (drive died again mid-heal) is dropped, not
        fatal (the reference heals with write quorum 1,
        cmd/erasure-lowlevel-heal.go:28)."""
        n = len(shuffled)
        k = fi.erasure.data_blocks
        shard_size = fi.erasure.shard_size()
        written = set(to_heal)

        def drop(i: int, writers: dict) -> None:
            written.discard(i)
            w = writers.pop(i, None)
            if w is not None:
                try:
                    w.close()
                except serr.StorageError:
                    pass

        for part in fi.parts:
            if part.size == 0:
                # empty part: just create the empty framed file
                for i in to_heal:
                    d = shuffled[i]
                    if d is not None and i in written:
                        try:
                            w = bitrot_io.new_bitrot_writer(
                                d, MINIO_META_TMP_BUCKET,
                                f"{tmp_id}/{fi.data_dir}/part.{part.number}",
                                -1, self.bitrot_algo, shard_size)
                            w.close()
                        except serr.StorageError:
                            written.discard(i)
                continue
            path = f"{object_name}/{fi.data_dir}/part.{part.number}"
            till = fi.erasure.shard_file_offset(0, part.size, part.size)
            readers: list[Optional[object]] = [None] * n
            for i, d in enumerate(healthy):
                if d is None:
                    continue
                csum = smeta[i].erasure.get_checksum_info(part.number)
                algo = (bitrot_mod.BitrotAlgorithm.from_string(
                    csum.algorithm) if csum else self.bitrot_algo)
                readers[i] = bitrot_io.new_bitrot_reader(
                    d, bucket, path, till, algo,
                    csum.hash if csum else b"", shard_size)
            writers: dict[int, object] = {}
            for i in to_heal:
                d = shuffled[i]
                if d is None or i not in written:
                    continue
                try:
                    writers[i] = bitrot_io.new_bitrot_writer(
                        d, MINIO_META_TMP_BUCKET,
                        f"{tmp_id}/{fi.data_dir}/part.{part.number}",
                        -1, self.bitrot_algo, shard_size)
                except serr.StorageError:
                    written.discard(i)

            from ..ops import rs_matrix
            n_blocks = -(-part.size // fi.erasure.block_size)
            bn = 0
            while bn < n_blocks:
                ge = min(bn + HEAL_BATCH_BLOCKS, n_blocks)
                group = []
                for b in range(bn, ge):
                    block_len = min(fi.erasure.block_size,
                                    part.size - b * fi.erasure.block_size)
                    shard_len = -(-block_len // k)
                    shards, _ = self._read_block_shards_raw(
                        readers, b, shard_size, shard_len, k, n)
                    group.append((b - bn, shard_len, shards))
                # rebuild exactly the writer rows, batched per erasure
                # pattern: many blocks -> ONE recover-matrix matmul
                rebuilt: dict[int, dict[int, np.ndarray]] = {}
                buckets: dict[tuple[int, int], list[int]] = {}
                for gi, (_b, sl, shards) in enumerate(group):
                    mask = sum(1 << i for i in range(n)
                               if shards[i] is not None)
                    buckets.setdefault((mask, sl), []).append(gi)
                for (mask, sl), gis in buckets.items():
                    _, used, _missing = rs_matrix.recover_matrix(
                        k, self.parity_shards, mask)
                    stacked = np.stack([
                        np.stack([group[gi][2][u] for u in used])
                        for gi in gis])
                    out, idxs = codec.recover_stacked(
                        stacked, mask, set(writers.keys()))
                    for row_i, gi in enumerate(gis):
                        rebuilt[gi] = {idx: out[row_i][r]
                                       for r, idx in enumerate(idxs)}
                for gi, (_b, shard_len, shards) in enumerate(group):
                    rows = rebuilt.get(gi, {})
                    for i, w in list(writers.items()):
                        src = rows.get(i)
                        if src is None and shards[i] is not None:
                            src = shards[i]   # shard readable elsewhere
                        if src is None:
                            drop(i, writers)
                            continue
                        try:
                            w.write(np.ascontiguousarray(
                                src[:shard_len]).tobytes())
                        except serr.StorageError:
                            drop(i, writers)
                bn = ge
            for r in readers:
                if r is not None:
                    r.close()
            for i, w in list(writers.items()):
                try:
                    w.close()
                except serr.StorageError:
                    drop(i, writers)
        return written

    def _remove_dangling(self, bucket, object_name, version_id) -> None:
        """Too few copies survive to ever reconstruct: purge the remnants
        (reference dangling-object GC, cmd/erasure-healing.go:311-325)."""
        fi = FileInfo(volume=bucket, name=object_name,
                      version_id=version_id)

        def rm(i, d):
            try:
                d.delete_version(bucket, object_name, fi)
            except serr.StorageError:
                pass

        meta.for_each_disk(self.disks, rm)
