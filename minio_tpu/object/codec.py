"""Host-side erasure codec: split/join + encode/reconstruct routing.

The engine-facing seam shaped like the reference's codec wrapper
(cmd/erasure-coding.go:28-112: EncodeData / DecodeDataBlocks /
DecodeDataAndParityBlocks / split semantics). Two backends, picked per
call by batch size — the generalized accelerator-offload pattern of the
fork's QAT engine gate (pkg/hash/reader.go:189-206):

  * native C++ GFNI/AVX-512 (utils/native.py) — low latency, small
    batches / single blocks;
  * TPU kernels (ops/rs_tpu.py) — batched blocks, amortizing dispatch.

Both produce byte-identical shards (tests/test_rs_tpu.py oracle checks).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..ops import gf256, rs_matrix, rs_ref, rs_tpu
from ..utils import knobs, native

# Batches at least this large go to the device (dispatch+transfer amortized).
DEVICE_MIN_BYTES = knobs.get_int("MINIO_TPU_DEVICE_MIN_BYTES")


_IS_TPU: Optional[bool] = None


def _device_is_tpu() -> bool:
    global _IS_TPU
    if _IS_TPU is None:
        try:
            import jax
            _IS_TPU = jax.devices()[0].platform == "tpu"
        except Exception:
            _IS_TPU = False
    return _IS_TPU


def _mesh_active():
    """Mesh the fused batches should dispatch over, or None for the
    single-device path. Default: a multi-device TPU pool. Env
    MINIO_TPU_MESH=1 forces mesh dispatch on any multi-device backend
    (the virtual CPU mesh tests and the driver dryrun), =0 disables.
    (VERDICT r4 #1: the serving stack routes through parallel/mesh.py,
    not only the driver's dryrun.)"""
    v = knobs.get_str("MINIO_TPU_MESH")
    if v == "0":
        return None
    if v != "1" and not _device_is_tpu():
        return None
    from ..parallel import mesh as pmesh
    return pmesh.default_mesh()


class Codec:
    """RS(k, m) over GF(2^8), klauspost-compatible matrices."""

    def __init__(self, data_shards: int, parity_shards: int,
                 block_size: int):
        if not (1 <= data_shards <= 256 and 0 <= parity_shards
                and data_shards + parity_shards <= 256):
            raise ValueError("unsupported erasure geometry")
        self.k = data_shards
        self.m = parity_shards
        self.block_size = block_size
        self.shard_size = -(-block_size // data_shards)
        self._parity_matrix = np.asarray(
            rs_matrix.parity_matrix(self.k, self.m), dtype=np.uint8)

    # -- split / join ------------------------------------------------------

    def split(self, block: bytes | memoryview) -> np.ndarray:
        """block -> (k, S) zero-padded shards, S = ceil(len/k)
        (klauspost Split semantics via reference EncodeData,
        cmd/erasure-coding.go:70-84)."""
        n = len(block)
        if n == 0:
            return np.zeros((self.k, 0), dtype=np.uint8)
        shard = -(-n // self.k)
        buf = np.zeros(self.k * shard, dtype=np.uint8)
        buf[:n] = np.frombuffer(block, dtype=np.uint8)
        return buf.reshape(self.k, shard)

    @staticmethod
    def join(data_shards: np.ndarray, size: int) -> bytes:
        """Concatenate data shards and trim padding."""
        return data_shards.reshape(-1).tobytes()[:size]

    # -- encode ------------------------------------------------------------

    def encode_batch(self, data: np.ndarray, *, force: str = ""
                     ) -> np.ndarray:
        """(B, k, S) or (k, S) data shards -> parity appended (…, k+m, S).

        force: "" auto-route, "native", "device", "numpy" (tests)."""
        if self.m == 0:
            return data
        single = data.ndim == 2
        batch = data[None] if single else data
        path = force or self._route(batch.nbytes)
        if path == "device":
            out = np.asarray(rs_tpu.encode(batch, self.k, self.m))
        elif path == "native" and native.available():
            b, k, s = batch.shape
            parity = np.empty((b, self.m, s), dtype=np.uint8)
            for i in range(b):
                parity[i] = native.gf_matmul(self._parity_matrix, batch[i])
            out = np.concatenate([batch, parity], axis=1)
        else:
            out = np.stack([rs_ref.encode(batch[i], self.m)
                            for i in range(batch.shape[0])])
        return out[0] if single else out

    def encode_parity_batch(self, data: np.ndarray, *, force: str = ""
                            ) -> np.ndarray:
        """(B, k, S) data shards -> (B, m, S) parity ONLY — the PUT hot
        path writes data rows straight out of the read buffer, so no
        full-array concat happens (encode_batch's concatenate was one
        whole extra pass over the payload)."""
        b, _k, s = data.shape
        if self.m == 0:
            return np.zeros((b, 0, s), dtype=np.uint8)
        path = force or self._route(data.nbytes)
        if path == "device":
            return np.asarray(
                rs_tpu.encode(data, self.k, self.m))[:, self.k:]
        parity = np.empty((b, self.m, s), dtype=np.uint8)
        if path == "native" and native.available():
            for i in range(b):
                parity[i] = native.gf_matmul(self._parity_matrix, data[i])
        else:
            for i in range(b):
                parity[i] = rs_ref.encode(data[i], self.m)[self.k:]
        return parity

    def _route(self, nbytes: int) -> str:
        if _device_is_tpu() and nbytes >= DEVICE_MIN_BYTES:
            return "device"
        if native.available():
            return "native"
        return "numpy"

    def _mesh_route(self, nbytes: int, force: str):
        """Mesh for a fused dispatch, or None. Mesh dispatch applies
        ONLY to the fused put/get/heal batches (the paths with sharded
        SPMD programs) — the plain encode/decode fallbacks keep their
        native/numpy routing, so forcing the mesh on a CPU-only host
        never demotes them to single-device XLA."""
        if force not in ("", "device"):
            return None
        if not force and nbytes < DEVICE_MIN_BYTES:
            return None
        return _mesh_active()

    # -- fused encode + bitrot (device) ------------------------------------

    @staticmethod
    def _device_hash_kernel(algo) -> Optional[str]:
        """Device kernel name for a bitrot algorithm, or None when the
        algorithm has no device implementation."""
        from .. import bitrot as bitrot_mod
        if algo in (bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256,
                    bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256S):
            return "highwayhash"
        if algo is bitrot_mod.BitrotAlgorithm.SHA256:
            return "sha256"
        return None

    @staticmethod
    def _staged(stage_cb, outputs):
        """Compute/fetch boundary for the single-device jit path: wait
        for the device values (compute), stamp the stage, and let the
        caller's numpy conversions (fetch = device→host readback) run
        after. No-op without a callback — the hot path pays nothing."""
        import time as _time
        if stage_cb is None:
            return _time.perf_counter()
        try:
            import jax
            jax.block_until_ready(outputs)
        except Exception:  # noqa: BLE001 — attribution is passive
            pass
        return _time.perf_counter()

    def encode_and_hash_batch(self, data: np.ndarray, algo,
                              *, force: str = "", stage_cb=None):
        """Fused device path for the PUT hot loop: one program computes
        parity AND every shard's HighwayHash256 digest (the reference's
        Erasure.Encode + streaming-bitrot work, cmd/erasure-encode.go:75 +
        cmd/bitrot-streaming.go:46, as a single device step).

        data: (B, k, S). Returns (full (B, k+m, S), digests (B, k+m, 32))
        as numpy arrays, or None when the batch doesn't route to the
        device or the bitrot algorithm has no device kernel.

        stage_cb(stage, seconds), when given, receives "compute" (device
        program to completion) and "fetch" (device→host readback +
        result assembly) timings — the batch scheduler's dispatch
        attribution. The mesh path reports a single "compute" stage (its
        sharded programs return host arrays in one step).
        """
        import time as _time
        kernel = self._device_hash_kernel(algo)
        if kernel is None or self.m == 0:
            return None
        mesh = self._mesh_route(data.nbytes, force)
        if mesh is not None:
            from ..parallel import mesh as pmesh
            t0 = _time.perf_counter()
            out = pmesh.mesh_encode_and_hash(mesh, data, self.k, self.m,
                                             kernel)
            if out is not None:
                if stage_cb is not None:
                    stage_cb("compute", _time.perf_counter() - t0)
                return out
        path = force or self._route(data.nbytes)
        if path != "device":
            return None
        from ..models.pipeline import put_step
        t0 = _time.perf_counter()
        parity, digests = put_step(data, self.k, self.m, algo=kernel)
        t1 = self._staged(stage_cb, (parity, digests))
        # only parity + digests cross back from the device; the k data
        # rows are the caller's own bytes
        out = (np.concatenate([np.asarray(data, np.uint8),
                               np.asarray(parity)], axis=1),
               np.asarray(digests))
        if stage_cb is not None:
            stage_cb("compute", t1 - t0)
            stage_cb("fetch", _time.perf_counter() - t1)
        return out

    def encrypt_encode_and_hash_batch(self, data: np.ndarray, keys,
                                      nonces, pkg_bytes: int, algo,
                                      *, force: str = "",
                                      stage_cb=None):
        """Fused device path for the ENCRYPTED PUT hot loop: ChaCha20
        cipher + parity + per-shard digests in one launch
        (models/pipeline.sse_put_step) — an encrypted batch costs the
        same single dispatch as a plaintext one.

        data: (B, k, S) staged PLAINTEXT shards; keys (B, 8) / nonces
        (B, P, 3) u32 word arrays (features/crypto.DeviceSSE.
        batch_params — P·pkg_bytes plaintext bytes per row). Returns
        (full (B, k+m, S) — CIPHERTEXT data rows with parity appended,
        digests (B, k+m, 32)), or None when the batch doesn't route to
        the device (the caller's CPU cipher path is the oracle). The
        mesh has no sse program yet, so mesh-routed hosts fall back to
        the CPU path too.
        """
        import time as _time
        kernel = self._device_hash_kernel(algo)
        if kernel is None or self.m == 0:
            return None
        path = force or self._route(data.nbytes)
        if path != "device":
            return None
        from ..models.pipeline import sse_put_step
        t0 = _time.perf_counter()
        full, digests = sse_put_step(data, keys, nonces, self.k,
                                     self.m, pkg_bytes, algo=kernel)
        t1 = self._staged(stage_cb, (full, digests))
        # the data rows DO cross back here: the caller staged plaintext
        # and must write (and Poly1305-tag) the ciphertext
        out = np.asarray(full), np.asarray(digests)
        if stage_cb is not None:
            stage_cb("compute", t1 - t0)
            stage_cb("fetch", _time.perf_counter() - t1)
        return out

    def verify_decode_decrypt_batch(self, survivors: np.ndarray,
                                    present_mask: int, shard_len: int,
                                    keys, nonces, pkg_bytes: int, algo,
                                    *, force: str = "", stage_cb=None):
        """Fused device path for the ENCRYPTED degraded GET: bitrot-
        verify survivors, reconstruct the missing data rows, and
        decipher the reassembled data shards in one launch
        (models/pipeline.sse_get_step).

        survivors: (B, k, S) in missing_data_matrix `used` order.
        Returns (plain (B, k, S) deciphered data shards in shard-index
        order, missing_idx, survivor_digests (B, k, 32)), or None when
        not device-routed / no device hash kernel / nothing missing.
        Package tags still verify host-side before any of this output
        is served (features/crypto.chacha_decrypt_ranged discipline).
        """
        import time as _time
        kernel = self._device_hash_kernel(algo)
        if kernel is None:
            return None
        path = force or self._route(survivors.nbytes)
        if path != "device":
            return None
        dm, used, missing = rs_matrix.missing_data_matrix(
            self.k, self.m, present_mask)
        if not missing:
            return None
        # static reassembly map: data shard j comes from the survivors
        # stack (decode `used` order) or the reconstructed rows
        # (`missing` order)
        data_src = tuple(
            (0, used.index(j)) if j in used else (1, missing.index(j))
            for j in range(self.k))
        m2 = rs_tpu._bit_expand_cached(dm.tobytes(), dm.shape)
        from ..models.pipeline import sse_get_step
        t0 = _time.perf_counter()
        plain, _ct_missing, digests = sse_get_step(
            survivors, m2, keys, nonces, dm.shape[0], self.k,
            data_src, pkg_bytes, shard_len, algo=kernel)
        t1 = self._staged(stage_cb, (plain, digests))
        result = np.asarray(plain), missing, np.asarray(digests)
        if stage_cb is not None:
            stage_cb("compute", t1 - t0)
            stage_cb("fetch", _time.perf_counter() - t1)
        return result

    # -- fused verify + decode / recover (device) --------------------------

    def verify_and_decode_batch(self, survivors: np.ndarray,
                                present_mask: int, shard_len: int, algo,
                                *, force: str = "", stage_cb=None):
        """Fused device path for the degraded-GET hot loop: ONE program
        bitrot-hashes every survivor shard AND reconstructs only the
        missing data rows (models/pipeline.get_step — the device form of
        cmd/erasure-decode.go:111-150's verify-then-decode).

        survivors: (B, k, S) stacked in missing_data_matrix `used` order.
        Returns (missing (B, r, S), missing_idx, survivor_digests
        (B, k, 32)) as numpy arrays, or None when the batch doesn't route
        to the device / the algorithm has no device kernel / nothing is
        missing (plain verify has no matmul to fuse with).
        """
        import time as _time
        kernel = self._device_hash_kernel(algo)
        if kernel is None:
            return None
        mesh = self._mesh_route(survivors.nbytes, force)
        if mesh is not None:
            from ..parallel import mesh as pmesh
            t0 = _time.perf_counter()
            out = pmesh.mesh_verify_and_decode(
                mesh, survivors, self.k, self.m, present_mask,
                shard_len, kernel)
            if out is not None:
                if stage_cb is not None:
                    stage_cb("compute", _time.perf_counter() - t0)
                return out
        path = force or self._route(survivors.nbytes)
        if path != "device":
            return None
        dm, _used, missing = rs_matrix.missing_data_matrix(
            self.k, self.m, present_mask)
        if not missing:
            return None
        m2 = rs_tpu._bit_expand_cached(dm.tobytes(), dm.shape)
        from ..models.pipeline import get_step
        t0 = _time.perf_counter()
        out, digests = get_step(survivors, m2, dm.shape[0], self.k,
                                shard_len, algo=kernel)
        t1 = self._staged(stage_cb, (out, digests))
        result = np.asarray(out), missing, np.asarray(digests)
        if stage_cb is not None:
            stage_cb("compute", t1 - t0)
            stage_cb("fetch", _time.perf_counter() - t1)
        return result

    def verify_and_recover_batch(self, survivors: np.ndarray,
                                 present_mask: int, rows: "set[int]",
                                 shard_len: int, algo, *,
                                 force: str = "", stage_cb=None):
        """Fused device path for heal: verify survivors, rebuild exactly
        the requested lost rows, and digest the rebuilt shards for their
        new bitrot frames (models/pipeline.heal_step).

        Returns (out (B, R, S), idxs, survivor_digests (B, k, 32),
        out_digests (B, R, 32)) or None when not device-routed.
        """
        import time as _time
        kernel = self._device_hash_kernel(algo)
        if kernel is None:
            return None
        mesh = self._mesh_route(survivors.nbytes, force)
        if mesh is not None:
            from ..parallel import mesh as pmesh
            t0 = _time.perf_counter()
            out = pmesh.mesh_verify_and_recover(
                mesh, survivors, self.k, self.m, present_mask, rows,
                shard_len, kernel)
            if out is not None:
                if stage_cb is not None:
                    stage_cb("compute", _time.perf_counter() - t0)
                return out
        path = force or self._route(survivors.nbytes)
        if path != "device":
            return None
        rec, idxs = self._recover_rows(present_mask, rows)
        if not idxs:
            return None
        m2 = rs_tpu._bit_expand_cached(rec.tobytes(), rec.shape)
        from ..models.pipeline import heal_step
        t0 = _time.perf_counter()
        out, sdig, odig = heal_step(survivors, m2, rec.shape[0], self.k,
                                    shard_len, algo=kernel)
        t1 = self._staged(stage_cb, (out, sdig, odig))
        result = (np.asarray(out), idxs, np.asarray(sdig),
                  np.asarray(odig))
        if stage_cb is not None:
            stage_cb("compute", t1 - t0)
            stage_cb("fetch", _time.perf_counter() - t1)
        return result

    def _recover_rows(self, present_mask: int, rows: "set[int]"
                      ) -> tuple[np.ndarray, list[int]]:
        """Recover matrix filtered to the requested shard rows — the
        row-selection invariant lives in rs_matrix.recover_rows, shared
        with the mesh heal step."""
        return rs_matrix.recover_rows(self.k, self.m, present_mask,
                                      rows)

    # -- batched decode (degraded GET) -------------------------------------

    def decode_stacked(self, survivors: np.ndarray, present_mask: int,
                       *, force: str = "") -> np.ndarray:
        """(B, k, S) survivors — stacked in decode_matrix `used` order —
        -> (B, k, S) data shards. The degraded-GET hot path: a batch of
        blocks sharing one erasure pattern reconstructs in ONE device
        matmul (cmd/erasure-decode.go's per-block ReconstructData,
        batched for the MXU)."""
        path = force or self._route(survivors.nbytes)
        if path == "device":
            return np.asarray(rs_tpu.reconstruct_data(
                survivors, present_mask, self.k, self.m))
        d, _used = rs_matrix.decode_matrix(self.k, self.m, present_mask)
        d = np.asarray(d, dtype=np.uint8)
        if path == "native" and native.available():
            return np.stack([native.gf_matmul(d, s) for s in survivors])
        return np.stack([gf256.gf_matmul(d, s) for s in survivors])

    def recover_stacked(self, survivors: np.ndarray, present_mask: int,
                        rows: "set[int]", *, force: str = ""
                        ) -> tuple[np.ndarray, list[int]]:
        """(B, k, S) survivors (recover_matrix `used` order) -> exactly
        the requested missing shard rows, one batched matmul — the heal
        hot path over many blocks (cmd/erasure-lowlevel-heal.go's
        decode→re-encode collapsed AND batched). Returns (out (B, R, S),
        shard indices for each output row)."""
        rec, idxs = self._recover_rows(present_mask, rows)
        path = force or self._route(survivors.nbytes)
        if path == "device":
            out = np.asarray(rs_tpu.apply_matrix(rec, survivors))
        elif path == "native" and native.available():
            out = np.stack([native.gf_matmul(rec, s) for s in survivors])
        else:
            out = np.stack([gf256.gf_matmul(rec, s) for s in survivors])
        return out, idxs

    # -- reconstruct -------------------------------------------------------

    def reconstruct(self, shards: list[np.ndarray | None],
                    data_only: bool = False, *, force: str = "",
                    rows: Optional[set[int]] = None) -> list[np.ndarray]:
        """Fill in missing (None) shards from >= k survivors.

        shards: length k+m list in shard-index order; returns the full
        list (or just data shards) — reference DecodeDataAndParityBlocks /
        DecodeDataBlocks (cmd/erasure-coding.go:89-112). With `rows`, only
        those shard indices are rebuilt (the heal path's exact-rows form;
        others stay None).
        """
        n = self.k + self.m
        if len(shards) != n:
            raise ValueError("bad shard count")
        present = [i for i, s in enumerate(shards) if s is not None]
        if len(present) < self.k:
            from . import api_errors
            raise api_errors.InsufficientReadQuorum(
                f"{len(present)} shards < k={self.k}")
        wanted = [i for i in range(n) if shards[i] is None
                  and (not data_only or i < self.k)
                  and (rows is None or i in rows)]
        if not wanted:
            return list(shards)  # type: ignore[arg-type]

        mask = sum(1 << i for i in present)
        rec, used, rec_missing = rs_matrix.recover_matrix(self.k, self.m,
                                                          mask)
        keep = [r for r, idx in enumerate(rec_missing) if idx in wanted]
        rec = rec[keep]
        rec_missing = tuple(idx for idx in rec_missing if idx in wanted)
        stacked = np.stack([shards[i] for i in used])
        path = force or self._route(stacked.nbytes)
        if path == "device":
            out = np.asarray(rs_tpu.apply_matrix(np.asarray(rec), stacked))
        elif path == "native" and native.available():
            out = native.gf_matmul(np.asarray(rec, dtype=np.uint8), stacked)
        else:
            out = gf256.gf_matmul(np.asarray(rec, dtype=np.uint8), stacked)
        result = list(shards)
        for row, idx in enumerate(rec_missing):
            result[idx] = out[row]
        return result  # type: ignore[return-value]
