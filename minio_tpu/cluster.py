"""Cluster assembly — boot a node into a runnable (multi-)node system.

The reference's serverMain (cmd/server-main.go:371-533): parse endpoints,
mount the internode RPC routers (storage/lock/peer/bootstrap) on the same
HTTP server that serves S3, verify cluster config against peers, assemble
the ObjectLayer from local + remote drives (waitForFormatErasure), swap
the namespace lock for dsync when distributed, and start the S3 API.

A node's own drives are local XLStorage objects (also exported over
storage RPC for peers); every other node's drives are RemoteStorage
clients. The drive order is the endpoint order, identical on every node,
so each drive occupies the same erasure-set slot cluster-wide.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Optional

from .distributed import membership
from .distributed.local_locker import LocalLocker
from .distributed.lock_rpc import LockRPCClient, LockRPCServer
from .distributed.peer_rpc import (BootstrapRPCServer, NotificationSys,
                                   PeerRPCClient, PeerRPCServer,
                                   verify_server_system_config)
from .distributed.storage_rpc import RemoteStorage, StorageRPCServer
from .distributed.dsync import DistNSLockMap
from .object.nslock import NSLockMap
from .object.sets import ErasureSets
from .object.server_sets import ErasureServerSets
from .s3.credentials import Credentials
from .s3.server import S3Server
from .storage import errors as serr
from .storage.xl_storage import XLStorage
from .utils import ellipses, knobs


@dataclasses.dataclass
class NodeSpec:
    """One node: where it listens and which drive paths it owns."""
    host: str
    port: int
    drives: list[str]

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"


def parse_node_arg(arg: str) -> NodeSpec:
    """"host:port=/d{1...4}" or "host:port=/a,/b" -> NodeSpec."""
    addr, _, paths = arg.partition("=")
    if not paths:
        raise ValueError(f"node arg needs host:port=drives, got {arg!r}")
    host, _, port = addr.rpartition(":")
    drives = []
    for p in paths.split(","):
        drives.extend(ellipses.expand_arg(p))
    return NodeSpec(host or "127.0.0.1", int(port), drives)


class ClusterNode:
    """One running node: S3 endpoint + internode RPC + object layer."""

    def __init__(self, nodes: list[NodeSpec], this: int,
                 creds: Credentials, parity: Optional[int] = None,
                 set_drive_count: int = 0, block_size: int = 1 << 22,
                 region: str = "us-east-1", iam=None,
                 bootstrap_timeout: float = 30.0,
                 format_timeout: float = 30.0,
                 certfile: Optional[str] = None,
                 keyfile: Optional[str] = None):
        self._tls = (certfile, keyfile)
        self.nodes = nodes
        self.this = this
        self.creds = creds
        self.spec = nodes[this]
        self.distributed = len(nodes) > 1
        # partition-tolerance plane identity: this process speaks as
        # spec.addr; every RPC carries it + the boot generation so
        # peers can fence stale per-peer state after a restart.
        # (In-process multi-node tests boot several ClusterNodes per
        # process — their handlers/clients carry explicit node_ids
        # below, which win over this process-level fallback.)
        membership.set_local_node(self.spec.addr)

        all_drives = [(ni, path) for ni, n in enumerate(nodes)
                      for path in n.drives]
        total = len(all_drives)
        node_counts = [len(n.drives) for n in nodes]
        if set_drive_count:
            if total % set_drive_count:
                raise ValueError("drives not divisible into sets")
            set_count = total // set_drive_count
        else:
            set_count, set_drive_count = ellipses.divide_into_sets(
                total, node_counts)
        if parity is None:
            parity = set_drive_count // 2   # reference default EC:N/2
        self.set_count, self.set_drive_count = set_count, set_drive_count
        self.parity = parity

        # -- local drives + RPC servers on this node's listener ------------
        self.local_drives: dict[str, XLStorage] = {}
        for path in self.spec.drives:
            try:
                self.local_drives[path] = XLStorage(path)
            except serr.StorageError:
                pass
        self.locker = LocalLocker()
        ak, sk = creds.access_key, creds.secret_key
        self._storage_rpc = StorageRPCServer(self.local_drives, ak, sk)
        self._storage_rpc.handler.node_id = self.spec.addr
        self._lock_rpc = LockRPCServer(self.locker, ak, sk)
        self._lock_rpc.handler.node_id = self.spec.addr
        self._peer_rpc = PeerRPCServer(ak, sk, node_id=self.spec.addr)
        endpoints = [f"{n.addr}{p}" for n in nodes for p in n.drives]
        self._bootstrap_rpc = BootstrapRPCServer(ak, sk, endpoints)
        self._bootstrap_rpc.handler.node_id = self.spec.addr

        # the S3 server carries every router (reference configureServerHandler)
        self.s3: Optional[S3Server] = None
        self.sets = None
        self._remote_clients: list[RemoteStorage] = []
        self._lock_clients: list[LockRPCClient] = []
        self._peer_clients: list[PeerRPCClient] = []
        self._start_server(region, iam)
        try:
            self._finish_boot(nodes, this, all_drives, endpoints, ak, sk,
                              set_count, set_drive_count, parity,
                              block_size, bootstrap_timeout,
                              format_timeout)
        except BaseException:
            # a failed boot must not leak the already-listening server /
            # RPC clients into the process (shutdown is idempotent and
            # tolerant of the partially-built state)
            self.shutdown()
            raise

    def _finish_boot(self, nodes, this, all_drives, endpoints, ak, sk,
                     set_count, set_drive_count, parity, block_size,
                     bootstrap_timeout, format_timeout) -> None:
        # -- bootstrap verify against peers --------------------------------
        peers = [(n.host, n.port) for i, n in enumerate(nodes)
                 if i != this]
        if peers:
            verify_server_system_config(
                peers, endpoints, ak, sk,
                retries=max(int(bootstrap_timeout), 1))

        # -- assemble the drive list in global endpoint order --------------
        drives: list = []
        for ni, path in all_drives:
            if ni == this:
                drives.append(self.local_drives.get(path))
            else:
                rc = RemoteStorage(nodes[ni].host, nodes[ni].port, path,
                                   ak, sk)
                rc.rc.node_id = self.spec.addr
                self._remote_clients.append(rc)
                drives.append(rc)

        # -- namespace lock: dsync across every node when distributed ------
        if self.distributed:
            lockers: list = []
            for i, n in enumerate(nodes):
                if i == this:
                    lockers.append(self.locker)
                else:
                    lc = LockRPCClient(n.host, n.port, ak, sk)
                    lc.rc.node_id = self.spec.addr
                    self._lock_clients.append(lc)
                    lockers.append(lc)
            ns_lock = DistNSLockMap(lockers, owner=self.spec.addr)
        else:
            ns_lock = NSLockMap()

        # -- cross-request device batch former + RAM-budgeted admission ----
        from .parallel import pipeline as _pipeline
        from .parallel.scheduler import BatchScheduler, requests_budget
        self.scheduler = BatchScheduler()
        budget = requests_budget(block_size, set_drive_count)
        self.s3.api.set_max_clients(budget)
        # staging rings sized from the SAME admission budget (each
        # admitted stream keeps ~2 batches in flight), not a flat
        # 2×cores guess
        _pipeline.configure_pool_buffers(budget)

        # -- format bootstrap (waitForFormatErasure) -----------------------
        deadline = time.monotonic() + format_timeout
        while True:
            try:
                sets = ErasureSets.from_storage(
                    drives, set_count, set_drive_count, parity,
                    block_size=block_size, ns_lock=ns_lock,
                    create_format=(this == 0),
                    scheduler=self.scheduler)
                break
            except serr.StorageError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.5)
        self.sets = sets
        # distributed clusters are single-pool (expansion/decommission
        # are the single-node surface today): skip the boot-time
        # cluster-wide topology read — during a concurrent multi-node
        # boot it races peers still formatting and can trip a remote
        # drive's offline backoff for nothing (the default map is
        # all-active, which is exactly a 1-pool cluster's only state)
        self.object_layer = ErasureServerSets(
            [sets], load_topology=not self.distributed)
        self.s3.api.set_object_layer(self.object_layer)
        self._block_size = block_size
        # a drain interrupted by a restart resumes from its persisted
        # checkpoint (the pool is still marked draining in the topology
        # epoch doc) instead of starting over
        try:
            self.object_layer.resume_rebalance_if_pending()
        except Exception:  # noqa: BLE001 — boot must proceed; the
            # admin rebalance endpoint can restart the drain manually
            pass

        # -- IAM over the object layer (erasure-coded identity store) ------
        if self.s3.api.iam is None:
            from .iam import IAMSys
            self.s3.api.iam = IAMSys(self.object_layer,
                                     root_cred=self.creds)
        self.iam = self.s3.api.iam
        self.iam.bucket_policy_lookup = \
            lambda b: self.s3.api.bucket_meta.get(b).policy_json

        # -- peer control plane hooks --------------------------------------
        self._peer_clients = [PeerRPCClient(n.host, n.port, ak, sk,
                                            node_id=self.spec.addr)
                              for i, n in enumerate(nodes) if i != this]
        self.notification = NotificationSys(self._peer_clients)
        # generation fencing, cluster edition: a peer that restarted
        # (new boot generation) invalidated every grant/subscription it
        # held for us — transport already clears its healthtrack windows
        # and offline marker (import-time listener); here the cluster
        # drops cached replication wire clients so the next replication
        # op reconnects instead of riding a dead session
        def _on_peer_restart(peer: str, _old: int, _new: int) -> None:
            if self.s3 is None:        # shut down: stale listener, no-op
                return
            targets = getattr(self, "repl_targets", None)
            if targets is not None:
                with targets._mu:
                    targets._clients.clear()
            try:
                self.console.log_line(
                    "INFO", f"peer {peer} restarted (new generation); "
                    "stale per-peer state reset")
            except Exception:  # noqa: BLE001 — console not up yet
                pass

        membership.TRACKER.add_listener(_on_peer_restart)
        self._peer_rpc.get_locks = self.locker.dump
        self._peer_rpc.get_server_info = lambda: {
            "addr": self.spec.addr,
            "sets": self.set_count,
            "drives_per_set": self.set_drive_count,
        }
        self._peer_rpc.reload_bucket_metadata = \
            lambda b: self.s3.api.bucket_meta.reload(b)
        self.s3.api.bucket_meta.on_change = \
            lambda b: self.notification.reload_bucket_metadata(b)
        self._peer_rpc.reload_iam = self.iam.load
        self._peer_rpc.apply_iam_delta = self.iam.apply_delta
        self.iam.on_change = self.notification.reload_iam
        self.iam.on_delta = self.notification.iam_delta
        # bounded staleness: a delta lost to a transient partition (the
        # sender's per-peer reload fallback failing too) must not
        # diverge this node forever — refresh the whole cache on an
        # interval like the reference's IAM refresh loop
        refresh_s = knobs.get_float("MINIO_TPU_IAM_REFRESH_S")
        self._iam_refresh_stop = threading.Event()

        def _iam_refresh_loop():
            while not self._iam_refresh_stop.wait(refresh_s):
                try:
                    self.iam.load()
                except Exception:  # noqa: BLE001 — retry next tick
                    pass

        threading.Thread(target=_iam_refresh_loop, daemon=True).start()
        self._peer_rpc.get_storage_info = self.object_layer.storage_info
        self._peer_rpc.get_trace = \
            lambda: list(self.s3.api.trace.recent)
        self._peer_rpc.get_bucket_usage = \
            lambda: (self.crawler.usage
                     if getattr(self, "crawler", None) is not None
                     else {})
        self._peer_rpc.obd_drive_paths = list(self.spec.drives)
        self._peer_rpc.get_bandwidth = \
            lambda: self.s3.api.bandwidth.report()
        # console-log ring: name this node's singleton so merged
        # cluster logs attribute lines to their origin
        from .utils.console import get_console
        self.console = get_console()
        self.console.node = self.spec.addr
        self.console.log_line("INFO", f"node {self.spec.addr} online")

        # -- admin / health / metrics routers ------------------------------
        from .s3.admin import mount_admin
        self.admin = mount_admin(self.s3, self)
        # cluster observability plane: trace records carry this node's
        # name, peers pull the full Prometheus exposition for the
        # federated ?cluster=1 scrape, and follow-mode trace streams
        # subscribe to this node's live hub over the trace-stream verb
        self.s3.api.trace.node = self.spec.addr
        self._peer_rpc.get_metrics_text = self.admin.metrics.local_text
        self._peer_rpc.trace_hub = self.s3.api.trace.hub

        # -- incident plane: event journal, SLO engine, flight recorder ----
        # the journal persists under the first local drive (like the
        # event-notifier backlog) so transitions survive a restart;
        # the flight recorder subscribes to it and snapshots
        # postmortem state on trigger events
        from .distributed import membership as _membership
        from .utils import eventlog, healthtrack, incidents, slo
        if self.spec.drives:
            eventlog.JOURNAL.attach(
                os.path.join(self.spec.drives[0], ".minio.sys",
                             "eventlog"),
                node=self.spec.addr)
            incidents.RECORDER.attach(
                os.path.join(self.spec.drives[0], ".minio.sys",
                             "incidents"))
        if knobs.get_bool("MINIO_TPU_SLO"):
            slo.ENGINE.ensure_started()
        incidents.RECORDER.add_provider(
            "healthtrack", lambda: {
                "drives": healthtrack.TRACKER.snapshot("drive"),
                "peers": healthtrack.TRACKER.snapshot("peer")})
        incidents.RECORDER.add_provider(
            "membership", _membership.TRACKER.snapshot)
        incidents.RECORDER.add_provider("slo", slo.ENGINE.status)
        incidents.RECORDER.add_provider(
            "topology",
            lambda: self.object_layer.topology.to_dict()
            if getattr(self.object_layer, "topology", None) is not None
            else {})
        self._peer_rpc.event_hub = eventlog.JOURNAL.hub
        self._peer_rpc.get_events = \
            lambda: eventlog.JOURNAL.recent(500)
        self._peer_rpc.list_incidents = incidents.RECORDER.list
        self._peer_rpc.get_incident = incidents.RECORDER.get

        # -- web JSON-RPC control surface (cmd/web-router.go) --------------
        from .s3.web import mount as mount_web
        self.web = mount_web(self.s3)

        # -- config KV (newAllSubsystems ConfigSys + lookupConfigs) --------
        from .config import ConfigSys
        self.config = ConfigSys(self.object_layer, secret=sk)
        self.s3.api.config = self.config

        # -- bucket federation over etcd DNS (cmd/etcd.go) -----------------
        etcd_ep = self.config.get("etcd", "endpoints")
        fed_domain = self.config.get("etcd", "domain")
        if etcd_ep and fed_domain:
            from .distributed.etcd import EtcdClient
            from .features.federation import BucketFederation
            try:
                etcd_client = EtcdClient(etcd_ep.split(",")[0].strip())
                fed = BucketFederation(
                    etcd_client,
                    fed_domain, self.spec.host, self.spec.port,
                    cluster_addrs=[(n.host, n.port)
                                   for n in self.nodes])
                self.s3.api.federation = fed
                # reference initFederatorBackend: buckets that predate
                # federation (or an etcd restore) get re-registered
                fed.register_existing(self.object_layer)
                # etcd configured => IAM moves to the etcd store
                # (cmd/iam-etcd-store.go): users/policies/service
                # accounts created on ANY federated cluster are
                # visible to all of them; identities that predate etcd
                # are seeded into it on first switch
                from .iam.store import EtcdIAMStore
                self.iam.migrate_to_store(EtcdIAMStore(etcd_client))
            except ValueError:
                pass              # bad endpoint: federation stays off

        # -- live bucket features (events, replication, lifecycle) ---------
        from .features import EventNotifier
        from .features.lifecycle import (crawler_action, mpu_abort_action,
                                         noncurrent_sweep_action)
        # durable event backlog lives under the node's first local
        # drive (queuestore.go semantics: pending events survive a
        # process restart)
        _evq = os.path.join(self.spec.drives[0], ".minio.sys", "events") \
            if self.spec.drives else None
        self.events = EventNotifier(self.s3.api.bucket_meta,
                                    queue_dir=_evq)
        self.s3.api.events = self.events
        # active-active replication plane (minio_tpu/replicate/): the
        # epoch-versioned target registry recovers from every pool
        # (highest epoch wins — targets survive decommission), the
        # plane rides the engine namespace-change feed so EVERY
        # mutation verb reaches the replication queue
        from .replicate import ReplicationPlane, TargetRegistry
        self.repl_targets = TargetRegistry(self.object_layer)
        try:
            if not self.repl_targets.load():
                # first boot: persist the minted site id so replicas
                # pushed before and after a restart carry ONE origin
                self.repl_targets.save()
        except Exception:  # noqa: BLE001 — boot proceeds; admin re-adds
            pass
        self.replication = ReplicationPlane(self.object_layer,
                                            self.repl_targets,
                                            bucket_meta=self.s3.api.
                                            bucket_meta)
        self.replication.bandwidth = self.s3.api.bandwidth
        self.object_layer.attach_replication(self.replication)
        try:
            buckets = [v.name for v in self.object_layer.list_buckets()]
        except Exception as e:  # noqa: BLE001 — boot must proceed, but
            # an unlistable namespace leaves targets unmounted: say so
            self.console.log_line(
                "ERROR", f"replication target mount skipped: {e}")
            buckets = []
        # legacy bucket-metadata remote targets mount into the registry
        for b in buckets:
            try:
                for entry in self.s3.api.bucket_meta.get(
                        b).replication_targets:
                    entry = dict(entry, source_bucket=b)
                    self.replication.mount_target_entry(entry)
            except Exception:  # noqa: BLE001 — per-bucket best effort
                continue
        # service restart/stop: peers run the same local action the
        # admin endpoint runs — DEFERRED so the RPC reply reaches the
        # broadcaster before this process exec-restarts
        import threading as _threading
        self._peer_rpc.signal_service = \
            lambda sig: _threading.Timer(
                0.2, self.admin.service_action, (sig,)).start()
        self.s3.api.replication = self.replication
        # apply stored/env config to the live subsystems
        self.config.apply(self.s3.api, events=self.events,
                          trace=self.s3.api.trace)

        # -- bucket event notification plane (minio_tpu/notify/) -----------
        # same epoch-versioned every-pool registry rule as replication
        # targets; the plane rides the SAME namespace feed, so every
        # mutation verb reaches the delivery queue. Durable per-target
        # backlog lives beside the legacy event queue on the first
        # local drive (pending events survive a restart).
        from .notify import NotificationPlane, NotifyTargetRegistry
        self.notify_targets = NotifyTargetRegistry(self.object_layer)
        try:
            self.notify_targets.load()
        except Exception:  # noqa: BLE001 — boot proceeds; admin re-adds
            pass
        _nq = os.path.join(self.spec.drives[0], ".minio.sys", "notify",
                           "queue") if self.spec.drives else None
        self.notify_plane = NotificationPlane(
            self.object_layer, self.notify_targets,
            bucket_meta=self.s3.api.bucket_meta,
            queue_dir=_nq, node=self.spec.addr,
            nodes=[n.addr for n in nodes],
            site_id=self.repl_targets.site_id)
        # owner-node delivery: non-owners hand the event to the
        # bucket's owner over the peer control plane (no double-fire
        # on multi-node clusters); peers' registries reload on admin
        # target mutations so a target added at any node serves on all
        _npeers = {p.addr: p for p in self._peer_clients}
        self.notify_plane.forward_fn = \
            lambda addr, b, k: (addr in _npeers
                                and _npeers[addr].notify_event(b, k))
        self._peer_rpc.notify_event = self.notify_plane.ingest
        self._peer_rpc.notify_reload = self.notify_targets.load
        self.notify_plane.reload_peers = self.notification.notify_reload
        self.object_layer.attach_notifications(self.notify_plane)
        self.s3.api.notify = self.notify_plane

        # -- tiering plane (remote tiers + ILM transitions) ----------------
        from .tier.config import TierManager
        self.tiers = TierManager(self.object_layer)
        try:
            self.tiers.load()
        except Exception:  # noqa: BLE001 — boot proceeds; admin re-adds
            pass
        self.s3.api.tiers = self.tiers

        # -- QoS budget registry (s3/qos.py) -------------------------------
        # same every-pool persistence rule as tiers: recover the newest
        # budget doc; a missing/torn doc just means default budgets
        self.s3.api.qos.registry.obj = self.object_layer
        try:
            self.s3.api.qos.registry.load()
        except Exception:  # noqa: BLE001 — boot proceeds on defaults
            pass

        # -- boot-time crash-consistency audit (object/fsck.py) ------------
        # MINIO_TPU_FSCK_BOOT=on: audit every pool and repair what the
        # last crash left behind (tmp garbage, orphan data dirs, torn
        # registry copies) BEFORE the scanners/index start trusting the
        # tree; repairable findings run the same heal/delete verbs the
        # admin fsck endpoint uses
        if this == 0 and knobs.get_bool("MINIO_TPU_FSCK_BOOT"):
            from .object.fsck import run_fsck
            try:
                rep = run_fsck(self.object_layer, repair=True,
                               tiers=self.tiers)
                if not rep.clean:
                    self.console.log_line(
                        "INFO", f"boot fsck: found {rep.counts()}, "
                        f"repaired {rep.repaired_counts()}, "
                        f"unrepaired {len(rep.unrepaired)}")
            except Exception as e:  # noqa: BLE001 — boot must proceed;
                # the admin endpoint can rerun the audit on demand
                self.console.log_line("ERROR", f"boot fsck failed: {e}")

        # -- bucket metacache (persisted listing index + scanner feed) -----
        from .object.metacache import MetacacheManager
        from .object import metacache as _mc
        self.metacache = None
        if _mc.enabled() and not self.distributed:
            # single-node clusters only today: deltas are engine-local,
            # so writes through a PEER's S3 endpoint would never feed
            # this node's journal — distributed nodes keep the
            # merge-walk (README "Listing and the bucket metacache")
            self.metacache = MetacacheManager(self.object_layer).start()
            self.object_layer.attach_metacache(self.metacache)

        # -- device scan plane (TPU-offloaded S3 Select) -------------------
        # wire the handler's ScanEngine onto the shared batch former:
        # concurrent SelectObjectContent requests coalesce their pages
        # into single device launches (fourth verb of the scheduler);
        # same instance, so its serve/fallback stats stay continuous
        self.s3.api.scan.scheduler = self.scheduler

        # -- hot-object read cache in front of the erasure path ------------
        from .object import cache as _cache
        self.read_cache = None
        if _cache.enabled() and self.spec.drives:
            default_dir = os.path.join(self.spec.drives[0],
                                       ".minio.sys", "cache")
            self.read_cache = _cache.CacheObjects.from_env(
                self.object_layer, default_dir)
            # invalidation rides the namespace feed; the S3 surface
            # serves THROUGH the wrapper (GET/Select hits skip the
            # erasure decode path entirely); background planes keep
            # the raw layer — they must never populate the cache
            self.object_layer.attach_read_cache(self.read_cache)
            self.s3.api.set_object_layer(self.read_cache)

        # -- background plane (initAutoHeal + initDataCrawler) -------------
        from .object.background import (DataUsageCrawler, DiskMonitor,
                                        HealScanner)
        from .object.update_tracker import DataUpdateTracker
        self.disk_monitor = DiskMonitor(sets).start()
        # data-update tracker: every mutation marks the bloom; the heal
        # scanner prunes unchanged work (cmd/data-update-tracker.go)
        _tpath = os.path.join(self.spec.drives[0], ".minio.sys",
                              "tracker", "update-tracker.bin") \
            if self.spec.drives else ""
        self.update_tracker = DataUpdateTracker(_tpath)
        self.s3.api.update_tracker = self.update_tracker
        self._peer_rpc.get_update_tracker = \
            self.update_tracker.rotate_snapshot
        self.heal_scanner = None
        self.crawler = None
        self.transition_worker = None
        if this == 0:
            self.heal_scanner = HealScanner(
                self.object_layer, self.update_tracker,
                peer_snapshots=self.notification.tracker_rotate_all
            ).start()
            # one transition worker per cluster, riding the same
            # crawler cadence lifecycle expiry does: Transition rules
            # enqueue moves, the worker drains them throttled off
            # foreground pressure
            from .tier.transition import (TransitionWorker,
                                          noncurrent_transition_action,
                                          restore_reclaim_action,
                                          transition_action)
            self.transition_worker = TransitionWorker(
                self.object_layer, self.tiers)
            # per-tier push budgets come from the QoS registry's
            # "tier" scope (same doc shape the tenant budgets use)
            self.transition_worker.budget_lookup = \
                lambda name: self.s3.api.qos.registry.get("tier", name)
            self.transition_worker.start()
            # async RestoreObject (202 + background pull) rides the
            # same worker, throttled with the transitions
            self.s3.api.restore_worker = self.transition_worker
            # one crawler per cluster (first node), like the reference's
            # leader-ish crawler cadence; usage cache feeds quota and the
            # crawler enforces lifecycle expiry + ILM transitions
            self.crawler = DataUsageCrawler(
                self.object_layer,
                actions=[crawler_action(self.s3.api.bucket_meta,
                                        self.object_layer,
                                        self.events, tiers=self.tiers),
                         transition_action(self.s3.api.bucket_meta,
                                           self.transition_worker),
                         restore_reclaim_action(self.object_layer,
                                                self.tiers)],
                bucket_actions=[
                    mpu_abort_action(self.s3.api.bucket_meta,
                                     self.object_layer),
                    noncurrent_sweep_action(self.s3.api.bucket_meta,
                                            self.object_layer,
                                            tiers=self.tiers),
                    noncurrent_transition_action(
                        self.s3.api.bucket_meta,
                        self.transition_worker),
                ]).start()
            self.s3.api.usage = self.crawler

    # ------------------------------------------------------------------
    # topology: online pool expansion
    # ------------------------------------------------------------------

    def add_pool(self, drive_roots: list[str],
                 set_drive_count: int = 0,
                 parity: Optional[int] = None) -> int:
        """Append one pool of LOCAL drives to the running node (online
        expansion; single-node form of upstream's server-pool list).
        Bumps+persists the placement epoch; new writes immediately
        weigh the new capacity. Returns the new pool index."""
        paths = ellipses.expand_args(list(drive_roots))
        if set_drive_count:
            if len(paths) % set_drive_count:
                raise ValueError("drives not divisible into sets")
            set_count = len(paths) // set_drive_count
        else:
            set_count, set_drive_count = ellipses.divide_into_sets(
                len(paths), [len(paths)])
        if parity is None:
            parity = set_drive_count // 2
        sets = ErasureSets.from_drives(
            paths, set_count, set_drive_count, parity,
            block_size=self._block_size, scheduler=self.scheduler)
        idx = self.object_layer.add_pool(sets)
        # the running DiskMonitor must cover the new pool's drives too:
        # a drive dying in a post-boot pool re-admits/heals exactly like
        # a boot-time one (ROADMAP follow-up from the topology PR)
        if getattr(self, "disk_monitor", None) is not None:
            self.disk_monitor.add_pool(sets)
        for p in paths:
            if p not in self.local_drives:
                try:
                    self.local_drives[p] = XLStorage(p)
                except serr.StorageError:
                    pass
        self.console.log_line(
            "INFO", f"pool {idx} added ({len(paths)} drives, "
            f"epoch {self.object_layer.topology.epoch})")
        return idx

    # ------------------------------------------------------------------

    def _start_server(self, region: str, iam) -> None:
        certfile, keyfile = getattr(self, "_tls", (None, None))
        self.s3 = S3Server(None, address=self.spec.host,
                           port=self.spec.port, region=region,
                           creds=self.creds, iam=iam,
                           certfile=certfile, keyfile=keyfile)
        self.s3.register_router("/minio/storage/",
                                self._storage_rpc.route)
        self.s3.register_router("/minio/lock/", self._lock_rpc.route)
        self.s3.register_router("/minio/peer/", self._peer_rpc.route)
        self.s3.register_router("/minio/bootstrap/",
                                self._bootstrap_rpc.route)
        self.s3.start()

    @property
    def url(self) -> str:
        return self.s3.url

    def shutdown(self) -> None:
        """Idempotent; safe on a partially-booted node."""
        if getattr(self, "_iam_refresh_stop", None) is not None:
            self._iam_refresh_stop.set()
        # persist the journal tail (flush, not close: in-process test
        # clusters share the process-global journal across nodes)
        from .utils import eventlog
        try:
            eventlog.JOURNAL.flush()
        except Exception:  # noqa: BLE001 — best-effort on the way down
            pass
        if getattr(self, "disk_monitor", None) is not None:
            self.disk_monitor.close()
            self.disk_monitor = None
        if getattr(self, "crawler", None) is not None:
            self.crawler.close()
            self.crawler = None
        if getattr(self, "transition_worker", None) is not None:
            self.transition_worker.close()
            self.transition_worker = None
        if getattr(self, "heal_scanner", None) is not None:
            self.heal_scanner.close()
            self.heal_scanner = None
        if getattr(self, "metacache", None) is not None:
            self.metacache.close()
            self.metacache = None
        if getattr(self, "update_tracker", None) is not None:
            try:
                self.update_tracker.flush()
            except Exception:  # noqa: BLE001 — hints only
                pass
            self.update_tracker = None
        if getattr(self, "events", None) is not None:
            self.events.close()
            self.events = None
        if getattr(self, "replication", None) is not None:
            self.replication.close()
            self.replication = None
        if getattr(self, "notify_plane", None) is not None:
            self.notify_plane.close()
            self.notify_plane = None
        if getattr(self, "scheduler", None) is not None:
            self.scheduler.close()
            self.scheduler = None
        if self.s3 is not None:
            try:
                self.s3.stop()
            except Exception:  # noqa: BLE001 — already stopped
                pass
            self.s3 = None
        if self.sets is not None:
            self.sets.close()
            self.sets = None
        self._lock_rpc.close()
        for c in self._remote_clients:
            c.close()
        self._remote_clients = []
        for c in self._lock_clients:
            c.close()
        self._lock_clients = []
        for c in self._peer_clients:
            c.close()
        self._peer_clients = []


def start_node(nodes: list[NodeSpec], this: int, creds: Credentials,
               **kw) -> ClusterNode:
    """Boot node `this` of a cluster described by `nodes`."""
    return ClusterNode(nodes, this, creds, **kw)


def start_single(drives: list[str], address: str = "127.0.0.1",
                 port: int = 0, creds: Optional[Credentials] = None,
                 **kw) -> ClusterNode:
    """Single-node server over local drives (reference `minio server
    /data/d{1...16}`)."""
    from .s3.credentials import global_credentials
    creds = creds or global_credentials()
    paths = ellipses.expand_args(drives)
    spec = NodeSpec(address, port, paths)
    return ClusterNode([spec], 0, creds, **kw)


class FSNode:
    """Single-directory FS-backend server (reference newObjectLayer's
    one-endpoint branch, cmd/server-main.go:524-532): no erasure, plain
    file tree, full S3 surface."""

    def __init__(self, root: str, address: str = "127.0.0.1",
                 port: int = 0, creds: Optional[Credentials] = None,
                 region: str = "us-east-1"):
        from .object.fs import FSObjects
        from .s3.credentials import global_credentials
        from .s3.admin import mount_admin
        from .iam import IAMSys
        self.creds = creds or global_credentials()
        self.object_layer = FSObjects(root)
        iam = IAMSys(self.object_layer, root_cred=self.creds)
        self.s3 = S3Server(self.object_layer, address=address, port=port,
                           region=region, creds=self.creds, iam=iam)
        self.iam = iam
        iam.bucket_policy_lookup = \
            lambda b: self.s3.api.bucket_meta.get(b).policy_json
        mount_admin(self.s3)
        self.s3.start()

    @property
    def url(self) -> str:
        return self.s3.url

    def shutdown(self) -> None:
        self.s3.stop()


def start_fs(root: str, address: str = "127.0.0.1", port: int = 0,
             creds: Optional[Credentials] = None, **kw) -> FSNode:
    return FSNode(root, address, port, creds, **kw)
