"""Flagship device pipelines: the "models" of this framework.

Where an ML framework has model families, an object store has data-path
pipelines. Each is a jittable function over batched shard tensors:

  * EncodePipeline  — PUT hot loop: batch of blocks -> parity shards (+
    per-shard bitrot digests). The device analog of the reference's
    Erasure.Encode loop (cmd/erasure-encode.go:75-146).
  * DecodePipeline  — GET-with-failures: survivor shards -> data shards
    (cmd/erasure-decode.go Reconstruct semantics).
  * HealPipeline    — decode->reencode in one matmul via the recover
    matrix (cmd/erasure-lowlevel-heal.go:28-48 collapsed to a single
    device op).

All pipelines are shape-static per (k, m, S, B) and cached; the batch
scheduler (parallel/scheduler.py) routes variable traffic into a small set
of bucketed shapes so XLA compiles each program once.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import rs_matrix, rs_tpu


@dataclasses.dataclass(frozen=True)
class ECConfig:
    """Erasure-set geometry: k data + m parity shards over blockSize-byte
    blocks (reference defaults: block 4 MiB; this framework benches 1 MiB
    per BASELINE config). Placement math delegates to
    storage.datatypes.ErasureInfo so there is exactly one copy of the
    cmd/erasure-coding.go:120-143 formulas."""
    data_shards: int
    parity_shards: int
    block_size: int = 1 << 20

    @property
    def total_shards(self) -> int:
        return self.data_shards + self.parity_shards

    def _erasure_info(self):
        from ..storage.datatypes import ErasureInfo
        return ErasureInfo(data_blocks=self.data_shards,
                           parity_blocks=self.parity_shards,
                           block_size=self.block_size)

    @property
    def shard_size(self) -> int:
        """Per-shard bytes of one full block (ceil division, zero-padded:
        same split semantics as the reference codec)."""
        return self._erasure_info().shard_size()

    def shard_file_size(self, total_length: int) -> int:
        return self._erasure_info().shard_file_size(total_length)

    def shard_file_offset(self, start: int, length: int, total: int) -> int:
        return self._erasure_info().shard_file_offset(start, length, total)


# ---------------------------------------------------------------------------
# Encode
# ---------------------------------------------------------------------------

def encode_blocks(data: jax.Array | np.ndarray, cfg: ECConfig,
                  *, use_pallas: bool | None = None) -> jax.Array:
    """(B, k, S) data shards -> (B, m, S) parity shards on device."""
    return rs_tpu.apply_matrix(
        np.asarray(rs_matrix.parity_matrix(cfg.data_shards,
                                           cfg.parity_shards)),
        data, use_pallas=use_pallas)


def encode_blocks_full(data, cfg: ECConfig, *,
                       use_pallas: bool | None = None) -> jax.Array:
    """(B, k, S) -> (B, n, S): data with parity appended (GET-comparable
    to the host oracle byte-for-byte)."""
    data = jnp.asarray(data, jnp.uint8)
    parity = encode_blocks(data, cfg, use_pallas=use_pallas)
    return jnp.concatenate([data, parity], axis=-2)


# ---------------------------------------------------------------------------
# Decode / heal
# ---------------------------------------------------------------------------

def decode_blocks(survivors, present_mask: int, cfg: ECConfig,
                  *, use_pallas: bool | None = None) -> jax.Array:
    """(B, k, S) stacked survivor shards (in decode_matrix `used` order)
    -> (B, k, S) data shards."""
    return rs_tpu.reconstruct_data(
        survivors, present_mask, cfg.data_shards, cfg.parity_shards,
        use_pallas=use_pallas)


def heal_blocks(survivors, present_mask: int, cfg: ECConfig,
                *, use_pallas: bool | None = None) -> jax.Array:
    """(B, k, S) survivors -> (B, |missing|, S): exactly the lost shards,
    one fused matmul (decode+reencode collapsed)."""
    return rs_tpu.recover_missing(
        survivors, present_mask, cfg.data_shards, cfg.parity_shards,
        use_pallas=use_pallas)


# ---------------------------------------------------------------------------
# The flagship jittable step (what __graft_entry__.entry() exposes)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5))
def put_step(data: jax.Array, k: int, m: int, shard_len: int = 0,
             key: bytes = b"", algo: str = "highwayhash"
             ) -> tuple[jax.Array, jax.Array]:
    """One PUT device step: RS-encode a batch of blocks AND compute each
    shard's streaming-bitrot digest — the full reference per-block PUT
    work (cmd/erasure-encode.go:75-146 + cmd/bitrot-streaming.go:46-58)
    as one device program.

    data: (B, k, S) uint8 data shards. S may include right zero-padding
    (GF coding is column-independent, so padded columns encode to zeros);
    shard_len (< = S, default S) is the true shard byte-length the bitrot
    digests must cover. algo: "highwayhash" (keyed HH256, the default
    bitrot) or "sha256".
    Returns (parity (B, m, S) uint8, digests (B, k+m, 32) uint8 in shard
    order data-then-parity) — byte-identical to the CPU bitrot path
    (minio_tpu/bitrot.py). The caller already holds the data rows, so
    only parity + digests cross back to the host.
    """
    from ..bitrot import MAGIC_HIGHWAYHASH_KEY
    b, k_, s = data.shape
    assert k_ == k
    shard_len = shard_len or s
    pm = np.asarray(rs_matrix.parity_matrix(k, m))
    m2 = rs_tpu._bit_expand_cached(pm.tobytes(), pm.shape)
    parity = rs_tpu._apply_matrix_impl(
        jnp.asarray(m2), data, m, k, rs_tpu.default_use_pallas())

    # one hash scan over data+parity rows together: splitting into two
    # scans measures slower (the small parity-only scan underfills the
    # vector lanes and doubles loop overhead)
    rows = jnp.concatenate([data, parity], axis=-2).reshape(b * (k + m), s)
    if algo == "sha256":
        from ..ops import sha256_jax
        digests = sha256_jax._sha256_impl(rows, shard_len)
    else:
        from ..ops import highwayhash_jax
        digests = highwayhash_jax._hh256_impl(
            rows, shard_len, bytes(key or MAGIC_HIGHWAYHASH_KEY))
    return parity, digests.reshape(b, k + m, 32)


def _hash_rows(rows: jax.Array, shard_len: int, key: bytes,
               algo: str) -> jax.Array:
    """(N, S) rows -> (N, 32) bitrot digests over the first shard_len
    bytes, on device (shared by put/get/heal steps)."""
    from ..bitrot import MAGIC_HIGHWAYHASH_KEY
    if algo == "sha256":
        from ..ops import sha256_jax
        return sha256_jax._sha256_impl(rows, shard_len)
    from ..ops import highwayhash_jax
    return highwayhash_jax._hh256_impl(
        rows, shard_len, bytes(key or MAGIC_HIGHWAYHASH_KEY))


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6))
def get_step(survivors: jax.Array, matrix_bits: jax.Array, r: int,
             k: int, shard_len: int = 0, key: bytes = b"",
             algo: str = "highwayhash") -> tuple[jax.Array, jax.Array]:
    """One degraded-GET device step: verify AND reconstruct in a single
    dispatch — the reference treats bitrot verification as inseparable
    from decode (streamingBitrotReader.ReadAt inside Erasure.Decode,
    cmd/bitrot-streaming.go:111-150 + cmd/erasure-decode.go:211), so the
    device program fuses them: one pass over the survivor rows feeds both
    the bitrot hash scan and the missing-row GF matmul.

    survivors:   (B, k, S) uint8 — the k surviving shards of each block,
                 stacked in missing_data_matrix `used` order.
    matrix_bits: (8r, 8k) 0/1 — bit-expanded missing-data matrix (only
                 the rows a GET actually needs, not the full k x k).
    shard_len:   true payload bytes per shard frame (digest coverage).
    Returns (missing (B, r, S) uint8 — the reconstructed shards in
    `missing` index order, digests (B, k, 32) uint8 — computed frame
    digests of the survivors, for the host to compare against the frame
    digests read from disk).
    """
    missing, digests = _reconstruct_and_hash(
        survivors, matrix_bits, r, k, shard_len, key, algo)
    return missing, digests[:, :k]


def _reconstruct_and_hash(survivors, matrix_bits, r, k, shard_len,
                          key, algo):
    """Shared fused core of get_step/heal_step: matmul the requested
    rows, then ONE hash scan over [survivors ‖ reconstructed]. Hashing
    the concat (not a reshaped view of the input argument) matters:
    the argument's layout pins the scan and measures ~4-5x slower on
    TPU — the concat lets XLA pick the scan-friendly layout, and the r
    extra hashed rows are noise (r << k). Returns (reconstructed
    (B, r, S), digests (B, k+r, 32) — survivors first)."""
    b, k_, s = survivors.shape
    assert k_ == k
    shard_len = shard_len or s
    from ..ops import rs_tpu
    out = rs_tpu._apply_matrix_impl(
        matrix_bits, survivors, r, k, rs_tpu.default_use_pallas())
    rows = jnp.concatenate([survivors, out],
                           axis=-2).reshape(b * (k + r), s)
    digests = _hash_rows(rows, shard_len, key, algo).reshape(
        b, k + r, 32)
    return out, digests


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6))
def heal_step(survivors: jax.Array, matrix_bits: jax.Array, r: int,
              k: int, shard_len: int = 0, key: bytes = b"",
              algo: str = "highwayhash"
              ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One heal device step: verify the survivors, recover the lost
    shards, AND digest the recovered shards for their new bitrot frames —
    the reference's decode→pipe→re-encode→rehash
    (cmd/erasure-lowlevel-heal.go:28-48 + both bitrot sides) as one
    program. The recovered rows never leave the device between the matmul
    and their frame digests.

    survivors:   (B, k, S) uint8 in recover_matrix `used` order.
    matrix_bits: (8r, 8k) bit-expanded recover matrix (r = lost shards,
                 data and parity rows both).
    Returns (recovered (B, r, S), survivor_digests (B, k, 32),
    recovered_digests (B, r, 32)) — the last are the digests the healer
    writes into the rebuilt shards' streaming-bitrot frames.
    """
    b, k_, s = survivors.shape
    recovered, digests = _reconstruct_and_hash(
        survivors, matrix_bits, r, k, shard_len, key, algo)
    return recovered, digests[:, :k], digests[:, k:]
