"""Flagship device pipelines: the "models" of this framework.

Where an ML framework has model families, an object store has data-path
pipelines. Each is a jittable function over batched shard tensors:

  * EncodePipeline  — PUT hot loop: batch of blocks -> parity shards (+
    per-shard bitrot digests). The device analog of the reference's
    Erasure.Encode loop (cmd/erasure-encode.go:75-146).
  * DecodePipeline  — GET-with-failures: survivor shards -> data shards
    (cmd/erasure-decode.go Reconstruct semantics).
  * HealPipeline    — decode->reencode in one matmul via the recover
    matrix (cmd/erasure-lowlevel-heal.go:28-48 collapsed to a single
    device op).

All pipelines are shape-static per (k, m, S, B) and cached; the batch
scheduler (parallel/scheduler.py) routes variable traffic into a small set
of bucketed shapes so XLA compiles each program once.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import chacha20_jax, rs_matrix, rs_tpu


@dataclasses.dataclass(frozen=True)
class ECConfig:
    """Erasure-set geometry: k data + m parity shards over blockSize-byte
    blocks (reference defaults: block 4 MiB; this framework benches 1 MiB
    per BASELINE config). Placement math delegates to
    storage.datatypes.ErasureInfo so there is exactly one copy of the
    cmd/erasure-coding.go:120-143 formulas."""
    data_shards: int
    parity_shards: int
    block_size: int = 1 << 20

    @property
    def total_shards(self) -> int:
        return self.data_shards + self.parity_shards

    def _erasure_info(self):
        from ..storage.datatypes import ErasureInfo
        return ErasureInfo(data_blocks=self.data_shards,
                           parity_blocks=self.parity_shards,
                           block_size=self.block_size)

    @property
    def shard_size(self) -> int:
        """Per-shard bytes of one full block (ceil division, zero-padded:
        same split semantics as the reference codec)."""
        return self._erasure_info().shard_size()

    def shard_file_size(self, total_length: int) -> int:
        return self._erasure_info().shard_file_size(total_length)

    def shard_file_offset(self, start: int, length: int, total: int) -> int:
        return self._erasure_info().shard_file_offset(start, length, total)


# ---------------------------------------------------------------------------
# Encode
# ---------------------------------------------------------------------------

def encode_blocks(data: jax.Array | np.ndarray, cfg: ECConfig,
                  *, use_pallas: bool | None = None) -> jax.Array:
    """(B, k, S) data shards -> (B, m, S) parity shards on device."""
    return rs_tpu.apply_matrix(
        np.asarray(rs_matrix.parity_matrix(cfg.data_shards,
                                           cfg.parity_shards)),
        data, use_pallas=use_pallas)


def encode_blocks_full(data, cfg: ECConfig, *,
                       use_pallas: bool | None = None) -> jax.Array:
    """(B, k, S) -> (B, n, S): data with parity appended (GET-comparable
    to the host oracle byte-for-byte)."""
    data = jnp.asarray(data, jnp.uint8)
    parity = encode_blocks(data, cfg, use_pallas=use_pallas)
    return jnp.concatenate([data, parity], axis=-2)


# ---------------------------------------------------------------------------
# Decode / heal
# ---------------------------------------------------------------------------

def decode_blocks(survivors, present_mask: int, cfg: ECConfig,
                  *, use_pallas: bool | None = None) -> jax.Array:
    """(B, k, S) stacked survivor shards (in decode_matrix `used` order)
    -> (B, k, S) data shards."""
    return rs_tpu.reconstruct_data(
        survivors, present_mask, cfg.data_shards, cfg.parity_shards,
        use_pallas=use_pallas)


def heal_blocks(survivors, present_mask: int, cfg: ECConfig,
                *, use_pallas: bool | None = None) -> jax.Array:
    """(B, k, S) survivors -> (B, |missing|, S): exactly the lost shards,
    one fused matmul (decode+reencode collapsed)."""
    return rs_tpu.recover_missing(
        survivors, present_mask, cfg.data_shards, cfg.parity_shards,
        use_pallas=use_pallas)


# ---------------------------------------------------------------------------
# The flagship jittable step (what __graft_entry__.entry() exposes)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5))
def put_step(data: jax.Array, k: int, m: int, shard_len: int = 0,
             key: bytes = b"", algo: str = "highwayhash"
             ) -> tuple[jax.Array, jax.Array]:
    """One PUT device step: RS-encode a batch of blocks AND compute each
    shard's streaming-bitrot digest — the full reference per-block PUT
    work (cmd/erasure-encode.go:75-146 + cmd/bitrot-streaming.go:46-58)
    as one device program.

    data: (B, k, S) uint8 data shards. S may include right zero-padding
    (GF coding is column-independent, so padded columns encode to zeros);
    shard_len (< = S, default S) is the true shard byte-length the bitrot
    digests must cover. algo: "highwayhash" (keyed HH256, the default
    bitrot) or "sha256".
    Returns (parity (B, m, S) uint8, digests (B, k+m, 32) uint8 in shard
    order data-then-parity) — byte-identical to the CPU bitrot path
    (minio_tpu/bitrot.py). The caller already holds the data rows, so
    only parity + digests cross back to the host.
    """
    from ..bitrot import MAGIC_HIGHWAYHASH_KEY
    b, k_, s = data.shape
    assert k_ == k
    shard_len = shard_len or s
    pm = np.asarray(rs_matrix.parity_matrix(k, m))
    m2 = rs_tpu._bit_expand_cached(pm.tobytes(), pm.shape)
    parity = rs_tpu._apply_matrix_impl(
        jnp.asarray(m2), data, m, k, rs_tpu.default_use_pallas())

    # one hash scan over data+parity rows together: splitting into two
    # scans measures slower (the small parity-only scan underfills the
    # vector lanes and doubles loop overhead)
    rows = jnp.concatenate([data, parity], axis=-2).reshape(b * (k + m), s)
    if algo == "sha256":
        from ..ops import sha256_jax
        digests = sha256_jax._sha256_impl(rows, shard_len)
    else:
        from ..ops import highwayhash_jax
        digests = highwayhash_jax._hh256_impl(
            rows, shard_len, bytes(key or MAGIC_HIGHWAYHASH_KEY))
    return parity, digests.reshape(b, k + m, 32)


@functools.partial(jax.jit, static_argnums=(3, 4, 5, 6, 7, 8))
def sse_put_step(data: jax.Array, keys: jax.Array, nonces: jax.Array,
                 k: int, m: int, pkg_bytes: int, shard_len: int = 0,
                 key: bytes = b"", algo: str = "highwayhash"
                 ) -> tuple[jax.Array, jax.Array]:
    """One ENCRYPTED PUT device step: ChaCha20-cipher each block, RS-
    encode the ciphertext, and digest every shard — the tentpole fusion.
    An encrypted batch costs the same single launch as a plaintext one;
    the host's only remaining cipher work is the Poly1305 tag trailer
    over the ciphertext this step returns (no laundered auth).

    data:   (B, k, S) uint8 staged shards whose flat (B, k·S) view holds
            the plaintext block in its first P·pkg_bytes bytes, zeros
            after (codec.split pad discipline). Only the plaintext span
            is ciphered — the keystream is zero-padded to k·S, so pad
            columns stay zero and the stored stream is byte-identical
            to the CPU ChaChaEncryptor path.
    keys:   (B, 8) uint32 per-row ChaCha20 key words; nonces (B, P, 3)
            uint32 per-row per-package nonce words (features/crypto.
            DeviceSSE.batch_params — rows of DIFFERENT objects coalesce
            because the bucket key carries only these arrays' shapes).
    Returns (full (B, k+m, S) uint8 — ciphertext data shards with
    parity appended, digests (B, k+m, 32)). Unlike put_step the data
    rows DO cross back: the caller staged plaintext and must write (and
    tag) ciphertext.
    """
    b, k_, s = data.shape
    assert k_ == k
    p = nonces.shape[1]
    ct_bytes = p * pkg_bytes
    ks = chacha20_jax.keystream_u8(keys, nonces, ct_bytes, pkg_bytes)
    if ct_bytes < k * s:
        ks = jnp.concatenate(
            [ks, jnp.zeros((b, k * s - ct_bytes), jnp.uint8)], axis=-1)
    ct = (jnp.asarray(data, jnp.uint8).reshape(b, k * s)
          ^ ks).reshape(b, k, s)
    pm = np.asarray(rs_matrix.parity_matrix(k, m))
    m2 = rs_tpu._bit_expand_cached(pm.tobytes(), pm.shape)
    parity = rs_tpu._apply_matrix_impl(
        jnp.asarray(m2), ct, m, k, rs_tpu.default_use_pallas())
    rows = jnp.concatenate([ct, parity], axis=-2)
    digests = _hash_rows(rows.reshape(b * (k + m), s),
                         shard_len or s, key, algo)
    return rows, digests.reshape(b, k + m, 32)


@functools.partial(jax.jit, static_argnums=(4, 5, 6, 7, 8, 9, 10))
def sse_get_step(survivors: jax.Array, matrix_bits: jax.Array,
                 keys: jax.Array, nonces: jax.Array, r: int, k: int,
                 data_src: tuple = (), pkg_bytes: int = 0,
                 shard_len: int = 0, key: bytes = b"",
                 algo: str = "highwayhash"
                 ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One ENCRYPTED degraded-GET device step: verify → decode →
    decipher fused. Reconstructs the missing rows from the survivors,
    reassembles the kd ciphertext data shards, and XORs the per-package
    keystream back off — the plaintext block leaves the device in the
    same launch that verified and decoded it. (Poly1305 package tags
    still verify host-side against the trailer BEFORE any output of
    this step is served.)

    data_src: static tuple with one (src, idx) per data shard — src 0
    takes survivors[:, idx] (shard arrived intact, in decode `used`
    order), src 1 takes reconstructed[:, idx] (in `missing` order).
    keys (B, 8) / nonces (B, P, 3): word arrays for the block's
    packages, plaintext span = P·pkg_bytes of the flat (B, kd·S) view.
    Returns (plain (B, kd, S) deciphered data shards, missing (B, r, S)
    reconstructed CIPHERTEXT shards — what a heal would write back,
    survivor digests (B, k, 32) for host bitrot comparison).
    """
    b, k_, s = survivors.shape
    assert k_ == k
    out, digests = _reconstruct_and_hash(
        survivors, matrix_bits, r, k, shard_len, key, algo)
    kd = len(data_src)
    stacked = jnp.stack(
        [survivors[:, i] if src == 0 else out[:, i]
         for src, i in data_src], axis=1)
    ct_bytes = nonces.shape[1] * pkg_bytes
    ks = chacha20_jax.keystream_u8(keys, nonces, ct_bytes, pkg_bytes)
    if ct_bytes < kd * s:
        ks = jnp.concatenate(
            [ks, jnp.zeros((b, kd * s - ct_bytes), jnp.uint8)], axis=-1)
    plain = (stacked.reshape(b, kd * s) ^ ks).reshape(b, kd, s)
    return plain, out, digests[:, :k]


def _hash_rows(rows: jax.Array, shard_len: int, key: bytes,
               algo: str) -> jax.Array:
    """(N, S) rows -> (N, 32) bitrot digests over the first shard_len
    bytes, on device (shared by put/get/heal steps)."""
    from ..bitrot import MAGIC_HIGHWAYHASH_KEY
    if algo == "sha256":
        from ..ops import sha256_jax
        return sha256_jax._sha256_impl(rows, shard_len)
    from ..ops import highwayhash_jax
    return highwayhash_jax._hh256_impl(
        rows, shard_len, bytes(key or MAGIC_HIGHWAYHASH_KEY))


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6))
def get_step(survivors: jax.Array, matrix_bits: jax.Array, r: int,
             k: int, shard_len: int = 0, key: bytes = b"",
             algo: str = "highwayhash") -> tuple[jax.Array, jax.Array]:
    """One degraded-GET device step: verify AND reconstruct in a single
    dispatch — the reference treats bitrot verification as inseparable
    from decode (streamingBitrotReader.ReadAt inside Erasure.Decode,
    cmd/bitrot-streaming.go:111-150 + cmd/erasure-decode.go:211), so the
    device program fuses them: one pass over the survivor rows feeds both
    the bitrot hash scan and the missing-row GF matmul.

    survivors:   (B, k, S) uint8 — the k surviving shards of each block,
                 stacked in missing_data_matrix `used` order.
    matrix_bits: (8r, 8k) 0/1 — bit-expanded missing-data matrix (only
                 the rows a GET actually needs, not the full k x k).
    shard_len:   true payload bytes per shard frame (digest coverage).
    Returns (missing (B, r, S) uint8 — the reconstructed shards in
    `missing` index order, digests (B, k, 32) uint8 — computed frame
    digests of the survivors, for the host to compare against the frame
    digests read from disk).
    """
    missing, digests = _reconstruct_and_hash(
        survivors, matrix_bits, r, k, shard_len, key, algo)
    return missing, digests[:, :k]


def _reconstruct_and_hash(survivors, matrix_bits, r, k, shard_len,
                          key, algo):
    """Shared fused core of get_step/heal_step: matmul the requested
    rows, then ONE hash scan over [survivors ‖ reconstructed]. Hashing
    the concat (not a reshaped view of the input argument) matters:
    the argument's layout pins the scan and measures ~4-5x slower on
    TPU — the concat lets XLA pick the scan-friendly layout, and the r
    extra hashed rows are noise (r << k). Returns (reconstructed
    (B, r, S), digests (B, k+r, 32) — survivors first)."""
    b, k_, s = survivors.shape
    assert k_ == k
    shard_len = shard_len or s
    from ..ops import rs_tpu
    out = rs_tpu._apply_matrix_impl(
        matrix_bits, survivors, r, k, rs_tpu.default_use_pallas())
    rows = jnp.concatenate([survivors, out],
                           axis=-2).reshape(b * (k + r), s)
    digests = _hash_rows(rows, shard_len, key, algo).reshape(
        b, k + r, 32)
    return out, digests


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6))
def heal_step(survivors: jax.Array, matrix_bits: jax.Array, r: int,
              k: int, shard_len: int = 0, key: bytes = b"",
              algo: str = "highwayhash"
              ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One heal device step: verify the survivors, recover the lost
    shards, AND digest the recovered shards for their new bitrot frames —
    the reference's decode→pipe→re-encode→rehash
    (cmd/erasure-lowlevel-heal.go:28-48 + both bitrot sides) as one
    program. The recovered rows never leave the device between the matmul
    and their frame digests.

    survivors:   (B, k, S) uint8 in recover_matrix `used` order.
    matrix_bits: (8r, 8k) bit-expanded recover matrix (r = lost shards,
                 data and parity rows both).
    Returns (recovered (B, r, S), survivor_digests (B, k, 32),
    recovered_digests (B, r, 32)) — the last are the digests the healer
    writes into the rebuilt shards' streaming-bitrot frames.
    """
    b, k_, s = survivors.shape
    recovered, digests = _reconstruct_and_hash(
        survivors, matrix_bits, r, k, shard_len, key, algo)
    return recovered, digests[:, :k], digests[:, k:]
