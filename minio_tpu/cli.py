"""CLI — `python -m minio_tpu server ...` process bootstrap.

The reference's L0 (main.go + cmd/server-main.go): parse args, boot the
node, print the startup banner, block on signals.

Single node:
    python -m minio_tpu server /data/d{1...16} --address :9000

Distributed (run once per node, same node list everywhere):
    python -m minio_tpu server \
        --node 10.0.0.1:9000=/data/d{1...8} \
        --node 10.0.0.2:9000=/data/d{1...8} \
        --this 0
"""

from __future__ import annotations

import argparse
import os
import signal
import sys

from .cluster import NodeSpec, parse_node_arg, start_node, start_single
from .s3.credentials import Credentials, global_credentials


def _parse(argv: list[str]) -> argparse.Namespace:
    p = argparse.ArgumentParser(prog="minio_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("server", help="start an object-store node")
    s.add_argument("drives", nargs="*",
                   help="local drive paths (ellipses {1...N} supported)")
    s.add_argument("--address", default=":9000",
                   help="listen address host:port (default :9000)")
    s.add_argument("--node", action="append", default=[],
                   help="host:port=/drive{1...N} — one per cluster node")
    s.add_argument("--this", type=int, default=-1,
                   help="index of this node in the --node list")
    s.add_argument("--parity", type=int, default=None,
                   help="parity shards per set (default N/2)")
    s.add_argument("--set-drive-count", type=int, default=0,
                   help="drives per erasure set (default: auto 4..16)")
    s.add_argument("--region", default=os.environ.get(
        "MINIO_REGION", "us-east-1"))
    s.add_argument("--cert", default="", help="TLS certificate file")
    s.add_argument("--key", default="", help="TLS private key file")
    s.add_argument("--pool", action="append", default=[],
                   help="extra drive pool /data2/d{1...N} appended "
                   "after boot (single-node topology expansion); "
                   "repeatable")

    d = sub.add_parser("decommission",
                       help="drain a pool's objects into the active "
                       "pools (admin rebalance surface)")
    d.add_argument("--url", default="127.0.0.1:9000",
                   help="server admin endpoint host:port")
    d.add_argument("--pool", type=int, default=None,
                   help="pool index to decommission")
    d.add_argument("--status", action="store_true",
                   help="print rebalance/topology status and exit")
    d.add_argument("--cancel", action="store_true",
                   help="cancel the running drain (pool returns to "
                   "active)")
    d.add_argument("--region", default=os.environ.get(
        "MINIO_REGION", "us-east-1"))

    t = sub.add_parser("tier", help="manage remote tiers for ILM "
                       "transitions (mc admin tier surface)")
    t.add_argument("action", choices=("add", "ls", "rm", "stats"))
    t.add_argument("--url", default="127.0.0.1:9000",
                   help="server admin endpoint host:port")
    t.add_argument("--name", default="",
                   help="tier name (add/rm)")
    t.add_argument("--type", default="fs", dest="tier_type",
                   choices=("fs", "s3", "azure", "gcs", "hdfs"),
                   help="tier backend type (add)")
    t.add_argument("--param", action="append", default=[],
                   help="backend param key=value (repeatable): fs needs "
                   "path=...; s3 needs host=, bucket= (+port/access_key/"
                   "secret_key/prefix/region)")
    t.add_argument("--force", action="store_true",
                   help="add: update an existing tier in place; "
                   "rm: remove even when lifecycle rules reference it")
    t.add_argument("--region", default=os.environ.get(
        "MINIO_REGION", "us-east-1"))

    q = sub.add_parser("qos", help="manage per-tenant/per-tier QoS "
                       "budgets (admission shares, request/byte rates)")
    q.add_argument("action", choices=("get", "set", "rm"))
    q.add_argument("--url", default="127.0.0.1:9000",
                   help="server admin endpoint host:port")
    q.add_argument("--scope", default="tenant",
                   choices=("tenant", "tier"),
                   help="budget scope (set/rm)")
    q.add_argument("--name", default="",
                   help="tenant account or tier name (set/rm)")
    q.add_argument("--share", type=float, default=0.0,
                   help="admission-share weight (0 = default)")
    q.add_argument("--rps", type=float, default=0.0,
                   help="request-rate budget, req/s (0 = unlimited)")
    q.add_argument("--rx-bps", type=float, default=0.0,
                   help="request-body byte budget, bytes/s "
                   "(0 = unlimited)")
    q.add_argument("--tx-bps", type=float, default=0.0,
                   help="response/push byte budget, bytes/s "
                   "(0 = unlimited)")
    q.add_argument("--region", default=os.environ.get(
        "MINIO_REGION", "us-east-1"))

    n = sub.add_parser("notify", help="manage bucket event "
                       "notification targets (webhook/queue/log)")
    n.add_argument("action", choices=("status", "add", "rm"))
    n.add_argument("--url", default="127.0.0.1:9000",
                   help="server admin endpoint host:port")
    n.add_argument("--type", default="webhook",
                   choices=("webhook", "queue", "log"),
                   help="target type (add)")
    n.add_argument("--name", default="",
                   help="ARN id segment (add; random when empty)")
    n.add_argument("--arn", default="",
                   help="target ARN (rm, or add --force to update)")
    n.add_argument("--endpoint", default="",
                   help="webhook POST URL (add --type webhook)")
    n.add_argument("--auth-token", default="",
                   help="webhook bearer token (add --type webhook)")
    n.add_argument("--timeout", type=float, default=0.0,
                   help="webhook send timeout, seconds (0 = default)")
    n.add_argument("--path", default="",
                   help="event log file (add --type log)")
    n.add_argument("--force", action="store_true",
                   help="add: update an existing target in place "
                   "(needs --arn)")
    n.add_argument("--region", default=os.environ.get(
        "MINIO_REGION", "us-east-1"))

    f = sub.add_parser("fsck", help="run the crash-consistency "
                       "auditor against a running node")
    f.add_argument("--url", default="127.0.0.1:9000",
                   help="server admin endpoint host:port")
    f.add_argument("--repair", action="store_true",
                   help="repair repairable findings (POST mode)")
    f.add_argument("--bucket", default="",
                   help="narrow the audit to one bucket")
    f.add_argument("--tmp-age", type=float, default=None,
                   help="staged tmp older than this (seconds) counts "
                   "as a crash leftover; 0 = reap all (quiesced only)")
    f.add_argument("--region", default=os.environ.get(
        "MINIO_REGION", "us-east-1"))

    i = sub.add_parser("incidents", help="list black-box capture "
                       "bundles from a running node (or fetch one "
                       "with --id)")
    i.add_argument("--url", default="127.0.0.1:9000",
                   help="server admin endpoint host:port")
    i.add_argument("--id", default="",
                   help="fetch one full bundle by incident id")
    i.add_argument("--cluster", action="store_true",
                   help="merge every peer's bundle list")
    i.add_argument("--region", default=os.environ.get(
        "MINIO_REGION", "us-east-1"))

    g = sub.add_parser("gateway", help="serve the S3 API over a "
                       "foreign backend (cmd/gateway-main.go)")
    g.add_argument("kind", choices=("nas", "s3", "azure", "gcs",
                                    "hdfs"))
    g.add_argument("target", nargs="?", default="",
                   help="nas: /mount/path; s3: host:port; "
                   "azure: blob endpoint host:port; gcs: endpoint "
                   "host:port (default storage.googleapis.com); "
                   "hdfs: namenode host:port")
    g.add_argument("--address", default=":9000")
    g.add_argument("--region", default=os.environ.get(
        "MINIO_REGION", "us-east-1"))
    return p.parse_args(argv)


def _creds() -> Credentials:
    ak = os.environ.get("MINIO_ACCESS_KEY") or \
        os.environ.get("MINIO_ROOT_USER")
    sk = os.environ.get("MINIO_SECRET_KEY") or \
        os.environ.get("MINIO_ROOT_PASSWORD")
    if ak and sk:
        return Credentials(access_key=ak, secret_key=sk)
    return global_credentials()


def _run_gateway(args, creds: Credentials) -> int:
    """`minio_tpu gateway <kind> <target>` — serve the full S3 surface
    over a foreign backend (reference cmd/gateway-main.go). Backend
    credentials come from MINIO_GATEWAY_{ACCESS,SECRET}_KEY (s3) or
    MINIO_AZURE_{ACCOUNT,KEY} (azure)."""
    from .gateway import new_gateway
    from .s3.server import S3Server
    from .utils import host_port

    if args.kind == "nas":
        if not args.target:
            print("gateway nas needs a mount path", file=sys.stderr)
            return 2
        layer = new_gateway("nas", path=args.target)
    elif args.kind == "s3":
        if not args.target:
            # no silent default: 127.0.0.1:9000 would be the gateway's
            # own listen address — a self-proxying loop
            print("gateway s3 needs an upstream host:port",
                  file=sys.stderr)
            return 2
        h, p = host_port(args.target, 9000)
        layer = new_gateway(
            "s3", host=h, port=p,
            access_key=os.environ.get("MINIO_GATEWAY_ACCESS_KEY",
                                      creds.access_key),
            secret_key=os.environ.get("MINIO_GATEWAY_SECRET_KEY",
                                      creds.secret_key),
            region=args.region)
    elif args.kind == "azure":
        account = os.environ.get("MINIO_AZURE_ACCOUNT", "")
        key = os.environ.get("MINIO_AZURE_KEY", "")
        if not account or not key:
            print("gateway azure needs MINIO_AZURE_ACCOUNT and "
                  "MINIO_AZURE_KEY", file=sys.stderr)
            return 2
        h, p = host_port(args.target or f"{account}.blob.core."
                         "windows.net:443", 443)
        layer = new_gateway("azure", account=account, key_b64=key,
                            host=h, port=p, secure=(p == 443))
    elif args.kind == "gcs":
        # JSON API (the reference's mode): a service-account key file
        # via GOOGLE_APPLICATION_CREDENTIALS / MINIO_GCS_CREDENTIALS.
        # XML interop fallback: HMAC keys.
        sa = os.environ.get("MINIO_GCS_CREDENTIALS", "") or \
            os.environ.get("GOOGLE_APPLICATION_CREDENTIALS", "")
        ak = os.environ.get("MINIO_GCS_ACCESS_KEY", "")
        sk = os.environ.get("MINIO_GCS_SECRET_KEY", "")
        h, p = host_port(args.target or "storage.googleapis.com:443",
                         443)
        if sa:
            layer = new_gateway(
                "gcs", credentials_json=sa,
                project=os.environ.get("MINIO_GCS_PROJECT", ""),
                host=h, port=p, secure=(p == 443))
        elif ak and sk:
            layer = new_gateway("gcs", access_key=ak, secret_key=sk,
                                host=h, port=p, secure=(p == 443))
        else:
            print("gateway gcs needs GOOGLE_APPLICATION_CREDENTIALS/"
                  "MINIO_GCS_CREDENTIALS (JSON API) or "
                  "MINIO_GCS_ACCESS_KEY + MINIO_GCS_SECRET_KEY "
                  "(HMAC interop)", file=sys.stderr)
            return 2
    else:
        if not args.target:
            print("gateway hdfs needs a namenode host:port",
                  file=sys.stderr)
            return 2
        h, p = host_port(args.target, 9870)
        layer = new_gateway("hdfs", host=h, port=p)

    lh, lp = host_port(args.address, 9000)
    srv = S3Server(layer, creds=creds, region=args.region,
                   address=lh or "0.0.0.0", port=lp).start()
    print(f"MinIO-TPU {args.kind} gateway up at "
          f"http://{lh or '127.0.0.1'}:{srv.port} "
          f"(access key {creds.access_key})")

    def cleanup():
        srv.stop()
        layer.close()

    return _serve_until_signal(cleanup)


def _serve_until_signal(cleanup) -> int:
    """Block until SIGTERM/SIGINT, then run cleanup (Event.wait is
    signal-safe: no lost-wakeup window)."""
    import threading
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    try:
        stop.wait()
    finally:
        cleanup()
    return 0


def _run_decommission(args, creds: Credentials) -> int:
    """`minio_tpu decommission` — drive the admin rebalance surface
    (start / --status / --cancel) against a running node."""
    import json as _json
    from .madmin import AdminClient, AdminClientError
    from .utils import host_port
    h, p = host_port(args.url, 9000)
    cli = AdminClient(h, p, creds.access_key, creds.secret_key,
                      region=args.region)
    try:
        if args.status:
            out = cli.rebalance_status()
        elif args.cancel:
            out = cli.cancel_rebalance()
        elif args.pool is None:
            print("decommission needs --pool N (or --status/--cancel)",
                  file=sys.stderr)
            return 2
        else:
            out = cli.start_rebalance(args.pool)
    except AdminClientError as e:
        print(f"decommission failed: {e}", file=sys.stderr)
        return 1
    print(_json.dumps(out, indent=2, sort_keys=True))
    return 0


def _run_tier(args, creds: Credentials) -> int:
    """`minio_tpu tier add|ls|rm|stats` — drive the admin tier registry
    against a running node."""
    import json as _json
    from .madmin import AdminClient, AdminClientError
    from .utils import host_port
    h, p = host_port(args.url, 9000)
    cli = AdminClient(h, p, creds.access_key, creds.secret_key,
                      region=args.region)
    try:
        if args.action == "ls":
            out = cli.list_tiers()
        elif args.action == "stats":
            out = cli.tier_stats()
        elif args.action == "rm":
            if not args.name:
                print("tier rm needs --name", file=sys.stderr)
                return 2
            out = cli.remove_tier(args.name, force=args.force)
        else:
            if not args.name:
                print("tier add needs --name", file=sys.stderr)
                return 2
            params = {}
            for kv in args.param:
                k, sep, v = kv.partition("=")
                if not sep:
                    print(f"bad --param {kv!r}: need key=value",
                          file=sys.stderr)
                    return 2
                params[k] = v
            out = cli.add_tier(args.name, args.tier_type,
                               update=args.force, **params)
    except AdminClientError as e:
        print(f"tier {args.action} failed: {e}", file=sys.stderr)
        return 1
    print(_json.dumps(out, indent=2, sort_keys=True))
    return 0


def _run_qos(args, creds: Credentials) -> int:
    """`minio_tpu qos get|set|rm` — drive the admin QoS budget
    registry against a running node."""
    import json as _json
    from .madmin import AdminClient, AdminClientError
    from .utils import host_port
    h, p = host_port(args.url, 9000)
    cli = AdminClient(h, p, creds.access_key, creds.secret_key,
                      region=args.region)
    try:
        if args.action == "get":
            out = cli.qos_get()
        elif args.action == "rm":
            if not args.name:
                print("qos rm needs --name", file=sys.stderr)
                return 2
            out = cli.qos_remove(args.name, scope=args.scope)
        else:
            if not args.name:
                print("qos set needs --name", file=sys.stderr)
                return 2
            out = cli.qos_set(args.name, scope=args.scope,
                              share=args.share, rps=args.rps,
                              rx_bps=args.rx_bps, tx_bps=args.tx_bps)
    except AdminClientError as e:
        print(f"qos {args.action} failed: {e}", file=sys.stderr)
        return 1
    print(_json.dumps(out, indent=2, sort_keys=True))
    return 0


def _run_notify(args, creds: Credentials) -> int:
    """`minio_tpu notify status|add|rm` — drive the admin
    notification-target registry against a running node."""
    import json as _json
    from .madmin import AdminClient, AdminClientError
    from .utils import host_port
    h, p = host_port(args.url, 9000)
    cli = AdminClient(h, p, creds.access_key, creds.secret_key,
                      region=args.region)
    try:
        if args.action == "status":
            out = cli.notify_status()
        elif args.action == "rm":
            if not args.arn:
                print("notify rm needs --arn", file=sys.stderr)
                return 2
            cli.remove_notify_target(args.arn)
            out = {"removed": args.arn}
        else:
            params = {}
            if args.endpoint:
                params["endpoint"] = args.endpoint
            if args.auth_token:
                params["auth_token"] = args.auth_token
            if args.timeout:
                params["timeout"] = args.timeout
            if args.path:
                params["path"] = args.path
            arn = cli.add_notify_target(
                type=args.type, name=args.name, arn=args.arn,
                update=args.force, **params)
            out = {"arn": arn}
    except AdminClientError as e:
        print(f"notify {args.action} failed: {e}", file=sys.stderr)
        return 1
    print(_json.dumps(out, indent=2, sort_keys=True))
    return 0


def _run_fsck(args, creds: Credentials) -> int:
    """`minio_tpu fsck` — drive the admin consistency auditor. Exit 0
    when the tree is clean (or everything repairable was repaired),
    1 when unrepaired findings remain."""
    import json as _json
    from .madmin import AdminClient, AdminClientError
    from .utils import host_port
    h, p = host_port(args.url, 9000)
    cli = AdminClient(h, p, creds.access_key, creds.secret_key,
                      region=args.region)
    try:
        out = cli.fsck(repair=args.repair, bucket=args.bucket,
                       tmp_age_s=args.tmp_age)
    except AdminClientError as e:
        print(f"fsck failed: {e}", file=sys.stderr)
        return 1
    print(_json.dumps(out, indent=2, sort_keys=True))
    return 0 if out.get("unrepaired", 0) == 0 else 1


def _run_incidents(args, creds: Credentials) -> int:
    """`minio_tpu incidents` — list capture bundles (or fetch one
    with --id); the black box's readback."""
    import json as _json
    from .madmin import AdminClient, AdminClientError
    from .utils import host_port
    h, p = host_port(args.url, 9000)
    cli = AdminClient(h, p, creds.access_key, creds.secret_key,
                      region=args.region)
    try:
        out = cli.incident(args.id) if args.id \
            else {"incidents": cli.incidents(cluster=args.cluster)}
    except AdminClientError as e:
        print(f"incidents failed: {e}", file=sys.stderr)
        return 1
    print(_json.dumps(out, indent=2, sort_keys=True))
    return 0


def main(argv: list[str] | None = None) -> int:
    args = _parse(argv if argv is not None else sys.argv[1:])
    creds = _creds()
    if args.cmd == "gateway":
        return _run_gateway(args, creds)
    if args.cmd == "fsck":
        return _run_fsck(args, creds)
    if args.cmd == "incidents":
        return _run_incidents(args, creds)
    if args.cmd == "decommission":
        return _run_decommission(args, creds)
    if args.cmd == "tier":
        return _run_tier(args, creds)
    if args.cmd == "qos":
        return _run_qos(args, creds)
    if args.cmd == "notify":
        return _run_notify(args, creds)
    kw = dict(parity=args.parity, set_drive_count=args.set_drive_count,
              region=args.region,
              certfile=args.cert or None, keyfile=args.key or None)

    if args.node:
        if args.this < 0 or args.this >= len(args.node):
            print("--this must index the --node list", file=sys.stderr)
            return 2
        nodes = [parse_node_arg(n) for n in args.node]
        node = start_node(nodes, args.this, creds, **kw)
    else:
        if not args.drives:
            print("no drives given", file=sys.stderr)
            return 2
        host, sep, port = args.address.rpartition(":")
        if not sep:
            host, port = args.address, ""
        try:
            port_n = int(port) if port else 9000
        except ValueError:
            print(f"bad --address {args.address!r}: port must be a "
                  "number (host:port)", file=sys.stderr)
            return 2
        from .utils import ellipses as _ell
        expanded = _ell.expand_args(args.drives)
        if len(expanded) == 1:
            # one path: FS backend, no erasure (reference newObjectLayer)
            if args.pool:
                print("--pool needs an erasure backend; the FS "
                      "backend has no pool topology", file=sys.stderr)
                return 2
            from .cluster import start_fs
            node = start_fs(expanded[0], host or "0.0.0.0", port_n,
                            creds, region=args.region)
            print(f"MinIO-TPU FS node up at {node.url} "
                  f"(access key {creds.access_key})")
            return _serve_until_signal(node.shutdown)
        node = start_single(args.drives, host or "0.0.0.0", port_n,
                            creds, **kw)

    for pool_arg in getattr(args, "pool", []) or []:
        if args.node:
            print("--pool expansion is single-node only; distributed "
                  "pools join via their own --node lists",
                  file=sys.stderr)
            node.shutdown()
            return 2
        node.add_pool([pool_arg])

    info = node.object_layer.storage_info()
    print(f"MinIO-TPU node {node.spec.addr} up: "
          f"{node.set_count} set(s) x {node.set_drive_count} drives, "
          f"EC:{node.parity}; {info['online_disks']} online / "
          f"{info['offline_disks']} offline drives")
    print(f"S3 endpoint: {node.url}  (access key {creds.access_key})")
    return _serve_until_signal(node.shutdown)


if __name__ == "__main__":
    sys.exit(main())
