"""Admin client SDK (reference pkg/madmin): a typed Python client for
the /minio/admin/v3 surface, /minio/health, and the metrics endpoint —
what `mc admin ...` scripts against."""

from __future__ import annotations

import hashlib
import http.client
import json
import urllib.parse
from typing import Iterator, Optional

from .s3 import signature as sig
from .s3.credentials import Credentials

ADMIN_PREFIX = "/minio/admin/v3"


class AdminClientError(Exception):
    def __init__(self, status: int, payload: dict):
        super().__init__(f"{status}: {payload}")
        self.status = status
        self.payload = payload


class AdminClient:
    def __init__(self, host: str, port: int, access_key: str,
                 secret_key: str, region: str = "us-east-1",
                 timeout: float = 30.0):
        self.host, self.port = host, port
        self.creds = Credentials(access_key, secret_key)
        self.region = region
        self.timeout = timeout

    # -- plumbing ----------------------------------------------------------

    def _request(self, method: str, sub: str,
                 query: Optional[dict] = None, body: bytes = b"",
                 prefix: str = ADMIN_PREFIX, sign: bool = True):
        path = f"{prefix}/{sub}" if sub else prefix
        query = {k: [v] for k, v in (query or {}).items()}
        qs = urllib.parse.urlencode({k: v[0] for k, v in query.items()})
        hdrs = {"host": f"{self.host}:{self.port}"}
        if sign:
            hdrs = sig.sign_v4(method, path, query, hdrs,
                               hashlib.sha256(body).hexdigest(),
                               self.creds, self.region)
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        conn.request(method, path + (f"?{qs}" if qs else ""), body=body,
                     headers=hdrs)
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        if resp.status >= 300:
            try:
                payload = json.loads(data.decode())
            except ValueError:
                payload = {"raw": data.decode(errors="replace")}
            raise AdminClientError(resp.status, payload)
        return data

    def _json(self, method, sub, query=None, body: bytes = b""):
        out = self._request(method, sub, query, body)
        return json.loads(out.decode()) if out else {}

    # -- info / health -----------------------------------------------------

    def server_info(self) -> dict:
        return self._json("GET", "info")

    def storage_info(self) -> dict:
        return self._json("GET", "storageinfo")

    def data_usage_info(self) -> dict:
        return self._json("GET", "datausageinfo")

    def top_locks(self) -> dict:
        return self._json("GET", "top/locks")

    def alive(self) -> bool:
        try:
            self._request("GET", "live", prefix="/minio/health",
                          sign=False)
            return True
        except AdminClientError:
            return False

    def metrics_text(self) -> str:
        return self._request("GET", "", prefix="/minio/prometheus/metrics",
                             sign=False).decode()

    def cluster_metrics(self) -> str:
        """ONE Prometheus exposition for the whole cluster: the serving
        node scrapes every peer over RPC and merges (counters summed,
        gauges carrying a `node` label, histograms bucket-merged). A
        dead peer degrades the scrape — check
        `minio_tpu_cluster_scrape_failed_total` in the output."""
        return self._request("GET", "metrics",
                             {"cluster": "1"}).decode()

    def node_metrics(self) -> str:
        """The serving node's own exposition via the authenticated
        admin route (the anonymous endpoint's SigV4 twin)."""
        return self._request("GET", "metrics").decode()

    # -- heal --------------------------------------------------------------

    def fsck(self, repair: bool = False, bucket: str = "",
             tmp_age_s: Optional[float] = None) -> dict:
        """Run the crash-consistency auditor; ``repair=True`` also
        repairs (POST). ``tmp_age_s=0`` reaps ALL staged tmp leftovers
        (safe only when nothing is in flight)."""
        q = {}
        if bucket:
            q["bucket"] = bucket
        if tmp_age_s is not None:
            q["tmp_age"] = str(tmp_age_s)
        return self._json("POST" if repair else "GET", "fsck", query=q)

    def naughtynet(self, payload: dict) -> dict:
        """Drive the node's network chaos injector (test-only; the node
        must run with MINIO_TPU_NAUGHTYNET=on). ``payload`` is the
        distributed/naughtynet admin op: {"op": "partition"|"heal"|
        "configure"|"arm"|"disarm"|"status"|"reset", ...}."""
        return self._json("POST", "naughtynet",
                          body=json.dumps(payload).encode())

    def heal_start(self, bucket: str = "", prefix: str = "") -> str:
        out = self._json("POST", "heal",
                         {"bucket": bucket, "prefix": prefix})
        return out["token"]

    def heal_status(self, token: str) -> dict:
        return self._json("GET", "heal/status", {"token": token})

    # -- topology / rebalance ----------------------------------------------

    def start_rebalance(self, pool: int) -> dict:
        """Begin decommissioning `pool`: mark it draining and start the
        background rebalance moving its objects to the active pools."""
        return self._json("POST", "rebalance", {"pool": str(pool)})

    def rebalance_status(self) -> dict:
        return self._json("GET", "rebalance")

    def cancel_rebalance(self) -> dict:
        return self._json("DELETE", "rebalance")

    def topology(self) -> dict:
        return self._json("GET", "topology")

    def set_pool_state(self, pool: int, state: str) -> dict:
        """Suspend ("suspended") or resume ("active") a pool for new
        writes without draining it."""
        return self._json("POST", "topology",
                          {"pool": str(pool), "state": state})

    def mrf_status(self) -> dict:
        """MRF heal-queue stats (pending/healed/requeued/failed/dropped;
        zones nested for server-sets backends)."""
        return self._json("GET", "mrf")

    def metacache_stats(self, bucket: str = "") -> dict:
        """Bucket metacache state: per-bucket index entries/state/
        invalid/dirty/generation, pending journal deltas, and the
        serve/fallback/drop/reconcile counters ({"enabled": False}
        when the node runs without the index)."""
        query = {"bucket": bucket} if bucket else None
        return self._json("GET", "metacache", query)

    # -- tiering -----------------------------------------------------------

    def add_tier(self, name: str, type_: str, update: bool = False,
                 **params) -> dict:
        """Register a remote tier (type_: fs|s3|azure|gcs|hdfs; params
        are backend-specific — fs: path; s3: host/port/bucket/prefix/
        access_key/secret_key/region)."""
        query = {"force": "true"} if update else None
        return self._json("PUT", "tier", query,
                          json.dumps({"name": name, "type": type_,
                                      "params": params}).encode())

    def list_tiers(self) -> list[dict]:
        """Registered tiers (secrets redacted)."""
        return self._json("GET", "tier")["tiers"]

    def remove_tier(self, name: str, force: bool = False) -> dict:
        """Remove a tier; `force` overrides the in-use refusal (a tier
        still named by lifecycle Transition rules answers 409)."""
        query = {"name": name}
        if force:
            query["force"] = "true"
        return self._json("DELETE", "tier", query)

    def tier_stats(self) -> dict:
        """Transition-worker queue/throughput counters."""
        return self._json("GET", "tier/stats")

    # -- multi-tenant QoS --------------------------------------------------

    def qos_get(self) -> dict:
        """QoS plane state: enabled flag, registry epoch, tenant/tier
        budgets, and live per-tenant stats."""
        return self._json("GET", "qos")

    def qos_set(self, name: str, scope: str = "tenant",
                share: float = 0.0, rps: float = 0.0,
                rx_bps: float = 0.0, tx_bps: float = 0.0) -> dict:
        """Set (or replace) one tenant/tier budget; 0 means
        default/unlimited for that dimension."""
        return self._json("PUT", "qos", None,
                          json.dumps({"scope": scope, "name": name,
                                      "share": share, "rps": rps,
                                      "rx_bps": rx_bps,
                                      "tx_bps": tx_bps}).encode())

    def qos_remove(self, name: str, scope: str = "tenant") -> dict:
        return self._json("DELETE", "qos",
                          {"scope": scope, "name": name})

    # -- IAM ---------------------------------------------------------------

    def add_user(self, access_key: str, secret_key: str) -> None:
        self._json("PUT", "add-user", {"accessKey": access_key},
                   json.dumps({"secretKey": secret_key}).encode())

    def remove_user(self, access_key: str) -> None:
        self._json("DELETE", "remove-user", {"accessKey": access_key})

    def list_users(self) -> list[str]:
        return self._json("GET", "list-users")["users"]

    def set_user_status(self, access_key: str, status: str) -> None:
        self._json("PUT", "set-user-status",
                   {"accessKey": access_key, "status": status})

    def add_canned_policy(self, name: str, policy_json: str) -> None:
        self._json("PUT", "add-canned-policy", {"name": name},
                   policy_json.encode())

    def remove_canned_policy(self, name: str) -> None:
        self._json("DELETE", "remove-canned-policy", {"name": name})

    def list_canned_policies(self) -> list[str]:
        return self._json("GET", "list-canned-policies")["policies"]

    def set_policy(self, policy_name: str, user_or_group: str,
                   is_group: bool = False) -> None:
        self._json("PUT", "set-user-or-group-policy",
                   {"policyName": policy_name,
                    "userOrGroup": user_or_group,
                    "isGroup": "true" if is_group else "false"})

    def add_service_account(self, parent: str, access_key: str = "",
                            secret_key: str = "") -> dict:
        return self._json("PUT", "add-service-account", None,
                          json.dumps({"parent": parent,
                                      "accessKey": access_key,
                                      "secretKey": secret_key}).encode())

    # -- config KV ---------------------------------------------------------

    def get_config(self) -> dict:
        return self._json("GET", "get-config")

    def set_config(self, subsys: str, **kv) -> None:
        self._json("PUT", "set-config", {"subsys": subsys},
                   json.dumps(kv).encode())

    def config_history(self) -> list[str]:
        return self._json("GET", "config-history")["entries"]

    def restore_config(self, entry: str) -> None:
        self._json("PUT", "restore-config", {"entry": entry})

    # -- trace / profiling -------------------------------------------------

    def trace(self, count: int = 10, idle: float = 5.0,
              api: str = "", errors_only: bool = False
              ) -> Iterator[dict]:
        """Stream live trace entries (blocks until idle/count).
        `api` is a comma list of API names to keep; `errors_only`
        keeps failed calls (HTTP >= 400)."""
        query = {"count": str(count), "idle": str(idle)}
        if api:
            query["api"] = api
        if errors_only:
            query["err"] = "1"
        data = self._request("GET", "trace", query)
        for line in data.splitlines():
            if line.strip():
                yield json.loads(line)

    def trace_follow(self, count: int = 0, api: str = "",
                     errors_only: bool = False,
                     timeout: Optional[float] = None) -> Iterator[dict]:
        """The `mc admin trace` analog: a LIVE cluster-wide stream —
        the serving node grafts every peer's records in. Yields entry
        dicts as they arrive; ends at `count` entries (0 = until the
        connection drops / `timeout`). Unlike trace(), this reads the
        chunked response incrementally."""
        query = {"follow": "1", "count": str(count)}
        if api:
            query["api"] = api
        if errors_only:
            query["err"] = "1"
        return self._follow("trace", query, count, timeout)

    def _follow(self, sub: str, query: dict, count: int = 0,
                timeout: Optional[float] = None) -> Iterator[dict]:
        """Incremental ND-JSON reader behind the follow streams
        (trace_follow / events_follow): yields entry dicts as they
        arrive, skipping heartbeat blanks."""
        import hashlib as _hl
        qs = urllib.parse.urlencode(query)
        path = f"{ADMIN_PREFIX}/{sub}"
        hdrs = sig.sign_v4("GET", path,
                           {k: [v] for k, v in query.items()},
                           {"host": f"{self.host}:{self.port}"},
                           _hl.sha256(b"").hexdigest(), self.creds,
                           self.region)
        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=timeout if timeout is not None else self.timeout)
        try:
            conn.request("GET", f"{path}?{qs}", headers=hdrs)
            resp = conn.getresponse()
            if resp.status >= 300:
                raise AdminClientError(
                    resp.status, {"raw": resp.read().decode(
                        errors="replace")})
            sent = 0
            while True:
                # readline, not read(n): a chunked read(n) blocks for n
                # bytes while the stream trickles heartbeats
                line = resp.readline()
                if not line:
                    return
                if not line.strip():
                    continue                       # heartbeat
                yield json.loads(line)
                sent += 1
                if count and sent >= count:
                    return
        finally:
            conn.close()

    def cluster_trace(self) -> list[dict]:
        return self._json("GET", "trace/cluster")["entries"]

    def events(self, count: int = 0, classes: str = "",
               subsystems: str = "", severity: str = "",
               cluster: bool = False) -> list[dict]:
        """Recent journal entries. `classes`/`subsystems` are comma
        lists, `severity` a minimum (info/warn/error/crit);
        `cluster=True` merges every peer's window."""
        query = {"count": str(count)}
        if classes:
            query["class"] = classes
        if subsystems:
            query["sub"] = subsystems
        if severity:
            query["sev"] = severity
        if cluster:
            query["cluster"] = "1"
        return self._json("GET", "events", query)["events"]

    def events_follow(self, count: int = 0, classes: str = "",
                      subsystems: str = "", severity: str = "",
                      timeout: Optional[float] = None
                      ) -> Iterator[dict]:
        """LIVE journal stream with peer grafting — the `mc admin
        events` analog of trace_follow."""
        query = {"follow": "1", "count": str(count)}
        if classes:
            query["class"] = classes
        if subsystems:
            query["sub"] = subsystems
        if severity:
            query["sev"] = severity
        return self._follow("events", query, count, timeout)

    def incidents(self, cluster: bool = False) -> list[dict]:
        """Black-box bundle summaries, newest first."""
        query = {"cluster": "1"} if cluster else None
        return self._json("GET", "incidents", query)["incidents"]

    def incident(self, inc_id: str) -> dict:
        """One full bundle — served by whichever node holds it."""
        return self._json("GET", "incidents", {"id": inc_id})

    def slo(self) -> dict:
        """Burn-rate status per objective (the error-budget view)."""
        return self._json("GET", "slo")

    def spans(self, count: int = 50, sort: str = "recent",
              api: str = "", trace_id: str = "") -> dict:
        """Kept span trees (+ keep/drop counters). `api` filters to
        one API's roots, `trace_id` selects the tree a trace entry
        named, `sort=slowest` orders by duration."""
        query = {"count": str(count), "sort": sort}
        if api:
            query["api"] = api
        if trace_id:
            query["trace_id"] = trace_id
        return self._json("GET", "spans", query)

    def profiling_start(self, profiler_type: str = "cpu") -> dict:
        """profiler_type: comma list of 'cpu' (cProfile) and 'mem'
        (tracemalloc) — the reference's profilerType=cpu,mem."""
        return self._json("POST", "profiling/start",
                          {"profilerType": profiler_type})

    def profiling_stop(self, profiler_type: str = "cpu"
                       ) -> dict[str, str]:
        """Stop cluster-wide profiling; returns
        {profile-<kind>-<node>.txt: text} extracted from the server's
        zip (one entry per kind per node)."""
        import io
        import zipfile
        blob = self._request("POST", "profiling/stop",
                             {"profilerType": profiler_type})
        out: dict[str, str] = {}
        with zipfile.ZipFile(io.BytesIO(blob)) as zf:
            for name in zf.namelist():
                out[name] = zf.read(name).decode()
        return out

    def console_log(self, count: int = 0) -> list[dict]:
        """Merged cluster console-log ring entries."""
        return self._json("GET", "consolelog",
                          {"count": str(count)})["entries"]

    # -- service / quota / remote targets ----------------------------------

    def service_action(self, action: str) -> dict:
        """Cluster-wide service restart/stop (mc admin service)."""
        return self._json("POST", "service", {"action": action})

    def set_bucket_quota(self, bucket: str, quota: int,
                         quota_type: str = "hard") -> None:
        self._json("PUT", "set-bucket-quota", {"bucket": bucket},
                   body=json.dumps({"quota": quota,
                                    "quotatype": quota_type}).encode())

    def get_bucket_quota(self, bucket: str) -> dict:
        return self._json("GET", "get-bucket-quota", {"bucket": bucket})

    # -- active-active replication (minio_tpu/replicate/) ------------------

    def replicate_status(self) -> dict:
        """Site id, persisted target registry, plane stats, resync —
        plus per-target health under ``targets_status`` (queue depth,
        oldest-pending age, last-sync timestamp, last observed lag)."""
        return self._json("GET", "replicate")

    def replicate_key_versions(self, bucket: str, key: str) -> dict:
        """Every version of one key as replayable specs (the peer-sync
        read HTTPReplClient drives)."""
        return self._json("GET", "replicate/key",
                          {"bucket": bucket, "key": key})

    def add_replicate_target(self, bucket: str, host: str, port: int,
                             dest_bucket: str, access_key: str,
                             secret_key: str, prefix: str = "",
                             bw_bps: int = 0, arn: str = "",
                             update: bool = False) -> str:
        """Register an active-active wire target; returns its ARN.
        Updating an existing target requires passing its `arn` back
        (the server mints a fresh one otherwise, which would register
        a duplicate instead of replacing)."""
        doc = {"bucket": bucket, "dest_bucket": dest_bucket,
               "prefix": prefix, "bw_bps": bw_bps, "type": "s3",
               "params": {"host": host, "port": port,
                          "access_key": access_key,
                          "secret_key": secret_key}}
        if arn:
            doc["arn"] = arn
        out = self._json("PUT", "replicate/target",
                         {"update": "true"} if update else None,
                         json.dumps(doc).encode())
        return out["arn"]

    def remove_replicate_target(self, arn: str) -> None:
        self._request("DELETE", "replicate/target", {"arn": arn})

    def start_replicate_resync(self, arn: str) -> dict:
        return self._json("POST", "replicate/resync", {"arn": arn})

    def replicate_resync_status(self) -> dict:
        return self._json("GET", "replicate/resync")

    def cancel_replicate_resync(self) -> dict:
        return self._json("DELETE", "replicate/resync")

    def notify_status(self) -> dict:
        """Notification target registry, plane stats, and per-target
        delivery health under ``targets_status`` (backlog depth,
        offline window, last delivery lag)."""
        return self._json("GET", "notify")

    def add_notify_target(self, type: str = "webhook", name: str = "",
                          arn: str = "", update: bool = False,
                          **params) -> str:
        """Register an event notification target; returns its ARN.
        ``params`` is the type-specific config — ``endpoint`` (and
        optional ``auth_token``, ``timeout``) for webhooks, ``path``
        for log targets. Updating an existing target requires passing
        its ``arn`` back (the server mints a fresh one otherwise)."""
        doc = {"type": type, "params": params}
        if name:
            doc["name"] = name
        if arn:
            doc["arn"] = arn
        out = self._json("PUT", "notify/target",
                         {"update": "true"} if update else None,
                         json.dumps(doc).encode())
        return out["arn"]

    def remove_notify_target(self, arn: str) -> None:
        self._request("DELETE", "notify/target", {"arn": arn})

    def set_remote_target(self, bucket: str, host: str, port: int,
                          target_bucket: str, access_key: str,
                          secret_key: str, region: str = "us-east-1"
                          ) -> str:
        """Register a replication destination; returns its ARN."""
        return self._json(
            "PUT", "set-remote-target", {"bucket": bucket},
            body=json.dumps({"host": host, "port": port,
                             "targetbucket": target_bucket,
                             "accesskey": access_key,
                             "secretkey": secret_key,
                             "region": region}).encode())["arn"]

    def list_remote_targets(self, bucket: str) -> list[dict]:
        return json.loads(self._request(
            "GET", "list-remote-targets", {"bucket": bucket}))

    def remove_remote_target(self, bucket: str, arn: str) -> None:
        self._json("DELETE", "remove-remote-target",
                   {"bucket": bucket, "arn": arn})

    def obd_info(self) -> list[dict]:
        """Per-node OBD bundles (drive latency probes, cpu/mem)."""
        return self._json("GET", "obdinfo")["nodes"]

    def drive_health(self) -> dict:
        """Gray-failure plane snapshot: per-drive/per-peer tracked
        latency + quarantine states + recent transition events."""
        return self._json("GET", "drivehealth")

    def bandwidth(self) -> dict:
        """Cluster-merged per-bucket byte rates/totals."""
        return self._json("GET", "bandwidth")["buckets"]
