"""Per-stage wall-time accounting for the live-server data path.

The reference answers "where does a PUT spend its time" with pprof; this
build needs the same answer without a profiler attached: bench_e2e.py
enables the collector, the hot path marks stages (auth, hash-reader,
split, encode, shard write, commit, lock), and the bench prints the
aggregate breakdown. Disabled (the default) the cost is one dict lookup
and an `if` per stage — safe to leave in production paths.

Stages nest across threads; each accumulates exclusive wall time per
(name) key with a call count, summed over all threads.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict

ENABLED = False

_lock = threading.Lock()
_acc: "defaultdict[str, list]" = defaultdict(lambda: [0.0, 0])


class _Stage:
    __slots__ = ("name", "t0")

    def __init__(self, name: str):
        self.name = name
        self.t0 = 0.0

    def __enter__(self):
        if ENABLED:
            self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if ENABLED:
            dt = time.perf_counter() - self.t0
            with _lock:
                slot = _acc[self.name]
                slot[0] += dt
                slot[1] += 1
        return False


def stage(name: str) -> _Stage:
    return _Stage(name)


def add(name: str, seconds: float, count: int = 1) -> None:
    """Record time measured externally (e.g. inside a hashing thread)."""
    if ENABLED:
        with _lock:
            slot = _acc[name]
            slot[0] += seconds
            slot[1] += count


def enable() -> None:
    global ENABLED
    ENABLED = True


def disable() -> None:
    global ENABLED
    ENABLED = False


def reset() -> None:
    with _lock:
        _acc.clear()


def report() -> Dict[str, dict]:
    """name -> {seconds, calls}, sorted by descending time."""
    with _lock:
        items = sorted(_acc.items(), key=lambda kv: -kv[1][0])
        return {k: {"seconds": round(v[0], 4), "calls": v[1]}
                for k, v in items}
