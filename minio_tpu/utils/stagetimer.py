"""Per-stage wall-time accounting for the live-server data path.

The reference answers "where does a PUT spend its time" with pprof; this
build needs the same answer without a profiler attached: bench_e2e.py
enables the collector, the hot path marks stages (auth, hash-reader,
split, encode, shard write, commit, lock), and the bench prints the
aggregate breakdown. Disabled (the default) the cost is one dict lookup
and an `if` per stage — safe to leave in production paths.

Stages nest across threads; each accumulates exclusive wall time per
(name) key with a call count, summed over all threads. When enabled,
per-call durations are additionally sampled (bounded reservoir) so the
bench can report p50/p99 latencies, not just means.

Overlap accounting (the pipelined data path's observable): the pipeline
records, per stream, the WALL time of the whole pipelined section and
the SUM of its stage times. stage_sum > wall means the stages actually
ran concurrently; stage_sum / wall is the effective parallelism. Always
on (a few adds per stream) — `overlap_report()` reads it back.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict

ENABLED = False

# per-stage duration samples kept for percentiles (per stage name);
# beyond the cap only sums/counts accumulate — the bench's runs fit
SAMPLE_CAP = 8192

_lock = threading.Lock()
_acc: "defaultdict[str, list]" = defaultdict(lambda: [0.0, 0])
_samples: "defaultdict[str, list]" = defaultdict(list)
# name -> [wall_s, stage_s, streams]
_overlap: "defaultdict[str, list]" = defaultdict(lambda: [0.0, 0.0, 0])


class _Stage:
    __slots__ = ("name", "t0")

    def __init__(self, name: str):
        self.name = name
        self.t0 = 0.0

    def __enter__(self):
        if ENABLED:
            self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if ENABLED:
            dt = time.perf_counter() - self.t0
            with _lock:
                slot = _acc[self.name]
                slot[0] += dt
                slot[1] += 1
                s = _samples[self.name]
                if len(s) < SAMPLE_CAP:
                    s.append(dt)
        return False


def stage(name: str) -> _Stage:
    return _Stage(name)


def add(name: str, seconds: float, count: int = 1) -> None:
    """Record time measured externally (e.g. inside a hashing thread)."""
    if ENABLED:
        with _lock:
            slot = _acc[name]
            slot[0] += seconds
            slot[1] += count
            s = _samples[name]
            if len(s) < SAMPLE_CAP:
                s.append(seconds / max(count, 1))


def add_overlap(name: str, wall_s: float, stage_s: float) -> None:
    """Record one pipelined stream: its wall time vs the summed time of
    its stages. Always on — the pipeline metrics read this back."""
    with _lock:
        slot = _overlap[name]
        slot[0] += wall_s
        slot[1] += stage_s
        slot[2] += 1


def enable() -> None:
    global ENABLED
    ENABLED = True


def disable() -> None:
    global ENABLED
    ENABLED = False


def reset() -> None:
    with _lock:
        _acc.clear()
        _samples.clear()
        _overlap.clear()


def report() -> Dict[str, dict]:
    """name -> {seconds, calls}, sorted by descending time."""
    with _lock:
        items = sorted(_acc.items(), key=lambda kv: -kv[1][0])
        return {k: {"seconds": round(v[0], 4), "calls": v[1]}
                for k, v in items}


def percentiles() -> Dict[str, dict]:
    """name -> {p50_ms, p99_ms, n} from the sampled per-call durations
    (requires ENABLED during the measured window)."""
    out: Dict[str, dict] = {}
    with _lock:
        snap = {k: list(v) for k, v in _samples.items() if v}
    for name, xs in sorted(snap.items()):
        xs.sort()
        n = len(xs)
        out[name] = {
            "p50_ms": round(xs[n // 2] * 1e3, 3),
            "p99_ms": round(xs[min(n - 1, (n * 99) // 100)] * 1e3, 3),
            "n": n,
        }
    return out


def overlap_report() -> Dict[str, dict]:
    """name -> {wall_s, stage_s, overlap_x, streams}: how much the
    pipelined sections actually overlapped (overlap_x = stage_s/wall_s;
    1.0 means fully serial)."""
    with _lock:
        return {k: {"wall_s": round(v[0], 4),
                    "stage_s": round(v[1], 4),
                    "overlap_x": round(v[1] / v[0], 3) if v[0] else 0.0,
                    "streams": v[2]}
                for k, v in _overlap.items()}
