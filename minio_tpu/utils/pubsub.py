"""In-process pub/sub hub (reference pkg/pubsub): trace and console-log
streams fan out to any number of subscribers; slow subscribers drop
messages rather than block publishers."""

from __future__ import annotations

import queue
import threading
from typing import Optional


class PubSub:
    def __init__(self, buffer: int = 1000):
        self._mu = threading.Lock()
        self._subs: list[queue.Queue] = []
        self.buffer = buffer

    def subscribe(self) -> "Subscription":
        q: queue.Queue = queue.Queue(maxsize=self.buffer)
        with self._mu:
            self._subs.append(q)
        return Subscription(self, q)

    def _unsubscribe(self, q: queue.Queue) -> None:
        with self._mu:
            try:
                self._subs.remove(q)
            except ValueError:
                pass

    def publish(self, item) -> None:
        with self._mu:
            subs = list(self._subs)
        for q in subs:
            try:
                q.put_nowait(item)
            except queue.Full:
                pass                    # slow subscriber: drop

    @property
    def subscriber_count(self) -> int:
        with self._mu:
            return len(self._subs)


class Subscription:
    def __init__(self, hub: PubSub, q: queue.Queue):
        self._hub = hub
        self._q = q

    def get(self, timeout: Optional[float] = None):
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def close(self) -> None:
        self._hub._unsubscribe(self._q)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
