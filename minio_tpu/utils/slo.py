"""slo — declarative per-API-class objectives + multi-window burn rates.

The reference answers "is the service healthy" with liveness probes;
an object store serving millions of users needs the SRE answer
instead: per-API-class OBJECTIVES (availability, latency) with error
budgets, evaluated as burn rates over several windows at once — a
fast-burning short window catches an outage in seconds, a slow long
window catches the quiet leak that would exhaust the month's budget.

Everything derives from telemetry the request path already pays for:

* availability — ``minio_tpu_http_responses_total{api, code_class}``
  (5xx = budget spend);
* latency — the ``minio_tpu_http_requests_duration_seconds``
  histogram's bucket counts (requests over the class threshold =
  budget spend). Thresholds default to exact bucket boundaries so the
  over-threshold count is exact, not interpolated.

The engine snapshots cumulative totals on a cadence, diffs snapshots
per window, and exposes ``minio_tpu_slo_burn_rate{objective,window}``
and ``minio_tpu_slo_error_budget_ratio{objective}`` gauges. A burn
rate crossing MINIO_TPU_SLO_BURN_THRESHOLD (with enough samples in
the window) emits an ``slo.breach`` journal event — the black-box
recorder's primary trigger — and clears at half the threshold
(hysteresis: a rate hovering at the line must not flap
breach/clear/breach).

Knobs: README "Incident plane".
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from . import eventlog, knobs, telemetry

# API-class membership: the S3 data-plane calls only. Internal and
# admin surfaces (including this plane's own streaming endpoints) are
# excluded — an idling `mc admin trace` must not spend read budget.
_EXCLUDED_APIS = frozenset({
    "Admin", "Health", "Metrics", "WebUI", "PeerRPC", "StorageRPC",
    "STS",
})

_BURN = telemetry.REGISTRY.gauge(
    "minio_tpu_slo_burn_rate",
    "Error-budget burn rate per objective and window (1.0 = spending "
    "exactly the budget; above the threshold knob = breach)")
_BUDGET = telemetry.REGISTRY.gauge(
    "minio_tpu_slo_error_budget_ratio",
    "Error budget remaining per objective over the longest window "
    "(1 = untouched, 0 = fully burned)")


def api_class(api: str) -> Optional[str]:
    """'read' / 'write' / None (excluded from objectives)."""
    if not api or api in _EXCLUDED_APIS:
        return None
    if api.startswith(("Get", "Head", "List")):
        return "read"
    return "write"


def _windows() -> List[float]:
    out = []
    for part in knobs.get_str("MINIO_TPU_SLO_WINDOWS_S").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            w = float(part)
        except ValueError:
            continue
        if w > 0:
            out.append(w)
    return sorted(out) or [60.0, 300.0]


class _Totals:
    """Cumulative (requests, errors, slow) per class at one instant."""

    __slots__ = ("ts", "cls")

    def __init__(self, ts: float, cls: Dict[str, List[int]]):
        self.ts = ts
        self.cls = cls


class SLOEngine:
    """Snapshot → diff → burn-rate evaluator. One per process (the
    metrics registry it reads is process-global); ``ensure_started``
    is idempotent so multi-node-in-process tests boot it once."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._responses = telemetry.REGISTRY.counter(
            "minio_tpu_http_responses_total")
        self._duration = telemetry.REGISTRY.histogram(
            "minio_tpu_http_requests_duration_seconds")
        self._snaps: "deque[_Totals]" = deque(maxlen=256)
        self._breached: Dict[str, dict] = {}    # objective -> info
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._last_status: dict = {}

    # -- lifecycle ---------------------------------------------------------

    def ensure_started(self) -> None:
        with self._mu:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="slo-eval")
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(
                timeout=knobs.get_float("MINIO_TPU_SLO_EVAL_S")):
            if not knobs.get_bool("MINIO_TPU_SLO"):
                continue
            try:
                self.evaluate_once()
            except Exception:  # noqa: BLE001 — evaluation is passive
                pass

    # -- collection --------------------------------------------------------

    def _collect(self, now: float) -> _Totals:
        cls: Dict[str, List[int]] = {"read": [0, 0, 0],
                                     "write": [0, 0, 0]}
        for key, v in self._responses.series().items():
            labels = dict(key)
            c = api_class(labels.get("api", ""))
            if c is None:
                continue
            cls[c][0] += int(v)
            if labels.get("code_class") == "5xx":
                cls[c][1] += int(v)
        thresholds = {
            "read": knobs.get_float("MINIO_TPU_SLO_LAT_READ_MS") / 1e3,
            "write": knobs.get_float("MINIO_TPU_SLO_LAT_WRITE_MS") / 1e3,
        }
        buckets = self._duration.buckets
        for key, (counts, _total, _count) in \
                self._duration.series_snapshot().items():
            labels = dict(key)
            c = api_class(labels.get("api", ""))
            if c is None:
                continue
            # bucket i holds observations in (buckets[i-1], buckets[i]]
            # — everything from the first boundary PAST the threshold
            # is over it (thresholds default to exact boundaries)
            idx = bisect.bisect_right(buckets, thresholds[c])
            cls[c][2] += sum(counts[idx:])
        return _Totals(now, cls)

    # -- evaluation --------------------------------------------------------

    @staticmethod
    def _objectives() -> List[dict]:
        avail_budget = max(
            1e-9, 1 - knobs.get_float("MINIO_TPU_SLO_AVAIL_TARGET")
            / 100.0)
        lat_budget = max(
            1e-9, 1 - knobs.get_float("MINIO_TPU_SLO_LAT_TARGET")
            / 100.0)
        out = []
        for c in ("read", "write"):
            out.append({"name": f"{c}-availability", "cls": c,
                        "kind": "availability", "budget": avail_budget})
            out.append({"name": f"{c}-latency", "cls": c,
                        "kind": "latency", "budget": lat_budget})
        return out

    def _baseline(self, now: float, window: float) -> Optional[_Totals]:
        """Newest snapshot at least `window` old — None until the ring
        spans the window (a half-filled window must not alert)."""
        base = None
        for snap in self._snaps:
            if now - snap.ts >= window:
                base = snap
            else:
                break
        return base

    def evaluate_once(self, now: Optional[float] = None) -> dict:
        """One snapshot + burn-rate pass; returns (and retains) the
        /slo status document. Split out of the loop so tests drive
        evaluation synchronously."""
        now = time.time() if now is None else now
        cur = self._collect(now)
        with self._mu:
            self._snaps.append(cur)
        windows = _windows()
        threshold = knobs.get_float("MINIO_TPU_SLO_BURN_THRESHOLD")
        min_samples = knobs.get_int("MINIO_TPU_SLO_MIN_SAMPLES")
        objectives = []
        for obj in self._objectives():
            name, c, kind = obj["name"], obj["cls"], obj["kind"]
            budget = obj["budget"]
            bad_idx = 1 if kind == "availability" else 2
            win_stats: Dict[str, dict] = {}
            worst = (0.0, "")              # (burn, window label)
            breach_now = False
            for w in windows:
                base = self._baseline(now, w)
                if base is None:
                    continue
                reqs = cur.cls[c][0] - base.cls[c][0]
                bad = cur.cls[c][bad_idx] - base.cls[c][bad_idx]
                burn = (bad / reqs) / budget if reqs > 0 else 0.0
                label = f"{int(w)}s"
                win_stats[label] = {"burn": round(burn, 3),
                                    "samples": reqs}
                _BURN.set(round(burn, 6), objective=name,
                          window=label)
                if burn > worst[0]:
                    worst = (burn, label)
                if reqs >= min_samples and burn >= threshold:
                    breach_now = True
            remaining = max(0.0, 1.0 - min(1.0, worst[0]))
            _BUDGET.set(round(remaining, 6), objective=name)
            was = name in self._breached
            if breach_now and not was:
                self._breached[name] = {"window": worst[1],
                                        "burn": round(worst[0], 3),
                                        "since": now}
                eventlog.emit("slo.breach", objective=name,
                              window=worst[1],
                              burn=round(worst[0], 3))
            elif was and win_stats and worst[0] < threshold / 2.0:
                # hysteresis: clear only once every window cooled to
                # half the trip point
                del self._breached[name]
                eventlog.emit("slo.clear", objective=name)
            objectives.append({
                "objective": name, "class": c, "kind": kind,
                "budget": round(budget, 6),
                "windows": win_stats,
                "breached": name in self._breached,
                "breach": self._breached.get(name),
                "budget_remaining": round(remaining, 3),
            })
        status = {
            "enabled": knobs.get_bool("MINIO_TPU_SLO"),
            "burn_threshold": threshold,
            "windows_s": windows,
            "objectives": objectives,
        }
        with self._mu:
            self._last_status = status
        return status

    def status(self) -> dict:
        """The last evaluated document (admin /slo + incident
        bundles); evaluates once if the engine never ran."""
        with self._mu:
            last = self._last_status
        if last:
            return last
        return self.evaluate_once()

    def reset(self) -> None:
        """Forget snapshots and breach state (test isolation)."""
        with self._mu:
            self._snaps.clear()
            self._breached.clear()
            self._last_status = {}


ENGINE = SLOEngine()
