"""SipHash-2-4 (64-bit) — the object→set routing hash.

Bit-identical to the SipHash-2-4 the reference routes with
(cmd/erasure-sets.go:590 sipHashMod over the deployment-ID key):
placement compatibility requires exact agreement, so this is the
standard Aumasson–Bernstein construction, validated against the
published reference vectors (tests/test_sets.py).
"""

from __future__ import annotations

MASK = 0xFFFFFFFFFFFFFFFF


def _rotl(x: int, b: int) -> int:
    return ((x << b) | (x >> (64 - b))) & MASK


def siphash24(key: bytes, data: bytes) -> int:
    """SipHash-2-4 with a 16-byte key -> 64-bit digest."""
    if len(key) != 16:
        raise ValueError("siphash key must be 16 bytes")
    k0 = int.from_bytes(key[:8], "little")
    k1 = int.from_bytes(key[8:], "little")
    v0 = k0 ^ 0x736F6D6570736575
    v1 = k1 ^ 0x646F72616E646F6D
    v2 = k0 ^ 0x6C7967656E657261
    v3 = k1 ^ 0x7465646279746573

    def sipround():
        nonlocal v0, v1, v2, v3
        v0 = (v0 + v1) & MASK
        v1 = _rotl(v1, 13)
        v1 ^= v0
        v0 = _rotl(v0, 32)
        v2 = (v2 + v3) & MASK
        v3 = _rotl(v3, 16)
        v3 ^= v2
        v0 = (v0 + v3) & MASK
        v3 = _rotl(v3, 21)
        v3 ^= v0
        v2 = (v2 + v1) & MASK
        v1 = _rotl(v1, 17)
        v1 ^= v2
        v2 = _rotl(v2, 32)

    n = len(data)
    end = n - (n % 8)
    for off in range(0, end, 8):
        m = int.from_bytes(data[off:off + 8], "little")
        v3 ^= m
        sipround()
        sipround()
        v0 ^= m

    b = (n & 0xFF) << 56
    tail = data[end:]
    for i, c in enumerate(tail):
        b |= c << (8 * i)
    v3 ^= b
    sipround()
    sipround()
    v0 ^= b

    v2 ^= 0xFF
    sipround()
    sipround()
    sipround()
    sipround()
    return (v0 ^ v1 ^ v2 ^ v3) & MASK


def sip_hash_mod(key: str, cardinality: int, id16: bytes) -> int:
    """Object name -> set index (reference sipHashMod,
    cmd/erasure-sets.go:590)."""
    if cardinality <= 0:
        return -1
    return siphash24(id16, key.encode()) % cardinality


def crc_hash_mod(key: str, cardinality: int) -> int:
    """Legacy CRCMOD routing (cmd/erasure-sets.go:599)."""
    import zlib
    if cardinality <= 0:
        return -1
    return zlib.crc32(key.encode()) % cardinality
