def host_port(addr: str, default_port: int) -> tuple[str, int]:
    """Split "host[:port]" robustly: a bare hostname gets the default
    port (a naive rpartition(":") would misparse it as the port)."""
    host, sep, port = addr.rpartition(":")
    if not sep:
        return addr, default_port
    try:
        return host, int(port)
    except ValueError:
        return addr, default_port


def backoff_delay(base: float, cap: float, attempt: int) -> float:
    """Capped exponential backoff with half-jitter: attempt 0 -> ~base,
    doubling per attempt up to `cap`, scaled by a uniform factor in
    [0.5, 1.0) so synchronized retriers de-correlate (the single home
    of the retry-delay formula: RPC retries, MRF heal requeues)."""
    import random
    return min(cap, base * (2 ** attempt)) * (0.5 + random.random() / 2)
