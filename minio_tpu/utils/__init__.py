def host_port(addr: str, default_port: int) -> tuple[str, int]:
    """Split "host[:port]" robustly: a bare hostname gets the default
    port (a naive rpartition(":") would misparse it as the port)."""
    host, sep, port = addr.rpartition(":")
    if not sep:
        return addr, default_port
    try:
        return host, int(port)
    except ValueError:
        return addr, default_port
