"""Federated Prometheus exposition: parse + cluster-wide merge.

The reference aggregates every node's metrics into one scrape at
``/minio/v2/metrics/cluster`` (cmd/metrics-v2.go): Prometheus sees ONE
endpoint instead of N, and the operator's dashboards need no per-node
relabeling. Here the admin ``GET /minio/admin/v3/metrics?cluster=1``
fans out over peer RPC for each node's text exposition and merges them
with these rules:

  * **counters** are SUMMED per label set across nodes (`rate()` over
    the merged family is the cluster rate — a `node` label would force
    every dashboard to `sum by ()` first);
  * **gauges** carry a ``node`` label per origin (summing instantaneous
    values like queue depth across nodes destroys the signal an
    operator pages on — WHICH node is saturated);
  * **histograms** merge BUCKET-WISE: per label set, each `le` bucket's
    cumulative count, `_sum` and `_count` are summed across nodes
    (cluster-wide quantiles stay computable; nodes share code so bucket
    edges agree, and a disagreeing edge simply contributes its own `le`
    series — cumulative counts remain monotone per node-set);
  * **untyped** families are treated like gauges (origin matters when
    the kind is unknown).

Parsing is deliberately tolerant: a malformed line from a peer drops
that line, never the scrape — a degraded merge beats a failed one.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

__all__ = ["ParsedFamily", "parse_exposition", "merge_expositions"]

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


_UNESCAPE_RE = re.compile(r"\\(.)")
_UNESCAPE_MAP = {"n": "\n", '"': '"', "\\": "\\"}


def _unescape(v: str) -> str:
    # single pass: sequential .replace() corrupts values containing a
    # backslash (the '\\' pair's second byte + 'n' would read as '\n')
    return _UNESCAPE_RE.sub(
        lambda m: _UNESCAPE_MAP.get(m.group(1), "\\" + m.group(1)), v)


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _parse_value(s: str) -> Optional[float]:
    if s == "+Inf":
        return float("inf")
    if s == "-Inf":
        return float("-inf")
    try:
        return float(s)
    except ValueError:
        return None


class ParsedFamily:
    """One family from a text exposition: kind, help, and samples as
    (sample_name, label_key_tuple) -> value."""

    __slots__ = ("name", "kind", "help", "samples")

    def __init__(self, name: str):
        self.name = name
        self.kind = "untyped"
        self.help = ""
        self.samples: Dict[Tuple[str, tuple], float] = {}


def _family_of(name: str, fams: Dict[str, ParsedFamily]
               ) -> Optional[str]:
    """Map a sample name to its family: exact, or histogram suffix."""
    if name in fams:
        return name
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if base in fams and fams[base].kind == "histogram":
                return base
    return None


def parse_exposition(text: str) -> Dict[str, ParsedFamily]:
    """Text exposition -> {family name: ParsedFamily}. Samples whose
    family never declared a # TYPE get an untyped family of their own
    name; malformed lines are skipped."""
    fams: Dict[str, ParsedFamily] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) >= 3:
                fam = fams.setdefault(parts[2], ParsedFamily(parts[2]))
                fam.help = parts[3] if len(parts) > 3 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) >= 4:
                fam = fams.setdefault(parts[2], ParsedFamily(parts[2]))
                fam.kind = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        value = _parse_value(m.group("value"))
        if value is None:
            continue
        name = m.group("name")
        labels = tuple(sorted(
            (k, _unescape(v))
            for k, v in _LABEL_RE.findall(m.group("labels") or "")))
        fam_name = _family_of(name, fams)
        if fam_name is None:
            fam_name = name
            fams.setdefault(name, ParsedFamily(name))
        fams[fam_name].samples[(name, labels)] = value
    return fams


def _render_labels(labels: tuple) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{_escape(str(v))}"'
                          for k, v in labels) + "}"


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def merge_expositions(nodes: List[Tuple[str, str]]) -> str:
    """Merge per-node text expositions into one cluster exposition.

    ``nodes`` is [(node_name, exposition_text)]; the first entry is
    conventionally the serving node. Counters sum, gauges/untyped gain
    a ``node`` label, histograms merge bucket-wise (see module doc).
    """
    merged: Dict[str, ParsedFamily] = {}
    for node, text in nodes:
        for name, fam in parse_exposition(text).items():
            out = merged.get(name)
            if out is None:
                out = merged[name] = ParsedFamily(name)
                out.kind = fam.kind
                out.help = fam.help
            elif out.kind == "untyped" and fam.kind != "untyped":
                out.kind = fam.kind
                out.help = out.help or fam.help
            for (sname, labels), value in fam.samples.items():
                if out.kind in ("counter", "histogram"):
                    key = (sname, labels)
                    out.samples[key] = out.samples.get(key, 0) + value
                else:
                    key = (sname, tuple(sorted(
                        labels + (("node", node),))))
                    out.samples[key] = value
    lines: List[str] = []
    for fam in sorted(merged.values(), key=lambda f: f.name):
        lines.append(f"# HELP {fam.name} {fam.help}".rstrip())
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for (sname, labels), value in sorted(fam.samples.items(),
                                             key=_sample_sort_key):
            lines.append(f"{sname}{_render_labels(labels)} {_fmt(value)}")
    return "\n".join(lines) + "\n"


def _sample_sort_key(item):
    """Stable sample order with histogram buckets ascending by `le`
    (lexical label sort would put +Inf first and unsorted buckets
    confuse scrapers): group by the non-le labels, then bucket series
    numerically, then _sum/_count after the buckets."""
    (sname, labels), _value = item
    le = None
    rest = []
    for k, v in labels:
        if k == "le":
            le = _parse_value(v)
        else:
            rest.append((k, v))
    order = 1 if le is not None else 2
    return (sname.rsplit("_bucket", 1)[0], tuple(rest), order,
            le if le is not None else 0.0, sname)
