"""Ellipses expansion + erasure-set sizing for CLI drive/endpoint args.

The reference's `minio server /data/d{1...16}` syntax (pkg/ellipses +
cmd/endpoint-ellipses.go): every `{a...b}` range in an argument expands
multiplicatively, and the total drive count is divided into erasure sets
of 4..16 drives preferring the largest symmetric divisor
(possibleSetCountsWithSymmetry / commonSetDriveCount,
cmd/endpoint-ellipses.go:67-91).
"""

from __future__ import annotations

import re

_ELLIPSIS = re.compile(r"\{(\d+)\.\.\.(\d+)\}")

SET_SIZES = tuple(range(4, 17))  # valid set drive counts (4..16)


def has_ellipses(*args: str) -> bool:
    return any(_ELLIPSIS.search(a) for a in args)


def expand_arg(arg: str) -> list[str]:
    """Expand every {a...b} range in `arg` (cartesian, left-to-right).

    Numbers keep their zero-padding width ({01...04} -> 01 02 03 04).
    """
    m = _ELLIPSIS.search(arg)
    if not m:
        return [arg]
    lo, hi = m.group(1), m.group(2)
    start, end = int(lo), int(hi)
    if end < start:
        raise ValueError(f"bad ellipsis range in {arg!r}")
    width = len(lo) if lo.startswith("0") else 0
    out = []
    for v in range(start, end + 1):
        s = str(v).rjust(width, "0") if width else str(v)
        out.extend(expand_arg(arg[:m.start()] + s + arg[m.end():]))
    return out


def expand_args(args: list[str]) -> list[str]:
    out: list[str] = []
    for a in args:
        out.extend(expand_arg(a))
    return out


def greatest_set_size(total: int, node_counts: list[int] | None = None
                      ) -> int:
    """Pick the erasure-set drive count: the largest divisor of `total`
    in 4..16 that also keeps per-node symmetry when node drive counts are
    given (every node's drive count must divide evenly into sets — the
    reference's possibleSetCountsWithSymmetry intent).
    """
    candidates = [s for s in SET_SIZES if total % s == 0]
    if node_counts:
        n_nodes = len(node_counts)
        sym = []
        for s in candidates:
            # symmetric when each set's drives spread evenly over nodes
            # (s divisible by node count) or each node contributes whole
            # sets (node drive count divisible by s)
            if s % n_nodes == 0 or all(c % s == 0 for c in node_counts):
                sym.append(s)
        if sym:
            candidates = sym
    if not candidates:
        raise ValueError(
            f"drive count {total} is not divisible into erasure sets of "
            f"4..16 drives")
    return max(candidates)


def divide_into_sets(total: int, node_counts: list[int] | None = None
                     ) -> tuple[int, int]:
    """(set_count, set_drive_count) for `total` drives."""
    size = greatest_set_size(total, node_counts)
    return total // size, size
