"""Latency-aware drive/peer health tracking — the gray-failure plane.

A production store is dominated not by components that die but by
components that are *slow while still answering*: a drive doing 500 ms
I/Os, a peer behind a saturated NIC ("The Tail at Scale", Dean &
Barroso; "Gray Failure", Huang et al.). The binary online/offline
health model (DiskMonitor, transport probes) cannot see them, so this
module keeps per-entity windowed latency and derives three behaviors
from it:

  * **Adaptive hedge deadlines** — the GET shard-read state machine
    races a spare shard read against any reader slower than
    ``healthy p95 × MINIO_TPU_HEDGE_K`` (clamped to floor/ceiling
    knobs) instead of waiting on errors alone (engine
    ``_read_group_shards_raw``).
  * **Quorum-ack write stalls** — PUT/multipart fan-outs ack once
    write-quorum drives are durable and abandon laggards past
    ``healthy p95 × MINIO_TPU_WRITE_STALL_K`` to a background lane
    that feeds the MRF heal queue (``metadata.for_each_disk_quorum``).
  * **Slow-drive quarantine** — DiskMonitor consults
    ``should_quarantine`` and walks drives through the
    ok → suspect → probation → ok state machine; suspect/probation
    drives are excluded from read plans and hedge targets
    (capacity-permitting) while still being written-and-MRF'd.

Every observation lands in ``minio_tpu_drive_latency_seconds{disk,
verb}`` / ``minio_tpu_peer_latency_seconds{peer,verb}`` histograms and
the ``minio_tpu_drive_health{disk}`` gauge mirrors the state machine
(0 = ok, 1 = suspect, 2 = probation), so the gray-failure plane is as
observable as the crash plane.

Deadlines are derived at call time (knobs are env-read-at-call like
everywhere else) and fall back to the CEILING when no samples exist
yet — a cold process must not hedge or abandon spuriously.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from . import eventlog, knobs, telemetry

__all__ = [
    "STATE_OK", "STATE_SUSPECT", "STATE_PROBATION",
    "HealthTracker", "TRACKER", "disk_key",
    "observe_disk", "observe_peer", "is_suspect_disk",
    "read_hedge_s", "write_stall_s", "hedging_enabled",
    "quorum_ack_enabled", "quarantine_enabled",
    "note_hedge", "note_laggard",
]

STATE_OK = "ok"
STATE_SUSPECT = "suspect"
STATE_PROBATION = "probation"
_STATE_NUM = {STATE_OK: 0, STATE_SUSPECT: 1, STATE_PROBATION: 2}

# sub-ms to tens-of-seconds: drive I/O spans tmpfs (~50 µs) to a
# gray-failing spindle (~seconds)
_LAT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_DRIVE_LAT = telemetry.REGISTRY.histogram(
    "minio_tpu_drive_latency_seconds",
    "Per-drive storage-verb latency (feeds hedge deadlines and "
    "slow-drive quarantine)", buckets=_LAT_BUCKETS)
_PEER_LAT = telemetry.REGISTRY.histogram(
    "minio_tpu_peer_latency_seconds",
    "Per-peer internode RPC latency (feeds the gray-failure health "
    "snapshot)", buckets=_LAT_BUCKETS)
_HEDGED = telemetry.REGISTRY.counter(
    "minio_tpu_hedged_reads_total",
    "Spare shard reads raced against slow/failed readers, by trigger "
    "(latency = hedge deadline expired, error = reader failed)")
_LAGGARDS = telemetry.REGISTRY.counter(
    "minio_tpu_write_laggards_total",
    "Shard-write fan-out stragglers abandoned to the background lane "
    "after quorum ack (each feeds the MRF degraded-write queue)")
_QUAR = telemetry.REGISTRY.counter(
    "minio_tpu_drive_quarantine_total",
    "Slow-drive quarantine state transitions, by event "
    "(suspect/probation/readmit)")
_HEALTH = telemetry.REGISTRY.gauge(
    "minio_tpu_drive_health",
    "Drive health state from the latency tracker "
    "(0 = ok, 1 = suspect, 2 = probation)")

# verbs whose latency drives the quarantine decision (probe latency is
# tracked separately: it must only prove RECOVERY, never re-convict a
# drive out of stale traffic samples)
_DECISION_VERBS = ("read", "write")


def disk_key(disk) -> str:
    """Stable identity of a drive across its wrapper chain
    (DiskIDCheck / NaughtyDisk / RemoteStorage all delegate
    ``endpoint()`` to the innermost drive)."""
    try:
        return disk.endpoint()
    except Exception:  # noqa: BLE001 — identity probe only
        return str(disk)


class _Window:
    """Fixed-size sample ring; percentile over whatever is retained."""

    __slots__ = ("cap", "buf", "idx")

    def __init__(self, cap: int):
        self.cap = max(4, cap)
        self.buf: List[float] = []
        self.idx = 0

    def add(self, v: float) -> None:
        if len(self.buf) < self.cap:
            self.buf.append(v)
        else:
            self.buf[self.idx] = v
            self.idx = (self.idx + 1) % self.cap

    def values(self) -> List[float]:
        return list(self.buf)


def _pct(vals: List[float], q: float) -> Optional[float]:
    if not vals:
        return None
    s = sorted(vals)
    i = min(len(s) - 1, max(0, int(q * len(s))))
    return s[i]


class _Entity:
    __slots__ = ("kind", "key", "windows", "state", "state_since",
                 "probes_ok", "ewma")

    def __init__(self, kind: str, key: str):
        self.kind = kind
        self.key = key
        self.windows: Dict[str, _Window] = {}
        self.state = STATE_OK
        self.state_since = time.monotonic()
        self.probes_ok = 0
        self.ewma: Dict[str, float] = {}


class HealthTracker:
    """Process-global latency + health-state registry, keyed by
    (kind, key) where kind ∈ {"drive", "peer"}."""

    EWMA_ALPHA = 0.2

    def __init__(self):
        self._mu = threading.Lock()
        self._entities: Dict[Tuple[str, str], _Entity] = {}
        telemetry.REGISTRY.register_collector(self._collect)

    # -- feeding -----------------------------------------------------------

    def observe(self, kind: str, key: str, verb: str,
                seconds: float) -> None:
        with self._mu:
            e = self._entities.get((kind, key))
            if e is None:
                e = self._entities[(kind, key)] = _Entity(kind, key)
            w = e.windows.get(verb)
            if w is None:
                w = e.windows[verb] = _Window(
                    knobs.get_int("MINIO_TPU_LAT_WINDOW"))
            w.add(seconds)
            prev = e.ewma.get(verb)
            e.ewma[verb] = seconds if prev is None else \
                prev + self.EWMA_ALPHA * (seconds - prev)
        if kind == "drive":
            _DRIVE_LAT.observe(seconds, disk=key, verb=verb)
        else:
            _PEER_LAT.observe(seconds, peer=key, verb=verb)

    # -- querying ----------------------------------------------------------

    def _samples(self, e: _Entity, verbs) -> List[float]:
        out: List[float] = []
        for v in (verbs or e.windows):
            w = e.windows.get(v)
            if w is not None:
                out.extend(w.buf)
        return out

    def percentile(self, kind: str, key: str, q: float,
                   verbs=None) -> Optional[float]:
        with self._mu:
            e = self._entities.get((kind, key))
            if e is None:
                return None
            return _pct(self._samples(e, verbs), q)

    def healthy_percentile(self, kind: str, q: float, verbs=None,
                           exclude: str = "") -> Optional[float]:
        """Pooled percentile across entities in state OK — the
        "healthy baseline" hedge deadlines and quarantine ratios
        compare against."""
        vals: List[float] = []
        with self._mu:
            for (k_, key), e in self._entities.items():
                if k_ != kind or key == exclude or e.state != STATE_OK:
                    continue
                vals.extend(self._samples(e, verbs))
        return _pct(vals, q)

    def state_of(self, kind: str, key: str) -> str:
        with self._mu:
            e = self._entities.get((kind, key))
            return e.state if e is not None else STATE_OK

    def state_age(self, kind: str, key: str) -> float:
        with self._mu:
            e = self._entities.get((kind, key))
            if e is None:
                return 0.0
            return time.monotonic() - e.state_since

    def set_state(self, kind: str, key: str, state: str,
                  event: str = "") -> None:
        with self._mu:
            e = self._entities.get((kind, key))
            if e is None:
                e = self._entities[(kind, key)] = _Entity(kind, key)
            if e.state == state:
                return
            e.state = state
            e.state_since = time.monotonic()
            e.probes_ok = 0
        if event:
            _QUAR.inc(event=event)
        eventlog.emit("health.transition", kind=kind, target=key,
                      state=state, event=event)

    # -- quarantine policy -------------------------------------------------

    def quarantine_threshold(self, kind: str, key: str) -> float:
        """Latency above which this entity counts slow: the absolute
        knob floor, raised by the relative ratio when a healthy
        baseline exists (a uniformly slow medium must not quarantine
        everything; a uniformly fast one must still catch the one
        drive doing 500 ms I/Os)."""
        thresh = knobs.get_float("MINIO_TPU_QUAR_LATENCY_S")
        healthy = self.healthy_percentile(kind, 0.95,
                                          verbs=_DECISION_VERBS,
                                          exclude=key)
        if healthy is not None:
            thresh = max(thresh,
                         healthy * knobs.get_float("MINIO_TPU_QUAR_RATIO"))
        return thresh

    def should_quarantine(self, kind: str, key: str) -> bool:
        with self._mu:
            e = self._entities.get((kind, key))
            vals = self._samples(e, _DECISION_VERBS) if e else []
        if len(vals) < knobs.get_int("MINIO_TPU_QUAR_MIN_SAMPLES"):
            return False
        p95 = _pct(vals, 0.95)
        return p95 is not None and p95 > self.quarantine_threshold(
            kind, key)

    def clear_samples(self, kind: str, key: str) -> None:
        """Drop an entity's latency windows (heal-verified
        re-admission calls this): conviction evidence gathered BEFORE
        recovery must not re-convict the drive on the next scan — a
        quarantined drive takes no reads, so stale slow samples would
        otherwise sit in the window and flap it forever."""
        with self._mu:
            e = self._entities.get((kind, key))
            if e is not None:
                e.windows.clear()
                e.ewma.clear()

    def note_probe(self, kind: str, key: str, ok: bool) -> int:
        """Record one probation probe verdict; returns consecutive
        passes (a failure resets the count AND the probation dwell —
        the drive re-convicts back to suspect)."""
        reconvicted = False
        with self._mu:
            e = self._entities.get((kind, key))
            if e is None:
                return 0
            if ok:
                e.probes_ok += 1
                return e.probes_ok
            e.probes_ok = 0
            if e.state == STATE_PROBATION:
                reconvicted = True
            e.state = STATE_SUSPECT
            e.state_since = time.monotonic()
        if reconvicted:
            # a flapping drive must be visible as flapping, not as
            # one forever-pending probation
            _QUAR.inc(event="reconvict")
            eventlog.emit("health.transition", kind=kind, target=key,
                          state=STATE_SUSPECT, event="reconvict")
        return 0

    # -- surfaces ----------------------------------------------------------

    def snapshot(self, kind: Optional[str] = None) -> list:
        """Per-entity latency + health summary (OBD / admin)."""
        out = []
        with self._mu:
            ents = [e for (k_, _), e in self._entities.items()
                    if kind is None or k_ == kind]
            for e in ents:
                verbs = {}
                for v, w in e.windows.items():
                    vals = w.buf
                    verbs[v] = {
                        "n": len(vals),
                        "p50_ms": round((_pct(vals, 0.5) or 0) * 1e3, 3),
                        "p95_ms": round((_pct(vals, 0.95) or 0) * 1e3, 3),
                        "ewma_ms": round(e.ewma.get(v, 0.0) * 1e3, 3),
                    }
                out.append({"kind": e.kind, "key": e.key,
                            "state": e.state,
                            "state_age_s": round(
                                time.monotonic() - e.state_since, 3),
                            "verbs": verbs})
        out.sort(key=lambda d: (d["kind"], d["key"]))
        return out

    def _collect(self) -> None:
        with self._mu:
            drives = [(e.key, e.state) for (k_, _), e in
                      self._entities.items() if k_ == "drive"]
        for key, state in drives:
            _HEALTH.set(_STATE_NUM.get(state, 0), disk=key)

    def reset(self) -> None:
        """Drop every entity (tests)."""
        with self._mu:
            self._entities.clear()


TRACKER = HealthTracker()


# ---------------------------------------------------------------------------
# call-site helpers (the engine / transport / DiskMonitor surface)
# ---------------------------------------------------------------------------

def observe_disk(disk, verb: str, seconds: float) -> None:
    TRACKER.observe("drive", disk_key(disk), verb, seconds)


def observe_peer(key: str, verb: str, seconds: float) -> None:
    TRACKER.observe("peer", key, verb, seconds)


def is_suspect_disk(disk) -> bool:
    """True while the drive sits in suspect OR probation — both are
    excluded from read plans and hedge targets until the heal-verified
    re-admission flips the state back to ok."""
    return TRACKER.state_of("drive", disk_key(disk)) != STATE_OK


def hedging_enabled() -> bool:
    return knobs.get_bool("MINIO_TPU_HEDGE")


def quorum_ack_enabled() -> bool:
    return knobs.get_bool("MINIO_TPU_QUORUM_ACK")


def quarantine_enabled() -> bool:
    return knobs.get_bool("MINIO_TPU_QUARANTINE")


def _clamped_deadline(p: Optional[float], k_mult: float, floor: float,
                      ceil: float) -> float:
    if p is None:
        return ceil            # cold start: never hedge/abandon early
    return min(max(p * k_mult, floor), ceil)


def read_hedge_s() -> Optional[float]:
    """Seconds a shard read may run before a spare read races it, or
    None when hedging is off."""
    if not hedging_enabled():
        return None
    p = TRACKER.healthy_percentile("drive", 0.95, verbs=("read",))
    return _clamped_deadline(p, knobs.get_float("MINIO_TPU_HEDGE_K"),
                             knobs.get_float("MINIO_TPU_HEDGE_FLOOR_S"),
                             knobs.get_float("MINIO_TPU_HEDGE_CEIL_S"))


def write_stall_s() -> Optional[float]:
    """Seconds a shard-write fan-out waits for stragglers once quorum
    is durable, or None when quorum-ack is off."""
    if not quorum_ack_enabled():
        return None
    p = TRACKER.healthy_percentile("drive", 0.95, verbs=("write",))
    return _clamped_deadline(
        p, knobs.get_float("MINIO_TPU_WRITE_STALL_K"),
        knobs.get_float("MINIO_TPU_WRITE_STALL_FLOOR_S"),
        knobs.get_float("MINIO_TPU_WRITE_STALL_CEIL_S"))


def note_hedge(trigger: str) -> None:
    _HEDGED.inc(trigger=trigger)


def note_laggard(stage: str) -> None:
    _LAGGARDS.inc(stage=stage)
