"""Atomic file commits with optional fsync/dirsync discipline.

Every raw-file commit in the tree follows one recipe — write a temp
sibling, atomically ``os.replace`` it over the final name — but the
recipe alone only protects against a crash of THIS process: without an
fsync barrier before the rename and a directory fsync after it, a
power cut (or a VM/host death) can surface the rename while the data
blocks are still unwritten — a torn or empty file under the committed
name. That is exactly the rename-before-fsync window the
crash-consistency literature (ALICE's "safe rename" pattern) calls
out.

The barriers are real I/O on the PUT hot path, so they ride one knob:
``MINIO_TPU_FSYNC=on`` (default off — tier-1 timing unchanged; the
kill/restart harness and durability-sensitive deployments turn it on).
``write_atomic`` is the shared helper the registry persist paths and
``xl_storage`` commit paths use; ``fsync_file``/``fsync_dir`` serve
call sites that manage their own file handles (shard appenders).

``load_json_doc`` is the read-side discipline: a checkpoint/registry
loader must treat a torn, truncated, or type-mangled JSON document as
ABSENT (fall back to the previous epoch / re-walk), never crash the
boot path on it.
"""

from __future__ import annotations

import json
import os
import uuid as _uuid
from typing import Optional

from . import knobs

__all__ = ["fsync_enabled", "fsync_file", "fsync_dir", "write_atomic",
           "load_json_doc"]


def fsync_enabled() -> bool:
    return knobs.get_bool("MINIO_TPU_FSYNC")


def fsync_file(f) -> None:
    """Flush + fsync an open file object (or raw fd) when the
    discipline is on. Best-effort on filesystems that refuse."""
    if not fsync_enabled():
        return
    try:
        if hasattr(f, "flush"):
            f.flush()
        os.fsync(f.fileno() if hasattr(f, "fileno") else f)
    except OSError:
        pass


def fsync_dir(path: str) -> None:
    """fsync the DIRECTORY so a just-committed rename survives power
    loss (the rename itself lives in the directory's data blocks)."""
    if not fsync_enabled():
        return
    try:
        dfd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    except OSError:
        pass
    finally:
        os.close(dfd)


def write_atomic(path: str, data: bytes) -> None:
    """write-temp → (fsync) → rename → (dirsync): the one sanctioned
    raw-file commit. Cleans up the temp on any failure. Callers map
    OSError to their own error taxonomy."""
    tmp = path + "." + _uuid.uuid4().hex[:8] + ".tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            fsync_file(f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(os.path.dirname(path) or ".")


def load_json_doc(raw: bytes) -> Optional[dict]:
    """Parse a persisted JSON document tolerantly: a torn/truncated
    file (crash inside the write) or a valid-JSON-but-wrong-type
    prefix (``b"12"`` from a truncated ``{"epoch": 12, ...}`` would
    parse as an int) returns None — the caller falls back to its
    previous copy — instead of raising into a boot path."""
    try:
        doc = json.loads(raw.decode())
    except (ValueError, UnicodeDecodeError):
        return None
    return doc if isinstance(doc, dict) else None
