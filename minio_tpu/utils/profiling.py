"""Process-global CPU profiler (admin profiling + peer fan-out share
one profiler per process — reference cmd/admin-handlers.go:461-525
globalProfiler; cProfile is the Python-native equivalent of the Go
pprof cpu kind)."""

from __future__ import annotations

import cProfile
import io
import pstats
import threading
from typing import Optional

_profiler: Optional[cProfile.Profile] = None
_mu = threading.Lock()


def start() -> bool:
    """Begin profiling; False when already running."""
    global _profiler
    with _mu:
        if _profiler is not None:
            return False
        _profiler = cProfile.Profile()
        _profiler.enable()
        return True


def running() -> bool:
    with _mu:
        return _profiler is not None


def stop_text(top: int = 60) -> Optional[str]:
    """Stop and render the profile (None when not running)."""
    global _profiler
    with _mu:
        prof, _profiler = _profiler, None
    if prof is None:
        return None
    prof.disable()
    buf = io.StringIO()
    pstats.Stats(prof, stream=buf).sort_stats("cumulative") \
        .print_stats(top)
    return buf.getvalue()
