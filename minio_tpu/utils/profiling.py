"""Process-global profilers (admin profiling + peer fan-out share one
profiler per process — reference cmd/admin-handlers.go:461-525
globalProfiler). Two kinds, mirroring the reference's cpu/mem pprof
set: "cpu" = cProfile (the Python-native pprof-cpu equivalent),
"mem" = tracemalloc (allocation sites by size, the pprof-heap
equivalent). Go's block/mutex kinds have no Python analog.
"""

from __future__ import annotations

import cProfile
import io
import pstats
import threading
import tracemalloc
from typing import Optional

KINDS = ("cpu", "mem")


def parse_kinds(raw: str) -> list[str]:
    """One parser for every surface (admin HTTP, peer RPC): tolerant
    of whitespace, preserving order, silently dropping unknowns —
    callers that must REJECT unknowns compare against split_raw()."""
    return [k for k in split_raw(raw) if k in KINDS]


def split_raw(raw: str) -> list[str]:
    return [k.strip() for k in raw.split(",") if k.strip()]

_profiler: Optional[cProfile.Profile] = None
_mem_running = False
_mu = threading.Lock()


def start(kind: str = "cpu") -> bool:
    """Begin profiling `kind`; False when already running (or the kind
    is unknown)."""
    global _profiler, _mem_running
    with _mu:
        if kind == "cpu":
            if _profiler is not None:
                return False
            _profiler = cProfile.Profile()
            _profiler.enable()
            return True
        if kind == "mem":
            if _mem_running or tracemalloc.is_tracing():
                return False
            tracemalloc.start(10)       # keep 10 frames per alloc site
            _mem_running = True
            return True
        return False


def running(kind: str = "cpu") -> bool:
    with _mu:
        if kind == "cpu":
            return _profiler is not None
        if kind == "mem":
            return _mem_running
        return False


def stop_text(kind: str = "cpu", top: int = 60) -> Optional[str]:
    """Stop `kind` and render the profile (None when not running)."""
    global _profiler, _mem_running
    if kind == "cpu":
        with _mu:
            prof, _profiler = _profiler, None
        if prof is None:
            return None
        prof.disable()
        buf = io.StringIO()
        pstats.Stats(prof, stream=buf).sort_stats("cumulative") \
            .print_stats(top)
        return buf.getvalue()
    if kind == "mem":
        with _mu:
            if not _mem_running:
                return None
            _mem_running = False
            # snapshot + stop stay under the lock: a concurrent
            # start("mem") between flag-clear and stop() would see
            # is_tracing() True, report "already running", and then
            # have its tracing torn down here
            snap = tracemalloc.take_snapshot()
            current, peak = tracemalloc.get_traced_memory()
            tracemalloc.stop()
        lines = [f"traced current={current} peak={peak} bytes",
                 "top allocation sites by size:"]
        for stat in snap.statistics("lineno")[:top]:
            lines.append(f"  {stat.size:>12d} B  {stat.count:>8d} x  "
                         f"{stat.traceback}")
        return "\n".join(lines) + "\n"
    return None
