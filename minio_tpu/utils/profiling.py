"""Process-global profilers (admin profiling + peer fan-out share one
profiler per process — reference cmd/admin-handlers.go:461-525
globalProfiler). Two kinds, mirroring the reference's cpu/mem pprof
set: "cpu" = cProfile (the Python-native pprof-cpu equivalent),
"mem" = tracemalloc (allocation sites by size, the pprof-heap
equivalent). Go's block/mutex kinds have no Python analog.

Each kind is one :class:`_Kind` entry in :data:`_TABLE` — start /
running / stop_text dispatch through the table instead of each
re-switching on the kind string, so adding a kind is one class, not
three if-ladders. Live state is exported as
``minio_tpu_profiler_running{kind=...}`` gauges.
"""

from __future__ import annotations

import cProfile
import io
import pstats
import threading
import tracemalloc
from typing import Optional

from . import telemetry

_mu = threading.Lock()


class _Kind:
    """One profiler kind: _begin/_end under the module lock, is_running
    without side effects. Subclasses own their runtime state."""

    def is_running(self) -> bool:
        raise NotImplementedError

    def _begin(self) -> bool:
        raise NotImplementedError

    def _end(self, top: int) -> Optional[str]:
        raise NotImplementedError


class _CpuKind(_Kind):
    def __init__(self) -> None:
        self._profiler: Optional[cProfile.Profile] = None

    def is_running(self) -> bool:
        return self._profiler is not None

    def _begin(self) -> bool:
        if self._profiler is not None:
            return False
        self._profiler = cProfile.Profile()
        self._profiler.enable()
        return True

    def _end(self, top: int) -> Optional[str]:
        prof, self._profiler = self._profiler, None
        if prof is None:
            return None
        prof.disable()
        buf = io.StringIO()
        pstats.Stats(prof, stream=buf).sort_stats("cumulative") \
            .print_stats(top)
        return buf.getvalue()


class _MemKind(_Kind):
    def __init__(self) -> None:
        self._running = False

    def is_running(self) -> bool:
        return self._running

    def _begin(self) -> bool:
        if self._running or tracemalloc.is_tracing():
            return False
        tracemalloc.start(10)           # keep 10 frames per alloc site
        self._running = True
        return True

    def _end(self, top: int) -> Optional[str]:
        if not self._running:
            return None
        self._running = False
        # snapshot + stop stay under the module lock (the caller holds
        # it): a concurrent start("mem") between flag-clear and stop()
        # would see is_tracing() True, report "already running", and
        # then have its tracing torn down here
        snap = tracemalloc.take_snapshot()
        current, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        lines = [f"traced current={current} peak={peak} bytes",
                 "top allocation sites by size:"]
        for stat in snap.statistics("lineno")[:top]:
            lines.append(f"  {stat.size:>12d} B  {stat.count:>8d} x  "
                         f"{stat.traceback}")
        return "\n".join(lines) + "\n"


_TABLE: dict[str, _Kind] = {"cpu": _CpuKind(), "mem": _MemKind()}
KINDS = tuple(_TABLE)


def parse_kinds(raw: str) -> list[str]:
    """One parser for every surface (admin HTTP, peer RPC): tolerant
    of whitespace, preserving order, silently dropping unknowns —
    callers that must REJECT unknowns compare against split_raw()."""
    return [k for k in split_raw(raw) if k in _TABLE]


def split_raw(raw: str) -> list[str]:
    return [k.strip() for k in raw.split(",") if k.strip()]


def start(kind: str = "cpu") -> bool:
    """Begin profiling `kind`; False when already running (or the kind
    is unknown)."""
    entry = _TABLE.get(kind)
    if entry is None:
        return False
    with _mu:
        return entry._begin()


def running(kind: str = "cpu") -> bool:
    entry = _TABLE.get(kind)
    if entry is None:
        return False
    with _mu:
        return entry.is_running()


def stop_text(kind: str = "cpu", top: int = 60) -> Optional[str]:
    """Stop `kind` and render the profile (None when not running)."""
    entry = _TABLE.get(kind)
    if entry is None:
        return None
    with _mu:
        return entry._end(top)


def _collect_profiler_metrics() -> None:
    g = telemetry.REGISTRY.gauge(
        "minio_tpu_profiler_running",
        "1 while the given profiler kind is collecting")
    with _mu:
        for kind, entry in _TABLE.items():
            g.set(int(entry.is_running()), kind=kind)


telemetry.REGISTRY.register_collector(_collect_profiler_metrics)
