"""ctypes bindings to the native C++ library (native/*.cpp).

The library is built on demand (make -C native) and provides:
  * HighwayHash-64/256 (single-shot, batched, streaming) — the CPU bitrot
    engine (reference behavior: cmd/bitrot.go algorithms).
  * gf_matmul — GFNI/AVX-512 (or portable) GF(2^8) coding matmul — the CPU
    fallback codec and bench baseline.

Everything degrades gracefully: if the shared library is missing and make
fails, `available()` returns False and pure-Python/numpy fallbacks take
over (slower, same bytes).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libminio_tpu_native.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR, "-s"],
                       check=True, capture_output=True, timeout=300)
        return True
    except Exception:
        return False


def get_lib() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # Always invoke make: it is a timestamp-based no-op when current,
        # and rebuilds the .so after source edits (a pre-existing stale
        # binary would otherwise be loaded silently forever).
        if not _build() and not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.hh64.restype = ctypes.c_uint64
        lib.hh64.argtypes = [u8p, u8p, ctypes.c_size_t]
        lib.hh256.restype = None
        lib.hh256.argtypes = [u8p, u8p, ctypes.c_size_t, u8p]
        lib.hh256_batch.restype = None
        lib.hh256_batch.argtypes = [u8p, u8p, ctypes.c_size_t,
                                    ctypes.c_size_t, ctypes.c_size_t, u8p]
        lib.hh_init.restype = None
        lib.hh_init.argtypes = [u8p, u8p]
        lib.hh_update_packets.restype = None
        lib.hh_update_packets.argtypes = [u8p, u8p, ctypes.c_size_t]
        lib.hh_final256.restype = None
        lib.hh_final256.argtypes = [u8p, u8p, ctypes.c_size_t, u8p]
        lib.gf_matmul.restype = None
        lib.gf_matmul.argtypes = [u8p, ctypes.c_size_t, ctypes.c_size_t,
                                  u8p, ctypes.c_size_t,
                                  u8p, ctypes.c_size_t, ctypes.c_size_t,
                                  ctypes.c_int]
        lib.gf_has_gfni.restype = ctypes.c_int
        lib.gf_has_gfni.argtypes = []
        # snappy/S2 codec (absent in a stale pre-r5 .so: make rebuilds,
        # but guard the lookup so an unwritable tree degrades cleanly)
        try:
            lib.snappy_crc32c.restype = ctypes.c_uint32
            lib.snappy_crc32c.argtypes = [u8p, ctypes.c_size_t]
            lib.snappy_max_compressed_length.restype = ctypes.c_size_t
            lib.snappy_max_compressed_length.argtypes = [ctypes.c_size_t]
            lib.snappy_compress_block.restype = ctypes.c_int
            lib.snappy_compress_block.argtypes = [
                u8p, ctypes.c_size_t, u8p,
                ctypes.POINTER(ctypes.c_size_t)]
            lib.snappy_uncompressed_length.restype = ctypes.c_int64
            lib.snappy_uncompressed_length.argtypes = [u8p,
                                                       ctypes.c_size_t]
            lib.snappy_uncompress_block.restype = ctypes.c_int64
            lib.snappy_uncompress_block.argtypes = [
                u8p, ctypes.c_size_t, u8p, ctypes.c_size_t]
            lib.snappy_ok = True
        except AttributeError:
            lib.snappy_ok = False
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def _u8p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def hh64(key: bytes, data: bytes | np.ndarray) -> int:
    lib = get_lib()
    assert lib is not None
    k = np.frombuffer(key, dtype=np.uint8)
    d = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray)) else np.ascontiguousarray(data, np.uint8)
    return int(lib.hh64(_u8p(k), _u8p(d), d.size))


def hh256(key: bytes, data: bytes | np.ndarray) -> bytes:
    lib = get_lib()
    assert lib is not None
    k = np.frombuffer(key, dtype=np.uint8)
    d = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray)) else np.ascontiguousarray(data, np.uint8)
    out = np.zeros(32, dtype=np.uint8)
    lib.hh256(_u8p(k), _u8p(d), d.size, _u8p(out))
    return out.tobytes()


def hh256_batch(key: bytes, shards: np.ndarray) -> np.ndarray:
    """Hash each row of a contiguous (n, L) uint8 array -> (n, 32)."""
    lib = get_lib()
    assert lib is not None
    shards = np.ascontiguousarray(shards, np.uint8)
    n, length = shards.shape
    k = np.frombuffer(key, dtype=np.uint8)
    out = np.zeros((n, 32), dtype=np.uint8)
    lib.hh256_batch(_u8p(k), _u8p(shards), n, length, shards.strides[0],
                    _u8p(out))
    return out


def gf_matmul(matrix: np.ndarray, data: np.ndarray,
              force_path: int = 0) -> np.ndarray:
    """out(r,L) = matrix(r,k) (x) data(k,L) over GF(2^8), native speed."""
    lib = get_lib()
    assert lib is not None
    matrix = np.ascontiguousarray(matrix, np.uint8)
    data = np.ascontiguousarray(data, np.uint8)
    r, k = matrix.shape
    k2, length = data.shape
    assert k == k2
    out = np.zeros((r, length), dtype=np.uint8)
    lib.gf_matmul(_u8p(matrix), r, k, _u8p(data), data.strides[0],
                  _u8p(out), out.strides[0], length, force_path)
    return out


def has_gfni() -> bool:
    lib = get_lib()
    return bool(lib and lib.gf_has_gfni())


# ---------------------------------------------------------------------------
# snappy/S2 block codec + CRC32C
# ---------------------------------------------------------------------------

def snappy_available() -> bool:
    lib = get_lib()
    return bool(lib and getattr(lib, "snappy_ok", False))


def crc32c(data: bytes | memoryview) -> int:
    lib = get_lib()
    assert lib is not None and lib.snappy_ok
    d = np.frombuffer(data, dtype=np.uint8)
    return int(lib.snappy_crc32c(_u8p(d), d.size))


def snappy_compress_block(data: bytes | memoryview) -> bytes:
    """One snappy block (<= 65536 bytes — the framing chunk limit; the
    C hash table stores 16-bit positions)."""
    lib = get_lib()
    assert lib is not None and lib.snappy_ok
    d = np.frombuffer(data, dtype=np.uint8)
    assert d.size <= 65536
    out = np.empty(int(lib.snappy_max_compressed_length(d.size)),
                   dtype=np.uint8)
    n = ctypes.c_size_t(0)
    lib.snappy_compress_block(_u8p(d), d.size, _u8p(out),
                              ctypes.byref(n))
    return out[:n.value].tobytes()


def snappy_uncompress_block(data: bytes | memoryview,
                            max_out: int = 1 << 24) -> bytes:
    """Decode one snappy/S2 block; raises ValueError on corrupt input
    and NotImplementedError on S2 encodings outside the subset."""
    lib = get_lib()
    assert lib is not None and lib.snappy_ok
    d = np.frombuffer(data, dtype=np.uint8)
    want = int(lib.snappy_uncompressed_length(_u8p(d), d.size))
    if want < 0 or want > max_out:
        raise ValueError("corrupt snappy block header")
    out = np.empty(want, dtype=np.uint8)
    got = int(lib.snappy_uncompress_block(_u8p(d), d.size, _u8p(out),
                                          want))
    if got == -2:
        raise NotImplementedError(
            "S2 extended repeat encoding outside the decoded subset")
    if got != want:
        raise ValueError("corrupt snappy block")
    return out.tobytes()
