"""eventlog — the structured event journal behind the incident plane.

The fault planes act autonomously — drives get quarantined, writes
shed, peers fenced, registry forks archived, device paths declined to
CPU — and until now each transition survived only as a counter bump or
a private deque. This module gives every such transition one durable,
queryable record: a process-global bounded journal of structured
events (ts, class, severity, node, bounded attrs), persisted in
segments under ``.minio.sys/eventlog/`` and served by the admin
``/events`` endpoint (filters, ``?follow=1`` streaming with peer
grafting, ``?cluster=1`` federation).

Two halves, same file:

* the EVENT-CLASS REGISTRY — declarative, like knobs and crashpoints:
  every emit site names a registered class, the README table is
  generated from here (``tools/check/run.py --write-event-table``) and
  drift-checked, and the ``eventlog`` lint rule rejects unregistered
  classes, undeclared attr keys, and attr keys from the unbounded
  label vocabulary. The registry half has NO package imports so
  ``tools/check/eventtable.py`` can load this file standalone.

* the JOURNAL — a bounded in-memory ring + pubsub hub + background
  segment flusher. ``emit()`` is hot-path safe: dict build, ring
  append and a pending-list append under one lock; persistence and
  fan-out happen off-thread. Segments are written via ``atomicfile``
  with the ``eventlog.persist.segment`` crashpoint in the commit
  window, so a crash mid-persist leaves either the previous segment
  set or the new one — restart replays the surviving prefix.

Knobs (README "Incident plane"): MINIO_TPU_EVENTLOG,
MINIO_TPU_EVENTLOG_RING, MINIO_TPU_EVENTLOG_SEGMENT_EVENTS,
MINIO_TPU_EVENTLOG_FLUSH_S, MINIO_TPU_EVENTLOG_KEEP_SEGMENTS.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

SEVERITIES = ("info", "warn", "error", "crit")

# attr keys that name per-request / per-object identities — the same
# vocabulary the label-cardinality lint bans on metrics. An event
# journal is bounded; attrs that explode per object would turn it into
# an access log (the trace plane already is one).
UNBOUNDED_ATTR_KEYS = frozenset({
    "bucket", "object", "key", "obj", "etag", "version_id",
    "upload_id", "prefix", "trace_id", "request_id", "caller",
})


class EventClass:
    """One registered event class: the schema an emit site binds to."""

    __slots__ = ("name", "subsystem", "severity", "attrs", "doc")

    def __init__(self, name: str, subsystem: str, severity: str,
                 attrs: Tuple[str, ...], doc: str):
        self.name = name
        self.subsystem = subsystem
        self.severity = severity
        self.attrs = attrs
        self.doc = doc


EVENTS: Dict[str, EventClass] = {}


def define(name: str, subsystem: str, severity: str,
           attrs: Tuple[str, ...], doc: str) -> None:
    if name in EVENTS:
        raise ValueError(f"event class {name!r} already registered")
    if severity not in SEVERITIES:
        raise ValueError(f"event class {name!r}: unknown severity "
                         f"{severity!r} (one of {SEVERITIES})")
    for a in attrs:
        if a in UNBOUNDED_ATTR_KEYS:
            raise ValueError(
                f"event class {name!r}: attr {a!r} is in the unbounded"
                f" label vocabulary — journal attrs must be bounded")
    EVENTS[name] = EventClass(name, subsystem, severity, tuple(attrs),
                              doc)


def sev_rank(severity: str) -> int:
    """info=0 … crit=3; unknown ranks lowest (filters keep them out)."""
    try:
        return SEVERITIES.index(severity)
    except ValueError:
        return -1


# ---------------------------------------------------------------------------
# the registry (grouped by subsystem; the README table mirrors this)
# ---------------------------------------------------------------------------

_S = "drive"
define("drive.suspect", _S, "warn", ("drive", "set"),
       "Drive health monitor marked a drive suspect (latency/error "
       "score over the conviction threshold)")
define("drive.probation", _S, "error", ("drive", "set"),
       "Suspect drive convicted into probation: reads deprioritized, "
       "writes steered away")
define("drive.reconvict", _S, "error", ("drive", "set"),
       "Probation probe failed — the quarantine clock restarts")
define("drive.readmit", _S, "info", ("drive", "set"),
       "Probation probes passed; the drive rejoins full duty")

_S = "heal"
define("mrf.enqueue", _S, "warn", ("queued",),
       "A degraded write enqueued its missing shards for background "
       "heal (MRF)")
define("mrf.drain", _S, "info", ("healed", "failed"),
       "An MRF entry finished draining (healed/failed are the "
       "queue's running totals)")

_S = "admission"
define("admission.shed", _S, "warn", ("reason",),
       "The admission plane refused a request with 503 SlowDown")

_S = "health"
define("health.transition", _S, "warn",
       ("kind", "target", "state", "event"),
       "A tracked entity (drive/peer) changed health state in the "
       "gray-failure tracker")

_S = "membership"
define("membership.generation", _S, "warn", ("peer", "generation"),
       "A peer came back under a new boot generation (restart "
       "detected; its locks and subscriptions are stale)")

_S = "net"
define("net.partition", _S, "error", ("rule", "peers"),
       "The network chaos plane partitioned this node from a peer set")
define("net.heal", _S, "info", ("peers",),
       "A network partition healed; cross-partition traffic resumed")

_S = "registry"
define("registry.fork", _S, "crit", ("epoch", "forks"),
       "fsck found divergent registry lineages under one epoch "
       "(split-brain residue); losers archived")

_S = "crash"
define("crashpoint.armed", _S, "warn", ("point", "nth"),
       "A crashpoint was armed (fault injection active in this "
       "process)")

_S = "device"
define("device.decline", _S, "info", ("stage", "reason"),
       "A device-path dispatch declined to CPU fallback "
       "(scheduler/scan/SSE)")

_S = "fsck"
define("fsck.complete", _S, "info",
       ("findings", "repaired", "unrepaired"),
       "An fsck sweep finished")
define("fsck.unrepaired", _S, "error", ("findings",),
       "fsck left findings it could not repair — operator attention "
       "needed (incident trigger)")

_S = "data"
define("rebalance.checkpoint", _S, "info", ("pool", "objects"),
       "Rebalance persisted a resumable progress checkpoint")
define("resync.checkpoint", _S, "info", ("target", "objects"),
       "Replication resync persisted a resumable progress checkpoint")

_S = "slo"
define("slo.breach", _S, "crit", ("objective", "window", "burn"),
       "An SLO burn rate crossed the alerting threshold (error budget "
       "burning too fast)")
define("slo.clear", _S, "info", ("objective",),
       "A breached SLO's burn rate dropped back under the clear "
       "threshold")

_S = "incident"
define("incident.captured", _S, "warn",
       ("trigger", "incident", "events"),
       "The black-box recorder wrote an incident bundle")

_S = "qos"
define("qos.update", _S, "info", ("epoch", "tenants", "tiers"),
       "The QoS budget registry committed a new epoch (budget set or "
       "removed)")
define("tenant.shed", _S, "warn", ("tenant", "reason"),
       "A tenant hit its QoS budget and was refused (first shed per "
       "tenant per debounce window)")

_S = "notify"
define("notify.update", _S, "info", ("epoch", "targets"),
       "The notification-target registry committed a new epoch "
       "(target added or removed)")
define("notify.offline", _S, "warn", ("target",),
       "A notification target failed a delivery and entered its "
       "offline window (first failure per window)")
define("notify.redrive", _S, "info", ("target", "delivered"),
       "A recovered notification target drained its persisted event "
       "backlog")
define("notify.drop", _S, "warn", ("target",),
       "An event record was dropped at a full per-target delivery "
       "queue (bounded backlog overflow)")

del _S


# ---------------------------------------------------------------------------
# README table (generated; tools/check/eventtable.py drift-checks it)
# ---------------------------------------------------------------------------

TABLE_BEGIN = ("<!-- EVENT_TABLE_BEGIN (generated by tools/check/"
               "run.py --write-event-table; edits below will be "
               "overwritten) -->")
TABLE_END = "<!-- EVENT_TABLE_END -->"


def render_table() -> str:
    subsystems: Dict[str, List[EventClass]] = {}
    for ec in EVENTS.values():
        subsystems.setdefault(ec.subsystem, []).append(ec)
    lines = ["| Event class | Severity | Attrs | Emitted when |",
             "|---|---|---|---|"]
    for sub in sorted(subsystems):
        lines.append(f"| **{sub}** | | | |")
        for ec in sorted(subsystems[sub], key=lambda e: e.name):
            attrs = ", ".join(f"`{a}`" for a in ec.attrs) or "—"
            lines.append(f"| `{ec.name}` | {ec.severity} | {attrs} "
                         f"| {ec.doc} |")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# the journal
# ---------------------------------------------------------------------------

_SEGMENT_FMT = "seg-%016d.json"


class EventJournal:
    """Process-global bounded event recorder + segment persistence.

    In-memory the journal is a ring (newest RING events) plus a pubsub
    hub for followers; on disk it is a sequence of immutable JSON
    segments, each holding a contiguous seq range, pruned to the
    newest KEEP_SEGMENTS. ``attach()`` replays surviving segments into
    the ring so the timeline spans restarts — that is what lets
    ``drivehealth`` answer "when was this drive quarantined" after the
    process that quarantined it died.
    """

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self.node = ""
        self._ring: "deque[dict]" = deque(maxlen=512)
        self._pending: List[dict] = []
        self._seq = 0
        self._hub = None                    # PubSub, created lazily
        self._dir: Optional[str] = None
        self._flusher: Optional[threading.Thread] = None
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._segment_events = 64
        self._flush_s = 2.0
        self._keep_segments = 16
        self.dropped_total = 0              # emits while disabled

    # -- config ------------------------------------------------------------

    @staticmethod
    def _enabled() -> bool:
        from . import knobs
        return knobs.get_bool("MINIO_TPU_EVENTLOG")

    @property
    def hub(self):
        """The follower hub (lazy: the registry half of this module
        must stay importable standalone, without the package)."""
        if self._hub is None:
            from .pubsub import PubSub
            self._hub = PubSub()
        return self._hub

    # -- emit --------------------------------------------------------------

    def emit(self, class_name: str, **attrs) -> Optional[dict]:
        """Record one event. The class must be registered (the
        ``eventlog`` lint enforces this statically; the raise here
        catches dynamic construction the lint cannot see). Returns the
        recorded entry, or None when the journal is off."""
        ec = EVENTS.get(class_name)
        if ec is None:
            raise ValueError(f"unregistered event class {class_name!r}")
        if not self._enabled():
            self.dropped_total += 1
            return None
        entry = {
            "ts": round(time.time(), 3),
            "class": ec.name,
            "sev": ec.severity,
            "sub": ec.subsystem,
            "node": self.node,
            "attrs": attrs,
        }
        kick = False
        with self._mu:
            self._seq += 1
            entry["seq"] = self._seq
            self._ring.append(entry)
            if self._dir is not None:
                self._pending.append(entry)
                kick = len(self._pending) >= self._segment_events
        if kick:
            self._kick.set()
        hub = self._hub
        if hub is not None and hub.subscriber_count:
            hub.publish(entry)
        return entry

    # -- queries -----------------------------------------------------------

    @staticmethod
    def entry_matches(entry: dict, classes: Optional[set] = None,
                      subsystems: Optional[set] = None,
                      min_sev: int = 0) -> bool:
        """The /events filter semantics: `classes` keeps only those
        event classes, `subsystems` only those subsystems, `min_sev`
        the given severity rank and above."""
        if classes and entry.get("class") not in classes:
            return False
        if subsystems and entry.get("sub") not in subsystems:
            return False
        if min_sev and sev_rank(entry.get("sev", "")) < min_sev:
            return False
        return True

    def recent(self, n: int = 0, classes: Optional[set] = None,
               subsystems: Optional[set] = None,
               min_sev: int = 0,
               since_seq: int = 0) -> List[dict]:
        """Newest-last matching entries from the ring (the non-follow
        /events response). `n=0` means every ring entry."""
        with self._mu:
            entries = list(self._ring)
        out = [e for e in entries
               if e.get("seq", 0) > since_seq
               and self.entry_matches(e, classes, subsystems, min_sev)]
        return out[-n:] if n else out

    @property
    def seq(self) -> int:
        with self._mu:
            return self._seq

    # -- persistence -------------------------------------------------------

    def attach(self, dir_path: str, node: str = "",
               ring: int = 0, segment_events: int = 0,
               flush_s: float = 0.0, keep_segments: int = 0) -> None:
        """Bind the journal to `.minio.sys/eventlog/` on the first
        local drive: replay surviving segments into the ring, then
        start the background flusher. Idempotent — with several
        in-process nodes (tests) the first boot wins and later ones
        only refresh the node name if it was never set."""
        from . import knobs
        with self._mu:
            if not self.node and node:
                self.node = node
            if self._dir is not None:
                return
            ring = ring or knobs.get_int("MINIO_TPU_EVENTLOG_RING")
            self._segment_events = segment_events or knobs.get_int(
                "MINIO_TPU_EVENTLOG_SEGMENT_EVENTS")
            self._flush_s = flush_s or knobs.get_float(
                "MINIO_TPU_EVENTLOG_FLUSH_S")
            self._keep_segments = keep_segments or knobs.get_int(
                "MINIO_TPU_EVENTLOG_KEEP_SEGMENTS")
            if ring != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=ring)
            os.makedirs(dir_path, exist_ok=True)
            self._dir = dir_path
            self._replay_locked()
            self._stop.clear()
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True,
                name="eventlog-flush")
            self._flusher.start()

    def _segment_paths(self) -> List[str]:
        if self._dir is None:
            return []
        try:
            names = sorted(n for n in os.listdir(self._dir)
                           if n.startswith("seg-")
                           and n.endswith(".json"))
        except OSError:
            return []
        return [os.path.join(self._dir, n) for n in names]

    def _replay_locked(self) -> None:
        """Load surviving segments oldest-first into the ring and move
        seq past anything persisted — a torn segment (crash inside the
        commit window) reads as None and is skipped, serving the
        surviving prefix rather than nothing."""
        from . import atomicfile
        high = self._seq
        for path in self._segment_paths():
            try:
                with open(path, "rb") as f:
                    doc = atomicfile.load_json_doc(f.read())
            except OSError:
                continue
            if not isinstance(doc, dict):
                continue
            events = doc.get("events")
            if not isinstance(events, list):
                continue
            for e in events:
                if isinstance(e, dict):
                    self._ring.append(e)
                    high = max(high, int(e.get("seq", 0) or 0))
        self._seq = high

    def _flush_loop(self) -> None:
        while not self._stop.is_set():
            self._kick.wait(timeout=self._flush_s)
            self._kick.clear()
            if self._stop.is_set():
                return
            try:
                self.flush()
            except Exception:  # noqa: BLE001 — journal is best-effort
                pass

    def flush(self) -> Optional[str]:
        """Persist pending events as one immutable segment; prune old
        segments past the retention bound. Returns the segment path
        (None when nothing was pending or the journal is detached)."""
        from . import atomicfile, crashpoint
        with self._mu:
            if self._dir is None or not self._pending:
                return None
            pending, self._pending = self._pending, []
            dir_path = self._dir
            keep = self._keep_segments
        doc = {
            "v": 1,
            "first_seq": pending[0].get("seq", 0),
            "last_seq": pending[-1].get("seq", 0),
            "events": pending,
        }
        path = os.path.join(dir_path,
                            _SEGMENT_FMT % doc["first_seq"])
        # the commit window: a crash here must leave either the old
        # segment set or the new one, never a torn segment the replay
        # would choke on (write_atomic's rename is the commit point)
        crashpoint.hit("eventlog.persist.segment",
                       segment=os.path.basename(path))
        atomicfile.write_atomic(
            path, (json.dumps(doc) + "\n").encode())
        paths = self._segment_paths()
        for old in paths[:max(0, len(paths) - keep)]:
            try:
                os.unlink(old)
            except OSError:
                pass
        return path

    def close(self) -> None:
        """Stop the flusher and persist what is pending (clean
        shutdown; SIGKILL relies on the flush cadence instead)."""
        self._stop.set()
        self._kick.set()
        t = self._flusher
        if t is not None and t.is_alive():
            t.join(timeout=5)
        try:
            self.flush()
        except Exception:  # noqa: BLE001 — shutdown is best-effort
            pass

    # -- streaming (the /events?follow=1 surface) --------------------------

    @staticmethod
    def _pump_peer(it, q: "queue.Queue", stop: threading.Event) -> None:
        """Reader thread for one peer event subscription: forwards
        entries into the merge queue until the stream ends or the
        consumer stops. A full queue drops (a slow follow client must
        not apply backpressure to a peer's hub)."""
        try:
            for entry in it:
                if stop.is_set():
                    return
                try:
                    q.put_nowait(entry)
                except queue.Full:
                    pass
        finally:
            it.close()

    def stream(self, max_entries: int = 0, idle_timeout: float = 10.0,
               follow: bool = False, classes: Optional[set] = None,
               subsystems: Optional[set] = None, min_sev: int = 0,
               peer_subs=None, max_s: float = 3600.0,
               backlog: int = 0):
        """JSON-line journal entries as they happen (admin /events).

        Same contract as the PR-12 trace stream, lesson included:
        `peer_subs` is a CALLABLE resolved lazily at the generator's
        first iteration, so a response abandoned before its first
        chunk never opens a peer subscription it could not unwind;
        each peer iterator gets a daemon pump thread that dies with
        the stream; follow mode emits bare-newline heartbeats that
        double as dead-client probes. `backlog` seeds the stream with
        that many ring entries before going live. Entries are deduped
        by (node, seq) — in-process multi-node tests share one
        journal, so a peer graft would otherwise echo local events."""
        q: "queue.Queue[dict]" = queue.Queue(maxsize=1000)
        stop = threading.Event()

        def gen():
            subs = list(peer_subs() if callable(peer_subs)
                        else peer_subs or [])
            for it in subs:
                threading.Thread(target=self._pump_peer,
                                 args=(it, q, stop), daemon=True,
                                 name="event-follow-peer").start()
            seen: set = set()
            sent = 0
            now = time.monotonic()
            deadline = now + max_s if follow else float("inf")
            last_entry = now
            last_beat = now
            try:
                with self.hub.subscribe() as sub:
                    got = self.recent(backlog, classes, subsystems,
                                      min_sev) if backlog else []
                    while time.monotonic() < deadline:
                        for e in got:
                            ident = (e.get("node", ""),
                                     e.get("seq", 0))
                            if ident in seen:
                                continue
                            seen.add(ident)
                            if not self.entry_matches(
                                    e, classes, subsystems, min_sev):
                                continue
                            yield (json.dumps(e) + "\n").encode()
                            # idle counts from the last MATCHED entry
                            # (a filtered stream that never writes
                            # must not live forever)
                            last_entry = now
                            last_beat = now
                            sent += 1
                            if max_entries and sent >= max_entries:
                                return
                        got = []
                        if follow or subs:
                            timeout = 0.25
                        else:
                            timeout = (last_entry + idle_timeout
                                       - time.monotonic())
                            if timeout <= 0:
                                return
                        entry = sub.get(timeout=timeout)
                        if entry is not None:
                            got.append(entry)
                        while True:
                            try:
                                got.append(q.get_nowait())
                            except queue.Empty:
                                break
                        now = time.monotonic()
                        if follow:
                            if now - last_beat >= 1.0:
                                yield b"\n"   # liveness + hangup probe
                                last_beat = now
                        elif now - last_entry >= idle_timeout:
                            return
            finally:
                stop.set()
                for it in subs:
                    it.close()

        return gen()


JOURNAL = EventJournal()


def emit(class_name: str, **attrs) -> Optional[dict]:
    """Module-level emit — what every instrumented site calls
    (``eventlog.emit("drive.suspect", pool=0, ...)``); the lint keys
    on this spelling."""
    # check: allow(eventlog) forwarding proxy — validated at runtime
    return JOURNAL.emit(class_name, **attrs)


_ONCE: set = set()
_ONCE_MU = threading.Lock()


def emit_once(class_name: str, **attrs) -> Optional[dict]:
    """Emit deduplicated by (class, attrs) for the process lifetime —
    for per-call decision points (device declines, codec fallbacks)
    where the FIRST occurrence is the signal and a per-request stream
    would drown the ring. Same lint contract as ``emit``."""
    key = (class_name, tuple(sorted(attrs.items())))
    with _ONCE_MU:
        if key in _ONCE:
            return None
        _ONCE.add(key)
    # check: allow(eventlog) forwarding proxy — validated at runtime
    return JOURNAL.emit(class_name, **attrs)
