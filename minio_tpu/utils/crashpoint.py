"""Declarative registry of named crash/fault points.

Crash-consistency testing (ALICE / CrashMonkey style) needs process
death at NAMED points inside multi-step commit windows — not random
kill -9 storms whose coverage nobody can enumerate. Every multi-file
commit in the tree (xl.meta write→rename, shard fan-out→meta commit,
multipart complete, metacache manifest/segment persist, registry epoch
writes, rebalance/resync checkpoints, MRF/journal drains) threads a
``crashpoint.hit("<name>")`` call through its window; the names are
declared HERE — name, doc, commit window — and ``tools/check``'s
``crashpoint`` rule enforces the discipline (a multi-file commit
function without a hit is a lint error, a hit naming an unregistered
point too), while the README crashpoint table is generated from this
registry exactly like the knob table.

Arming, two ways:

  * **process mode** (the kill/restart harness):
    ``MINIO_TPU_CRASHPOINT=<name>[:<nth>]`` — the Nth hit of ``name``
    calls ``os._exit(137)``: no atexit, no finally blocks, no flushes —
    the closest a process can get to SIGKILLing itself at a named
    instruction. ``tests/harness/proc.py`` seeds this env per node.

  * **in-process mode** (unit tests): ``arm(name, nth=, action=)``
    installs a callable fired at the Nth hit — raise
    :class:`CrashpointAbort` to abort the commit mid-window (the
    torn-write / partial-rename injector), or do arbitrary damage via
    the ``ctx`` kwargs the hit site passes (e.g. ``path=``/``data=``
    on raw file commits). ``disarm()`` in the test's finally.

``hit()`` is one global ``is None`` check when nothing is armed — the
hot paths pay nothing.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Callable, Dict, List, Optional

# NOTE: no top-level package imports — tools/check/crashtable.py loads
# this file standalone (importlib, no package context) to generate the
# README table, exactly like knobtable.py loads knobs.py. The knobs
# import happens lazily inside _parse_env.

__all__ = [
    "Crashpoint", "CRASHPOINTS", "define", "names", "hit",
    "arm", "arm_exit", "disarm", "armed_name", "hits", "refresh",
    "CrashpointAbort", "torn_write_action",
    "render_table", "TABLE_BEGIN", "TABLE_END",
]

CRASH_EXIT_CODE = 137        # what SIGKILL would have produced


class CrashpointAbort(Exception):
    """Raised by the default in-process action: the commit dies
    mid-window exactly where a crash would have, but the test process
    survives to inspect the wreckage."""

    def __init__(self, name: str):
        super().__init__(f"crashpoint {name} fired")
        self.name = name


class Crashpoint:
    """One declared point: name, one-line doc, the commit window it
    interrupts (module-level description for the README table)."""

    __slots__ = ("name", "doc", "window")

    def __init__(self, name: str, doc: str, window: str):
        self.name = name
        self.doc = doc
        self.window = window


CRASHPOINTS: Dict[str, Crashpoint] = {}


def define(name: str, doc: str, window: str) -> Crashpoint:
    assert name not in CRASHPOINTS, f"crashpoint {name} declared twice"
    cp = Crashpoint(name, doc, window)
    CRASHPOINTS[name] = cp
    return cp


def names() -> List[str]:
    return list(CRASHPOINTS)


# ---------------------------------------------------------------------------
# the registry — grouped by commit window, in README table order
# ---------------------------------------------------------------------------

_W = "PUT commit (engine._commit)"
define("put.shards.before_meta",
       "after the shard fan-out completes, before the staged xl.meta "
       "write — shards exist in tmp, no metadata anywhere", _W)
define("put.meta.before_rename",
       "after the staged xl.meta lands in tmp, before the rename_data "
       "fan-out — a fully staged but uncommitted write", _W)
define("put.rename.partial",
       "inside the per-disk rename fan-out (one hit per disk; arm "
       ":<nth> to die after n-1 disks committed) — a torn commit "
       "below/at write quorum", _W)

_W = "Drive commit (xl_storage.rename_data)"
define("storage.rename_data.before_meta",
       "on ONE drive, after the data dir moved into place, before "
       "that drive's xl.meta write — an unreferenced data dir the "
       "fsck orphan sweep must reclaim", _W)
define("storage.write_all.commit",
       "inside every raw-file temp-write→rename commit (one hit per "
       "call, arm :<nth>); in-process actions receive path=/data= — "
       "the torn-write injector", _W)

_W = "Multipart (multipart.py)"
define("multipart.part.before_rename",
       "after a part's shards staged in tmp, before the rename into "
       "the session data dir — the session journal never saw the "
       "part", _W)
define("multipart.complete.before_rename",
       "after the final session meta write, before the commit "
       "rename_data fan-out — session intact, object absent", _W)
define("multipart.complete.rename.partial",
       "inside complete's per-disk rename fan-out (one hit per disk, "
       "arm :<nth>)", _W)

_W = "Metacache persist (object/metacache.py)"
define("metacache.persist.segment",
       "after each persisted index segment write (one hit per "
       "segment, arm :<nth>) — segments without a manifest", _W)
define("metacache.persist.before_manifest",
       "after every segment landed, before the manifest write — the "
       "orphan-segment window", _W)
define("metacache.journal.drain",
       "in the journal drainer, before a claimed delta batch applies "
       "— acked writes whose index deltas die with the process", _W)

_W = "Registry epoch writes"
define("topology.save.pool",
       "inside TopologyStore.save's per-pool loop (one hit per pool, "
       "arm :<nth>) — pools disagree on the topology epoch", _W)
define("tier.save.pool",
       "inside TierManager.save's per-pool loop (arm :<nth>) — pools "
       "disagree on the tier-config epoch", _W)
define("replicate.registry.save.pool",
       "inside TargetRegistry.save's per-pool loop (arm :<nth>) — "
       "pools disagree on the replication-target epoch", _W)
define("qos.save.pool",
       "inside QoSRegistry.save's per-pool loop (arm :<nth>) — pools "
       "disagree on the tenant-budget epoch", _W)
define("notify.registry.save.pool",
       "inside NotifyTargetRegistry.save's per-pool loop (arm :<nth>) "
       "— pools disagree on the notification-target epoch", _W)

_W = "Background checkpoints"
define("rebalance.checkpoint",
       "inside the drain's per-pool checkpoint write (arm :<nth>) — "
       "resume must tolerate a stale/torn checkpoint", _W)
define("resync.checkpoint",
       "inside the resync walker's per-pool checkpoint write (arm "
       ":<nth>) — resume must re-cover the un-checkpointed tail", _W)

_W = "Queues and drains"
define("replicate.push.before_apply",
       "in the sync worker, after spooling the source version, before "
       "the target apply — the push must survive as a retry, never a "
       "half-applied replica", _W)
define("mrf.drain.before_heal",
       "in the MRF drainer, after dequeuing an entry, before its heal "
       "runs — a crashed drain loses only retries, never objects", _W)
define("notify.queue.persist",
       "after one event record lands in a target's durable queue, "
       "before its delivery attempt — a restart must redrive exactly "
       "this entry (at-least-once, never lost)", _W)

_W = "Event journal (utils/eventlog.py)"
define("eventlog.persist.segment",
       "in the journal flusher, before a segment's temp-write→rename "
       "commit — a crash here must leave the prior segment set "
       "readable (restart serves the surviving prefix)", _W)

del _W


# ---------------------------------------------------------------------------
# arming + firing
# ---------------------------------------------------------------------------

class _Armed:
    __slots__ = ("name", "nth", "action", "count")

    def __init__(self, name: str, nth: int,
                 action: Optional[Callable[..., None]]):
        self.name = name
        self.nth = max(int(nth), 1)
        self.action = action
        self.count = 0


_mu = threading.Lock()
_UNSET = object()
# _UNSET until the env is parsed; then None (disarmed) or an _Armed
_armed = _UNSET


def _parse_env():
    from . import knobs
    spec = knobs.get_str("MINIO_TPU_CRASHPOINT").strip()
    if not spec:
        return None
    name, _, nth = spec.partition(":")
    if name not in CRASHPOINTS:
        # a typo'd point must not silently arm nothing AND must not
        # crash an otherwise-healthy request path: say so once, loudly
        print(f"minio_tpu: MINIO_TPU_CRASHPOINT names unregistered "
              f"point {name!r} — never fires", file=sys.stderr)
    try:
        n = int(nth) if nth else 1
    except ValueError:
        n = 1
    _note_armed(name, n)
    return _Armed(name, n, None)


def _note_armed(name: str, nth: int) -> None:
    """Journal that fault injection is live in this process — incident
    bundles must distinguish injected faults from organic ones."""
    try:
        from . import eventlog
        eventlog.emit("crashpoint.armed", point=name, nth=nth)
    except Exception:  # noqa: BLE001 — arming must not depend on the journal
        pass


def refresh() -> None:
    """Re-read MINIO_TPU_CRASHPOINT (tests that monkeypatch the env
    call this; server processes read it once, lazily)."""
    global _armed
    with _mu:
        _armed = _parse_env()


def arm(name: str, nth: int = 1,
        action: Optional[Callable[..., None]] = None) -> None:
    """In-process arming. ``action(name, **ctx)`` runs at the Nth hit;
    None means the default in-process action: raise CrashpointAbort
    (the commit dies mid-window, the process survives)."""
    global _armed
    if name not in CRASHPOINTS:
        raise KeyError(f"unregistered crashpoint {name!r} — declare it "
                       "in minio_tpu/utils/crashpoint.py")
    with _mu:
        _armed = _Armed(name, nth, action or _raise_abort)
    _note_armed(name, nth)


def arm_exit(name: str, nth: int = 1) -> None:
    """In-process arming of the PROCESS action (os._exit) — what the
    env spec does; for tests that spawn their own children."""
    arm(name, nth, action=_hard_exit)


def disarm() -> None:
    global _armed
    with _mu:
        _armed = None


def armed_name() -> Optional[str]:
    a = _armed
    if a is _UNSET or a is None:
        return None
    return a.name


def hits(name: str) -> int:
    """How many times the armed point has been hit (0 when another —
    or no — point is armed)."""
    a = _armed
    if a is _UNSET or a is None or a.name != name:
        return 0
    return a.count


def _raise_abort(name: str, **ctx) -> None:
    raise CrashpointAbort(name)


def _hard_exit(name: str, **ctx) -> None:
    # no atexit, no finally, no stream flushes: the closest an
    # in-process call gets to SIGKILL-at-an-instruction
    os._exit(CRASH_EXIT_CODE)


def torn_write_action(fraction: float = 0.5) -> Callable[..., None]:
    """An action for hit sites that pass ``path=``/``data=`` context
    (raw file commits): writes a truncated copy straight to the FINAL
    path, then aborts — the torn-file state a power cut mid-commit
    without fsync discipline leaves behind."""
    def act(name: str, **ctx) -> None:
        path, data = ctx.get("path"), ctx.get("data")
        if path is not None and data is not None:
            with open(path, "wb") as f:
                f.write(bytes(data)[: max(int(len(data) * fraction), 1)])
        raise CrashpointAbort(name)
    return act


def hit(name: str, **ctx) -> None:
    """Fire-if-armed. Call this AT the named instruction inside the
    commit window the registry describes. Near-free when disarmed."""
    global _armed
    a = _armed
    if a is _UNSET:
        with _mu:
            if _armed is _UNSET:
                _armed = _parse_env()
            a = _armed
    if a is None or a.name != name:
        return
    with _mu:
        a.count += 1
        fire = a.count == a.nth
    if fire:
        (a.action or _hard_exit)(name, **ctx)


# ---------------------------------------------------------------------------
# README table generator (tools/check/crashtable.py drift-checks this)
# ---------------------------------------------------------------------------

TABLE_BEGIN = ("<!-- crashpoint-table:begin "
               "(generated by tools/check/run.py --write-crashpoint-table) -->")
TABLE_END = "<!-- crashpoint-table:end -->"


def render_table() -> str:
    """The README crashpoint table, grouped by commit window —
    generated, never hand-edited (the `crashpoint` drift check pins
    it)."""
    lines: List[str] = []
    window = None
    for cp in CRASHPOINTS.values():
        if cp.window != window:
            window = cp.window
            if lines:
                lines.append("")
            lines.append(f"**{window}**")
            lines.append("")
            lines.append("| Crashpoint | Fires |")
            lines.append("|---|---|")
        lines.append(f"| `{cp.name}` | {cp.doc} |")
    return "\n".join(lines) + "\n"
