"""incidents — black-box capture bundles (the flight recorder).

The reference ships ``madmin`` health-diagnostics bundles because
counters alone cannot answer "what happened at 14:32". Here the
answer is captured AT 14:32: when a trigger event lands in the
journal (SLO breach, drive probation, network partition, unrepaired
fsck findings, registry fork — knob-configurable), the recorder
snapshots everything a postmortem needs into one JSON bundle under
``.minio.sys/incidents/``:

* the recent journal window (the causal timeline across subsystems),
* the top slow span trees from the SpanSink (where the latency went),
* the metric-registry delta since the last capture (what moved),
* live state providers: healthtrack, membership, topology, SLO status.

Bundles are bounded (retention knob), debounced per trigger class (a
flapping trigger must not churn the retention window), and retrieved
via ``GET /minio/admin/v3/incidents`` / ``madmin`` /
``minio_tpu incidents``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from . import atomicfile, eventlog, knobs, telemetry


def _trigger_classes() -> set:
    return {c.strip() for c in
            knobs.get_str("MINIO_TPU_INCIDENT_EVENTS").split(",")
            if c.strip()}


class IncidentRecorder:
    """Journal-hub subscriber that turns trigger events into bundles.

    One per process (the journal it watches is process-global);
    ``attach()`` is idempotent so multi-node-in-process tests boot it
    once. State providers are callables registered at boot — they run
    at capture time, never on the hot path."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._dir: Optional[str] = None
        self._providers: Dict[str, Callable[[], object]] = {}
        self._last_capture: Dict[str, float] = {}   # class -> ts
        self._metrics_base: dict = {}
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.captured_total = 0

    # -- wiring ------------------------------------------------------------

    def attach(self, dir_path: str) -> None:
        with self._mu:
            if self._dir is not None:
                return
            os.makedirs(dir_path, exist_ok=True)
            self._dir = dir_path
            self._metrics_base = telemetry.REGISTRY.snapshot(
                "minio_tpu_")
            self._stop.clear()
            self._worker = threading.Thread(
                target=self._watch, daemon=True,
                name="incident-capture")
            self._worker.start()

    def add_provider(self, name: str,
                     fn: Callable[[], object]) -> None:
        with self._mu:
            self._providers.setdefault(name, fn)

    def stop(self) -> None:
        self._stop.set()
        t = self._worker
        if t is not None and t.is_alive():
            t.join(timeout=5)

    # -- trigger loop ------------------------------------------------------

    def _watch(self) -> None:
        with eventlog.JOURNAL.hub.subscribe() as sub:
            while not self._stop.is_set():
                entry = sub.get(timeout=0.5)
                if entry is None:
                    continue
                if not knobs.get_bool("MINIO_TPU_INCIDENTS"):
                    continue
                cls = entry.get("class", "")
                if cls not in _trigger_classes():
                    continue
                if not self._debounce_ok(cls):
                    continue
                try:
                    self.capture(entry)
                except Exception:  # noqa: BLE001 — capture is best-effort
                    pass

    def _debounce_ok(self, cls: str) -> bool:
        now = time.monotonic()
        window = knobs.get_float("MINIO_TPU_INCIDENT_DEBOUNCE_S")
        with self._mu:
            last = self._last_capture.get(cls, 0.0)
            if now - last < window:
                return False
            self._last_capture[cls] = now
            return True

    # -- capture -----------------------------------------------------------

    @staticmethod
    def _metrics_delta(base: dict, cur: dict) -> dict:
        """Series that moved since the last capture — counters as
        numeric deltas, histograms as {sum, count} deltas, gauges as
        their current value (a gauge's delta is meaningless)."""
        out: dict = {}
        for name, series in cur.items():
            base_series = base.get(name, {})
            moved = {}
            for lk, v in series.items():
                b = base_series.get(lk)
                if isinstance(v, dict):
                    db = b if isinstance(b, dict) else {}
                    d = {"sum": round(v.get("sum", 0)
                                      - db.get("sum", 0), 6),
                         "count": v.get("count", 0)
                         - db.get("count", 0)}
                    if d["count"]:
                        moved[lk] = d
                elif isinstance(b, (int, float)):
                    if v != b:
                        moved[lk] = round(v - b, 6)
                elif v:
                    moved[lk] = v
            if moved:
                out[name] = moved
        return out

    def capture(self, trigger: dict) -> Optional[str]:
        """Write one bundle; returns its incident id (None when the
        recorder is detached)."""
        with self._mu:
            dir_path = self._dir
            providers = dict(self._providers)
            base = self._metrics_base
        if dir_path is None:
            return None
        now = time.time()
        cls = trigger.get("class", "unknown")
        self.captured_total += 1
        inc_id = "inc-%d-%03d-%s" % (
            int(now), self.captured_total % 1000,
            cls.replace(".", "-"))
        cur = telemetry.REGISTRY.snapshot("minio_tpu_")
        state = {}
        for name, fn in providers.items():
            try:
                state[name] = fn()
            except Exception as e:  # noqa: BLE001 — a dead provider
                state[name] = {"error": f"{type(e).__name__}: {e}"}
        bundle = {
            "v": 1,
            "id": inc_id,
            "time": now,
            "node": eventlog.JOURNAL.node,
            "trigger": trigger,
            "events": eventlog.JOURNAL.recent(
                knobs.get_int("MINIO_TPU_INCIDENT_WINDOW")),
            "slow_spans": telemetry.SPANS.dump(5, slowest=True),
            "metrics_delta": self._metrics_delta(base, cur),
            "state": state,
        }
        with self._mu:
            self._metrics_base = cur
        path = os.path.join(dir_path, inc_id + ".json")
        atomicfile.write_atomic(
            path, (json.dumps(bundle) + "\n").encode())
        self._prune(dir_path)
        eventlog.emit("incident.captured", trigger=cls,
                      incident=inc_id, events=len(bundle["events"]))
        return inc_id

    def _prune(self, dir_path: str) -> None:
        keep = knobs.get_int("MINIO_TPU_INCIDENT_KEEP")
        try:
            names = sorted(n for n in os.listdir(dir_path)
                           if n.startswith("inc-")
                           and n.endswith(".json"))
        except OSError:
            return
        for old in names[:max(0, len(names) - keep)]:
            try:
                os.unlink(os.path.join(dir_path, old))
            except OSError:
                pass

    # -- readback ----------------------------------------------------------

    def list(self) -> List[dict]:
        """Newest-first bundle summaries (admin /incidents)."""
        with self._mu:
            dir_path = self._dir
        if dir_path is None:
            return []
        out = []
        try:
            names = sorted(n for n in os.listdir(dir_path)
                           if n.startswith("inc-")
                           and n.endswith(".json"))
        except OSError:
            return []
        for name in reversed(names):
            doc = self._read(os.path.join(dir_path, name))
            if doc is None:
                continue
            out.append({
                "id": doc.get("id", name[:-5]),
                "time": doc.get("time"),
                "node": doc.get("node", ""),
                "trigger": (doc.get("trigger") or {}).get("class", ""),
                "events": len(doc.get("events") or ()),
            })
        return out

    def get(self, inc_id: str) -> Optional[dict]:
        with self._mu:
            dir_path = self._dir
        if dir_path is None or "/" in inc_id or os.sep in inc_id:
            return None
        return self._read(os.path.join(dir_path, inc_id + ".json"))

    @staticmethod
    def _read(path: str) -> Optional[dict]:
        try:
            with open(path, "rb") as f:
                doc = atomicfile.load_json_doc(f.read())
        except OSError:
            return None
        return doc if isinstance(doc, dict) else None


RECORDER = IncidentRecorder()
