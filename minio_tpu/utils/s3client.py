"""Minimal SigV4 S3 client (used by the S3 gateway and replication).

Covers the verbs the gateway's ObjectLayer surface needs: bucket CRUD +
list, object put/get/stat/delete, ListObjectsV2. Streaming GET bodies.
"""

from __future__ import annotations

import hashlib
import http.client
import urllib.parse
import xml.etree.ElementTree as ET
from email.utils import parsedate_to_datetime
from typing import Iterator, Optional

from ..s3 import signature as sig
from ..s3.credentials import Credentials

_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


def _findall(el, tag):
    return list(el.findall(tag)) + list(el.findall(_NS + tag))


def _text(el, tag, default=""):
    r = el.find(tag)
    if r is None:
        r = el.find(_NS + tag)
    return (r.text or "") if r is not None and r.text is not None \
        else default


class S3ClientError(Exception):
    def __init__(self, status: int, code: str, body: bytes = b""):
        super().__init__(f"{status} {code}")
        self.status = status
        self.code = code
        self.body = body


class S3Client:
    def __init__(self, host: str, port: int, creds: Credentials,
                 region: str = "us-east-1", timeout: float = 60.0,
                 secure: bool = False):
        self.host, self.port = host, port
        self.secure = secure
        self.creds = creds
        self.region = region
        self.timeout = timeout

    # -- plumbing ----------------------------------------------------------

    def _request(self, method: str, path: str,
                 query: Optional[dict] = None, body: bytes = b"",
                 headers: Optional[dict] = None, stream: bool = False):
        query = {k: [v] for k, v in (query or {}).items()}
        qs = urllib.parse.urlencode({k: v[0] for k, v in query.items()})
        hdrs = {k.lower(): v for k, v in (headers or {}).items()}
        hdrs["host"] = f"{self.host}:{self.port}"
        hdrs = sig.sign_v4(method, urllib.parse.quote(path), query, hdrs,
                           hashlib.sha256(body).hexdigest(), self.creds,
                           self.region)
        conn_cls = http.client.HTTPSConnection if self.secure \
            else http.client.HTTPConnection
        conn = conn_cls(self.host, self.port,
                                          timeout=self.timeout)
        conn.request(method, urllib.parse.quote(path) +
                     (f"?{qs}" if qs else ""), body=body, headers=hdrs)
        resp = conn.getresponse()
        if resp.status >= 300:
            data = resp.read()
            conn.close()
            code = ""
            try:
                code = _text(ET.fromstring(data), "Code")
            except ET.ParseError:
                pass
            raise S3ClientError(resp.status, code, data)
        if stream:
            return conn, resp
        data = resp.read()
        out_headers = {k.lower(): v for k, v in resp.getheaders()}
        conn.close()
        return out_headers, data

    # -- buckets -----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        self._request("PUT", f"/{bucket}")

    def delete_bucket(self, bucket: str) -> None:
        self._request("DELETE", f"/{bucket}")

    def bucket_exists(self, bucket: str) -> bool:
        try:
            self._request("HEAD", f"/{bucket}")
            return True
        except S3ClientError:
            return False

    def list_buckets(self) -> list[tuple[str, float]]:
        _, data = self._request("GET", "/")
        out = []
        root = ET.fromstring(data)
        for b in root.iter():
            if b.tag.endswith("Bucket"):
                name = _text(b, "Name")
                if name:
                    out.append((name, 0.0))
        return out

    # -- objects -----------------------------------------------------------

    def put_object(self, bucket: str, key: str, body: bytes,
                   metadata: Optional[dict] = None) -> str:
        hdrs = dict(metadata or {})
        h, _ = self._request("PUT", f"/{bucket}/{key}", body=body,
                             headers=hdrs)
        return h.get("etag", "").strip('"')

    def head_object(self, bucket: str, key: str) -> dict:
        h, _ = self._request("HEAD", f"/{bucket}/{key}")
        return h

    def get_object(self, bucket: str, key: str, offset: int = 0,
                   length: int = -1) -> tuple[dict, Iterator[bytes]]:
        hdrs = {}
        if offset or length >= 0:
            end = "" if length < 0 else str(offset + length - 1)
            hdrs["range"] = f"bytes={offset}-{end}"
        conn, resp = self._request("GET", f"/{bucket}/{key}",
                                   headers=hdrs, stream=True)
        out_headers = {k.lower(): v for k, v in resp.getheaders()}

        def gen():
            try:
                while True:
                    chunk = resp.read(1 << 16)
                    if not chunk:
                        return
                    yield chunk
            finally:
                conn.close()

        return out_headers, gen()

    def delete_object(self, bucket: str, key: str) -> None:
        self._request("DELETE", f"/{bucket}/{key}")

    def list_objects_v2(self, bucket: str, prefix: str = "",
                        delimiter: str = "",
                        continuation: str = "", max_keys: int = 1000
                        ) -> tuple[list[dict], list[str], str]:
        q = {"list-type": "2", "max-keys": str(max_keys)}
        if prefix:
            q["prefix"] = prefix
        if delimiter:
            q["delimiter"] = delimiter
        if continuation:
            q["continuation-token"] = continuation
        _, data = self._request("GET", f"/{bucket}", query=q)
        root = ET.fromstring(data)
        objs = []
        for c in _findall(root, "Contents"):
            lm = _text(c, "LastModified")
            try:
                mt = parsedate_to_datetime(lm).timestamp()
            except (TypeError, ValueError):
                try:
                    import datetime as _dt
                    mt = _dt.datetime.fromisoformat(
                        lm.replace("Z", "+00:00")).timestamp()
                except ValueError:
                    mt = 0.0
            objs.append({"key": _text(c, "Key"),
                         "size": int(_text(c, "Size", "0") or 0),
                         "etag": _text(c, "ETag").strip('"'),
                         "mod_time": mt})
        prefixes = [_text(p, "Prefix")
                    for p in _findall(root, "CommonPrefixes")]
        token = _text(root, "NextContinuationToken")
        return objs, prefixes, token
