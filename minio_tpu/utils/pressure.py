"""Foreground-pressure probe shared by the background movers.

Both the pool rebalancer (object/rebalance.py) and the tier transition
worker (tier/transition.py) must yield to foreground traffic: they
back off whenever the live ``BatchScheduler`` shows queued encode
blocks or the shared ``BytePool`` staging rings report fresh waits —
the same two signals the admission plane sheds on. This is the single
home of that probe so the two movers cannot drift apart.
"""

from __future__ import annotations

from typing import Callable, Optional

from . import backoff_delay


class ForegroundPressure:
    """Samples scheduler occupancy + staging-ring waits of an object
    layer (ErasureServerSets, ErasureSets, or anything with ``sets``).

    ``busy_fn`` overrides the probe entirely (tests / custom gating).
    """

    def __init__(self, object_layer,
                 busy_fn: Optional[Callable[[], bool]] = None):
        self.obj = object_layer
        self._busy_fn = busy_fn
        self._last_pool_waits: Optional[int] = None

    def _layers(self):
        return getattr(self.obj, "server_sets", None) or [self.obj]

    def busy(self) -> bool:
        """True when foreground traffic is visibly queued: any engine's
        scheduler has blocks waiting for a device batch, or the staging
        BytePool accumulated NEW waits since the last sample."""
        if self._busy_fn is not None:
            return bool(self._busy_fn())
        queued = 0
        for z in self._layers():
            for eng in getattr(z, "sets", ()) or ():
                sched = getattr(eng, "scheduler", None)
                if sched is not None:
                    queued += sched.stats()["queued_blocks"]
        if queued > 0:
            return True
        from ..parallel import pipeline
        waits = pipeline.pool_pressure()["waits"]
        last, self._last_pool_waits = self._last_pool_waits, waits
        return last is not None and waits > last

    def throttle(self, stop_event, base_s: float, max_s: float,
                 tries: int) -> None:
        """Back off while busy, up to `tries` capped-exponential waits;
        after the cap, proceed anyway (a permanently-loaded cluster must
        still make background progress, just at the slow cadence)."""
        for attempt in range(tries):
            if stop_event.is_set() or not self.busy():
                return
            stop_event.wait(backoff_delay(base_s, max_s, attempt))
