"""Declarative registry of every ``MINIO_TPU_*`` tuning knob.

Before this module, ~45 env knobs were scattered as raw
``os.environ.get("MINIO_TPU_…")`` reads across a dozen modules, each
with its own parsing idiom (`_env_f`, `_env_int`, `_flag`, inline
``int(...)``) and a hand-maintained README table that drifted from the
code. Now every knob is declared HERE — name, type, default, doc — and
read through the typed getters below. ``tools/check`` enforces the
discipline two ways:

  * the ``knob-env`` lint rule fails any raw ``MINIO_TPU_*`` environ
    access outside this module (and any getter call naming an
    unregistered knob);
  * ``tools/check/knobtable.py`` regenerates the README knob table from
    this registry and the drift check fails when the committed table
    disagrees.

Getters read the ENVIRONMENT at call time (never cached here): tests
flip knobs with ``monkeypatch.setenv`` and modules that want an
import-time snapshot simply call the getter at module scope, exactly
like the old reads. Parse failures fall back to the declared default —
a typo'd value must degrade to documented behavior, not crash the
server at boot.

Boolean knobs accept ``on/1/true/yes`` and ``off/0/false/no``
(case-insensitive); anything else means the default. Defaults may be
callables (evaluated per read) for host-derived values such as the
staging-ring size; ``display`` carries the README-facing rendering of
such defaults ("2×cores", "64 MiB").
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Union

__all__ = [
    "Knob", "KNOBS", "define", "get", "all_knobs",
    "get_str", "get_int", "get_float", "get_bool", "get_raw", "is_set",
    "render_table", "TABLE_BEGIN", "TABLE_END",
]

_TRUE = ("on", "1", "true", "yes")
_FALSE = ("off", "0", "false", "no")

Default = Union[str, int, float, bool, Callable[[], Union[int, float, str]]]


class Knob:
    """One declared knob: name, type, default, one-line doc."""

    __slots__ = ("name", "type", "default", "doc", "section", "display")

    def __init__(self, name: str, type_: str, default: Default,
                 doc: str, section: str, display: str = ""):
        assert name.startswith("MINIO_TPU_"), name
        assert type_ in ("str", "int", "float", "bool"), type_
        self.name = name
        self.type = type_
        self.default = default
        self.doc = doc
        self.section = section
        self.display = display

    def resolve_default(self):
        d = self.default
        return d() if callable(d) else d

    def default_display(self) -> str:
        if self.display:
            return self.display
        d = self.resolve_default()
        if self.type == "bool":
            return "on" if d else "off"
        return str(d)


KNOBS: Dict[str, Knob] = {}


def define(name: str, type_: str, default: Default, doc: str,
           section: str, display: str = "") -> Knob:
    assert name not in KNOBS, f"knob {name} declared twice"
    k = Knob(name, type_, default, doc, section, display)
    KNOBS[name] = k
    return k


def get(name: str) -> Knob:
    try:
        return KNOBS[name]
    except KeyError:
        raise KeyError(f"unregistered knob {name!r} — declare it in "
                       "minio_tpu/utils/knobs.py") from None


def all_knobs() -> List[Knob]:
    return list(KNOBS.values())


# ---------------------------------------------------------------------------
# typed getters — the ONLY sanctioned MINIO_TPU_* environment reads
# ---------------------------------------------------------------------------

def get_raw(name: str) -> Optional[str]:
    """The raw environment value, or None when unset. Registered knobs
    only (a typo'd name must fail loudly, not silently default)."""
    get(name)
    return os.environ.get(name)


def is_set(name: str) -> bool:
    get(name)
    return name in os.environ


def get_str(name: str) -> str:
    k = get(name)
    v = os.environ.get(name)
    return str(k.resolve_default()) if v is None else v


def get_int(name: str) -> int:
    k = get(name)
    v = os.environ.get(name)
    if v is not None:
        try:
            return int(v)
        except ValueError:
            pass
    return int(k.resolve_default())


def get_float(name: str) -> float:
    k = get(name)
    v = os.environ.get(name)
    if v is not None:
        try:
            return float(v)
        except ValueError:
            pass
    return float(k.resolve_default())


def get_bool(name: str) -> bool:
    k = get(name)
    v = os.environ.get(name)
    if v is not None:
        s = v.strip().lower()
        if s in _TRUE:
            return True
        if s in _FALSE:
            return False
    return bool(k.resolve_default())


# ---------------------------------------------------------------------------
# the registry — grouped by plane, in README table order
# ---------------------------------------------------------------------------

_S = "Data path"
define("MINIO_TPU_PIPELINE", "bool", True,
       "`off` selects the serial PUT/GET hot loops", _S)
define("MINIO_TPU_PIPELINE_DEPTH", "int", 2,
       "bounded queue depth between pipeline stages", _S)
define("MINIO_TPU_PIPELINE_POOL", "int",
       lambda: 2 * (os.cpu_count() or 4),
       "staging buffers per geometry ring (boot re-derives from the "
       "admission budget; the env knob wins)", _S, display="2×cores")
define("MINIO_TPU_PIPELINE_POOL_TIMEOUT_S", "float", 60.0,
       "staging-buffer wait before the PUT fails loudly", _S)
define("MINIO_TPU_ENCODE_BATCH", "int", 8,
       "blocks fused per PUT encode+digest call", _S)
define("MINIO_TPU_GET_BATCH", "int", 8,
       "blocks fused per GET verify/decode call", _S)
define("MINIO_TPU_HEAL_BATCH", "int", 8,
       "blocks fused per heal recover call", _S)
define("MINIO_TPU_DEVICE_MIN_BYTES", "int", 8 << 20,
       "batch bytes below which the codec stays on the host path", _S,
       display="8 MiB")
define("MINIO_TPU_MESH", "str", "",
       "`1` forces mesh dispatch on any multi-device backend, `0` "
       "disables; default meshes only multi-device TPU pools", _S,
       display="auto")
define("MINIO_TPU_DIRECT_IO", "bool", False,
       "`on` = O_DIRECT shard writes (page-cache bypass; buffered "
       "fallback where the filesystem refuses)", _S)

_S = "Batch former"
define("MINIO_TPU_SCHED_MAX_BATCH", "int", 32,
       "blocks per fused device dispatch", _S)
define("MINIO_TPU_SCHED_MAX_WAIT_MS", "float", 3.0,
       "cross-request coalescing grace window, milliseconds", _S)
define("MINIO_TPU_SCHED_INFLIGHT", "int", 2,
       "concurrent dispatches in flight (transfer/compute overlap)", _S)

define("MINIO_TPU_SCHED_ATTRIB", "bool", True,
       "`off` disables per-dispatch stage attribution (queue/transfer/"
       "compute/fetch histograms + child spans) — the overhead A/B "
       "escape hatch", _S)

_S = "SSE device path"
define("MINIO_TPU_SSE_CIPHER", "str", "aes-gcm",
       "package cipher for NEW SSE writes: `aes-gcm` (CPU DARE "
       "packages) or `chacha20` (ChaCha20-Poly1305, device-fusable); "
       "reads dispatch on each object's recorded cipher", _S)
define("MINIO_TPU_SSE_DEVICE", "str", "on",
       "`off` pins chacha20 SSE to the CPU stage (byte-identical "
       "stream); `on` fuses cipher+RS+digest into one device launch "
       "per PUT batch when a device is present", _S)
define("MINIO_TPU_SSE_DEVICE_MIN_BYTES", "int", 1 << 20,
       "smallest PUT (stated size) that rides the fused SSE device "
       "path; smaller or unknown-length streams stay on the CPU "
       "cipher", _S, display="1 MiB")
define("MINIO_TPU_SSE_DEVICE_MAX_BYTES", "int", 0,
       "upper bound of the fused-SSE size window (device-capacity "
       "guard); 0 = unbounded", _S)

_S = "Server"
define("MINIO_TPU_MAX_CLIENTS", "int", 0,
       "admission-gate size; 0 derives it from the RAM+CPU budget", _S,
       display="auto")
define("MINIO_TPU_REQUEST_DEADLINE", "float", 10.0,
       "seconds a request waits on admission before SlowDown", _S)
define("MINIO_TPU_SHED_WINDOW_S", "float", 5.0,
       "shed data writes this long after a staging-pool timeout", _S)
define("MINIO_TPU_ADMIT_SCHED_QUEUE", "int", 0,
       "queued device-batch blocks above which data writes shed "
       "(scheduler-occupancy admission signal; 0 disables)", _S,
       display="off")
define("MINIO_TPU_REQUEST_QUEUE", "int", 128,
       "threaded-listener accept backlog (socketserver "
       "request_queue_size)", _S)
define("MINIO_TPU_IAM_REFRESH_S", "float", 300.0,
       "full IAM cache refresh interval (bounded staleness)", _S)

_S = "Multi-tenant QoS"
define("MINIO_TPU_QOS", "bool", False,
       "enforce per-tenant admission shares and budgets at the "
       "admission gate (off = byte-identical legacy behavior)", _S)
define("MINIO_TPU_QOS_DEFAULT_SHARE", "float", 1.0,
       "admission-share weight for tenants without a registered "
       "budget", _S)
define("MINIO_TPU_QOS_DEFAULT_RPS", "float", 0.0,
       "default per-tenant request-rate budget (requests/s); "
       "0 = unlimited", _S, display="off")
define("MINIO_TPU_QOS_DEFAULT_RX_BPS", "float", 0.0,
       "default per-tenant request-body byte budget (bytes/s); "
       "0 = unlimited", _S, display="off")
define("MINIO_TPU_QOS_DEFAULT_TX_BPS", "float", 0.0,
       "default per-tenant response-body byte budget (bytes/s); "
       "0 = unlimited", _S, display="off")
define("MINIO_TPU_QOS_ACTIVE_S", "float", 2.0,
       "seconds since last request a tenant stays in the active set "
       "the share math divides the gate across", _S)
define("MINIO_TPU_QOS_SHED_WINDOW_S", "float", 5.0,
       "debounce window for tenant.shed journal events (first shed "
       "per tenant per window)", _S)

_S = "HTTP edge"
define("MINIO_TPU_EDGE", "bool", True,
       "`off` selects the threaded frontend (escape hatch and "
       "correctness oracle; TLS listeners always use it)", _S)
define("MINIO_TPU_EDGE_WORKERS", "int", 1,
       "event-loop threads; >1 binds one SO_REUSEPORT listener per "
       "loop", _S)
define("MINIO_TPU_EDGE_MAX_CONNS", "int", 8192,
       "open-connection budget per edge server; beyond it new "
       "connections shed 503 before any read", _S)
define("MINIO_TPU_EDGE_HEADER_S", "float", 10.0,
       "deadline for a complete request line + headers (slowloris "
       "partial requests shed at expiry)", _S)
define("MINIO_TPU_EDGE_IDLE_S", "float", 120.0,
       "idle keep-alive connection deadline (quiet close)", _S)
define("MINIO_TPU_EDGE_POOL", "int", 0,
       "blocking handler worker threads behind the event loop "
       "(0 = 8×cores + 16)", _S, display="auto")
define("MINIO_TPU_EDGE_LAG_S", "float", 1.0,
       "event-loop lag sampler interval (each tick observes how late "
       "the loop ran it into minio_tpu_edge_loop_lag_seconds; "
       "0 disables)", _S)

_S = "Fault plane"
define("MINIO_TPU_MRF_QUEUE_SIZE", "int", 10000,
       "max queued MRF heal entries (overflow drops)", _S)
define("MINIO_TPU_MRF_MAX_RETRIES", "int", 10,
       "heal retries before an entry counts failed", _S)
define("MINIO_TPU_MRF_BACKOFF_BASE", "float", 0.05,
       "first heal-retry delay, seconds (doubles per retry)", _S)
define("MINIO_TPU_MRF_BACKOFF_MAX", "float", 15.0,
       "heal-retry delay cap, seconds (schedule spans ~40 s — past "
       "the 10 s drive re-probe and the probe backoff)", _S)
define("MINIO_TPU_RPC_RETRIES", "int", 2,
       "extra attempts for idempotent RPC verbs", _S)
define("MINIO_TPU_RPC_RETRY_BACKOFF", "float", 0.05,
       "first RPC retry delay, seconds", _S)
define("MINIO_TPU_RPC_RETRY_BACKOFF_MAX", "float", 2.0,
       "RPC retry delay cap, seconds", _S)
define("MINIO_TPU_DISK_PROBE_S", "float", 10.0,
       "DiskMonitor scan interval: dead-slot re-probes AND slow-drive "
       "health evaluation run on this cadence", _S)
define("MINIO_TPU_PEER_PROBE_S", "float", 30.0,
       "offline peer health-probe backoff cap, seconds (any "
       "successful direct call re-admits the host immediately)", _S)
define("MINIO_TPU_CHAOS_SEED", "str", "",
       "replay a chaos test's exact fault schedule (tests print the "
       "failing seed)", _S, display="per-test")

_S = "Gray-failure plane"
define("MINIO_TPU_LAT_WINDOW", "int", 64,
       "latency samples retained per (drive/peer, verb) window", _S)
define("MINIO_TPU_HEDGE", "bool", True,
       "`off` disables latency-hedged shard reads (error-triggered "
       "hedging stays)", _S)
define("MINIO_TPU_HEDGE_K", "float", 3.0,
       "hedge deadline = healthy read p95 × this", _S)
define("MINIO_TPU_HEDGE_FLOOR_S", "float", 0.05,
       "hedge deadline floor, seconds", _S)
define("MINIO_TPU_HEDGE_CEIL_S", "float", 2.0,
       "hedge deadline ceiling, seconds (also the cold-start value "
       "before any latency samples exist)", _S)
define("MINIO_TPU_QUORUM_ACK", "bool", True,
       "`off` makes every shard-write fan-out wait for ALL drives "
       "again instead of acking at write quorum and abandoning "
       "laggards to the MRF-fed background lane", _S)
define("MINIO_TPU_WRITE_STALL_K", "float", 4.0,
       "write-straggler grace = healthy write p95 × this", _S)
define("MINIO_TPU_WRITE_STALL_FLOOR_S", "float", 0.5,
       "write-straggler grace floor, seconds", _S)
define("MINIO_TPU_WRITE_STALL_CEIL_S", "float", 10.0,
       "write-straggler grace ceiling, seconds (cold-start value)", _S)
define("MINIO_TPU_QUARANTINE", "bool", True,
       "`off` disables the slow-drive suspect/probation state machine",
       _S)
define("MINIO_TPU_QUAR_LATENCY_S", "float", 0.25,
       "absolute p95 latency above which a drive turns suspect", _S)
define("MINIO_TPU_QUAR_RATIO", "float", 8.0,
       "relative conviction bar: suspect needs p95 above healthy-peer "
       "p95 × this too (uniformly slow media quarantine nothing)", _S)
define("MINIO_TPU_QUAR_MIN_SAMPLES", "int", 8,
       "read/write samples required before a drive can be convicted",
       _S)
define("MINIO_TPU_QUAR_PROBATION_S", "float", 15.0,
       "suspect dwell before probation re-probes begin", _S)
define("MINIO_TPU_QUAR_PROBES", "int", 3,
       "consecutive healthy probation probes before the heal-verified "
       "re-admission", _S)

_S = "Partition tolerance"
define("MINIO_TPU_NAUGHTYNET", "bool", False,
       "`on` exposes the test-only naughtynet admin verb so harnesses "
       "can partition a live node's internode transport", _S)
define("MINIO_TPU_NAUGHTYNET_SEED", "int", 0,
       "default seed for the naughtynet fault schedule (chaos tests "
       "print the seed they armed)", _S, display="0")
define("MINIO_TPU_RPC_STREAM_READ_S", "float", 30.0,
       "per-read socket deadline on streamed RPC responses: a peer "
       "that goes silent mid-stream fails the reader instead of "
       "parking it forever (0 disables)", _S)
define("MINIO_TPU_REGISTRY_WRITE_QUORUM", "str", "1",
       "pools an epoch-registry write must land on before the commit "
       "is acked: a count, or `majority` — below it the write refuses "
       "instead of bumping the epoch on a minority side", _S)
define("MINIO_TPU_PEER_SHED_DEADLINE_X", "float", 4.0,
       "peer fan-out deadline tightening: effective deadline = min("
       "default, observed peer p99 × this), floored at 0.5 s "
       "(0 disables the healthtrack-derived tightening)", _S)

_S = "Telemetry"
define("MINIO_TPU_TRACE_SLOW_MS", "float", 500.0,
       "span trees at least this slow are always kept", _S)
define("MINIO_TPU_TRACE_SAMPLE", "float", 0.0,
       "keep-probability for ordinary (fast, error-free) traces", _S)
define("MINIO_TPU_TRACE_KEEP", "int", 128,
       "kept span-tree ring size", _S)
define("MINIO_TPU_TRACE_MAX_SPANS", "int", 512,
       "span budget per trace; extras no-op and are counted as "
       "`spans_dropped`", _S)
define("MINIO_TPU_CLUSTER_SCRAPE_S", "float", 2.0,
       "per-peer deadline for the federated metrics scrape "
       "(?cluster=1); a peer past it degrades the scrape and counts in "
       "minio_tpu_cluster_scrape_failed_total", _S)
define("MINIO_TPU_TRACE_FOLLOW_MAX_S", "float", 3600.0,
       "hard lifetime cap on a ?follow=1 trace stream (a forgotten "
       "client cannot hold peer subscriptions forever)", _S)

_S = "Topology"
define("MINIO_TPU_REBALANCE_MPU_GRACE_S", "float", 30.0,
       "live multipart sessions idle less than this get a grace "
       "before the decommission drain migrates them off the pool", _S)
define("MINIO_TPU_REBALANCE_CHECKPOINT_EVERY", "int", 16,
       "objects moved between drain checkpoints", _S)
define("MINIO_TPU_REBALANCE_PAGE", "int", 256,
       "rebalance listing page size", _S)
define("MINIO_TPU_REBALANCE_BACKOFF_S", "float", 0.05,
       "first drain backoff when the foreground is busy", _S)
define("MINIO_TPU_REBALANCE_BACKOFF_MAX_S", "float", 1.0,
       "drain backoff cap, seconds", _S)
define("MINIO_TPU_REBALANCE_BACKOFF_TRIES", "int", 8,
       "busy polls before the drain proceeds anyway", _S)

_S = "Tiering"
define("MINIO_TPU_TIER_QUEUE_SIZE", "int", 10000,
       "max queued tier-transition entries", _S)
define("MINIO_TPU_TIER_BACKOFF_S", "float", 0.05,
       "first transition backoff when the foreground is busy", _S)
define("MINIO_TPU_TIER_BACKOFF_MAX_S", "float", 1.0,
       "transition backoff cap, seconds", _S)
define("MINIO_TPU_TIER_BACKOFF_TRIES", "int", 8,
       "busy polls before a transition proceeds anyway", _S)

_S = "Replication"
define("MINIO_TPU_REPL_WORKERS", "int", 2,
       "sync workers draining the replication queue", _S)
define("MINIO_TPU_REPL_QUEUE", "int", 10000,
       "max queued (bucket, key) sync tasks (overflow drops; the "
       "resync verb is the backstop)", _S)
define("MINIO_TPU_REPL_BACKOFF_S", "float", 0.05,
       "first replication backoff when the foreground is busy", _S)
define("MINIO_TPU_REPL_BACKOFF_MAX_S", "float", 1.0,
       "replication backoff cap, seconds", _S)
define("MINIO_TPU_REPL_BACKOFF_TRIES", "int", 8,
       "busy polls before a sync proceeds anyway", _S)
define("MINIO_TPU_REPL_BW_BPS", "int", 0,
       "default per-target push bandwidth budget, bytes/sec "
       "(0 = unlimited; a target's own bw_bps wins)", _S,
       display="unlimited")
define("MINIO_TPU_REPL_RESYNC_CHECKPOINT_EVERY", "int", 16,
       "keys pushed between resync checkpoints", _S)
define("MINIO_TPU_REPL_RESYNC_PAGE", "int", 256,
       "resync listing page size", _S)

_S = "Tiering (restore)"
define("MINIO_TPU_RESTORE_ASYNC_BYTES", "int", 64 << 20,
       "RestoreObject switches to 202 + background tier pull at this "
       "size (0 = always synchronous)", _S, display="64 MiB")

_S = "Metacache"
define("MINIO_TPU_METACACHE", "bool", True,
       "`off` = exactly the old merge-walk listing behavior", _S)
define("MINIO_TPU_METACACHE_FEED", "bool", True,
       "scanners consume the index namespace feed", _S)
define("MINIO_TPU_METACACHE_STALENESS_S", "float", 2.0,
       "serve-time staleness bound (older deltas drain synchronously)",
       _S)
define("MINIO_TPU_METACACHE_FLUSH_S", "float", 0.2,
       "journal drain cadence, seconds", _S)
define("MINIO_TPU_METACACHE_PERSIST_S", "float", 30.0,
       "min seconds between persisted segment writes", _S)
define("MINIO_TPU_METACACHE_RECONCILE_S", "float", 300.0,
       "drift-repair walk cadence, seconds", _S)
define("MINIO_TPU_METACACHE_SEGMENT_KEYS", "int", 5000,
       "keys per persisted index segment", _S)
define("MINIO_TPU_METACACHE_JOURNAL", "int", 100000,
       "max pending deltas (overflow invalidates the bucket until "
       "reconcile — never a silent wrong listing)", _S)

_S = "Scan plane"
define("MINIO_TPU_SCAN_DEVICE", "str", "on",
       "`on` rides the device when one is present, `off` forces the "
       "CPU evaluator, `force` dispatches even on CPU backends "
       "(tests/bench)", _S)
define("MINIO_TPU_SCAN_PAGE_ROWS", "int", 2048,
       "rows per tokenized column page (fixed shape = stable jit "
       "cache)", _S)
define("MINIO_TPU_SCAN_MAX_STR", "int", 128,
       "widest cacheable string cell; wider cells decline to CPU", _S)
define("MINIO_TPU_SCAN_KERNEL_CACHE", "int", 64,
       "bounded LRU of compiled scan kernels (signatures bake in "
       "query literals)", _S)
define("MINIO_TPU_SCAN_MAX_BYTES", "int", 64 << 20,
       "device-path input cap; bigger objects stream via CPU", _S,
       display="64 MiB")

_S = "Hot-object cache"
define("MINIO_TPU_CACHE", "bool", False,
       "master switch for the erasure-aware read cache", _S)
define("MINIO_TPU_CACHE_DIR", "str", "",
       "cache entry directory", _S,
       display="<first-drive>/.minio.sys/cache")
define("MINIO_TPU_CACHE_BUDGET_BYTES", "int", 1 << 30,
       "watermark LRU budget", _S, display="1 GiB")
define("MINIO_TPU_CACHE_ADMIT", "int", 2,
       "GETs inside the window before an object is admitted", _S)
define("MINIO_TPU_CACHE_ADMIT_WINDOW_S", "float", 300.0,
       "access-frequency admission window, seconds", _S)

_S = "Events"
define("MINIO_TPU_QUEUE_FSYNC", "bool", False,
       "fsync durable event-queue writes (survives power loss)", _S)

_S = "Notifications"
define("MINIO_TPU_NOTIFY_WORKERS", "int", 2,
       "delivery workers draining the notification queue", _S)
define("MINIO_TPU_NOTIFY_QUEUE", "int", 10000,
       "max queued (bucket, key) namespace events (overflow drops + "
       "counts; delivery never blocks a mutation)", _S)
define("MINIO_TPU_NOTIFY_BACKOFF_S", "float", 0.05,
       "first delivery backoff when the foreground is busy", _S)
define("MINIO_TPU_NOTIFY_BACKOFF_MAX_S", "float", 1.0,
       "delivery backoff cap, seconds", _S)
define("MINIO_TPU_NOTIFY_BACKOFF_TRIES", "int", 8,
       "busy polls before a delivery proceeds anyway", _S)
define("MINIO_TPU_NOTIFY_STORE_LIMIT", "int", 10000,
       "per-target delivery backlog cap (overflow drops + counts — "
       "bounded memory/disk against a dead target)", _S)
define("MINIO_TPU_NOTIFY_OFFLINE_S", "float", 2.0,
       "offline window after a failed delivery: new events for that "
       "target queue without burning a send timeout each", _S)
define("MINIO_TPU_NOTIFY_REDRIVE_S", "float", 5.0,
       "periodic backlog redrive cadence, seconds", _S)
define("MINIO_TPU_NOTIFY_REPLICA_EVENTS", "bool", False,
       "`on` = replica-apply writes fire bucket notifications too "
       "(reference parity keeps them suppressed: replication does not "
       "re-fire source events)", _S)

_S = "Crash consistency"
define("MINIO_TPU_FSYNC", "bool", False,
       "`on` = fsync barriers on commit paths (fsync before rename, "
       "directory fsync after; shard files synced at close) — "
       "power-loss durability at real I/O cost", _S)
define("MINIO_TPU_CRASHPOINT", "str", "",
       "`<name>[:<nth>]` hard-exits the process (os._exit 137) at the "
       "Nth hit of the named crashpoint — the kill/restart harness's "
       "deterministic crash injector (see README crashpoint table)", _S,
       display="unset")
define("MINIO_TPU_FSCK_BOOT", "bool", False,
       "`on` runs the fsck consistency auditor (repair mode) at "
       "cluster boot, feeding repairable findings to heal/MRF", _S)
define("MINIO_TPU_FSCK_TMP_AGE_S", "float", 3600.0,
       "staged tmp writes older than this count as crash leftovers "
       "for fsck (younger ones may be in-flight PUTs)", _S)

_S = "Incident plane"
define("MINIO_TPU_EVENTLOG", "bool", True,
       "`off` disables the structured event journal (emits drop; the "
       "overhead A/B escape hatch)", _S)
define("MINIO_TPU_EVENTLOG_RING", "int", 2048,
       "in-memory journal ring size (the /events backlog bound)", _S)
define("MINIO_TPU_EVENTLOG_SEGMENT_EVENTS", "int", 64,
       "pending events that force an early segment flush", _S)
define("MINIO_TPU_EVENTLOG_FLUSH_S", "float", 2.0,
       "journal segment flush cadence, seconds", _S)
define("MINIO_TPU_EVENTLOG_KEEP_SEGMENTS", "int", 16,
       "persisted journal segments retained (older ones pruned)", _S)
define("MINIO_TPU_EVENTS_FOLLOW_MAX_S", "float", 3600.0,
       "hard lifetime cap on a ?follow=1 event stream (a forgotten "
       "client cannot hold peer subscriptions forever)", _S)
define("MINIO_TPU_SLO", "bool", True,
       "`off` disables the SLO burn-rate engine (gauges stop, no "
       "breach events)", _S)
define("MINIO_TPU_SLO_EVAL_S", "float", 5.0,
       "SLO evaluation cadence, seconds", _S)
define("MINIO_TPU_SLO_WINDOWS_S", "str", "60,300",
       "comma-separated burn-rate windows, seconds (multi-window "
       "alerting: short catches fast burn, long catches slow leaks)",
       _S)
define("MINIO_TPU_SLO_AVAIL_TARGET", "float", 99.9,
       "availability objective, percent of non-5xx responses per API "
       "class", _S)
define("MINIO_TPU_SLO_LAT_TARGET", "float", 99.0,
       "latency objective, percent of requests under the class "
       "threshold", _S)
define("MINIO_TPU_SLO_LAT_READ_MS", "float", 250.0,
       "read-class latency threshold, milliseconds", _S)
define("MINIO_TPU_SLO_LAT_WRITE_MS", "float", 1000.0,
       "write-class latency threshold, milliseconds", _S)
define("MINIO_TPU_SLO_BURN_THRESHOLD", "float", 4.0,
       "burn rate at which an objective breaches (clears at half "
       "this — hysteresis stops breach/clear flapping)", _S)
define("MINIO_TPU_SLO_MIN_SAMPLES", "int", 10,
       "requests a window must hold before its burn rate can breach "
       "(a single early 500 must not page)", _S)
define("MINIO_TPU_INCIDENTS", "bool", True,
       "`off` disables black-box incident capture", _S)
define("MINIO_TPU_INCIDENT_KEEP", "int", 16,
       "incident bundles retained on disk (older ones pruned)", _S)
define("MINIO_TPU_INCIDENT_DEBOUNCE_S", "float", 30.0,
       "min seconds between captures for the same trigger class "
       "(a flapping trigger must not fill the retention window)", _S)
define("MINIO_TPU_INCIDENT_EVENTS", "str",
       "slo.breach,drive.probation,net.partition,fsck.unrepaired,"
       "registry.fork",
       "comma-separated journal event classes that trigger a capture",
       _S)
define("MINIO_TPU_INCIDENT_WINDOW", "int", 256,
       "journal entries snapshotted into each bundle", _S)

_S = "Lock watchdog"
define("MINIO_TPU_LOCKCHECK", "bool", False,
       "instrument named locks: record the cross-thread acquisition "
       "graph, fail on order cycles (on under the chaos/concurrency "
       "suites)", _S)
define("MINIO_TPU_LOCKCHECK_RAISE", "bool", True,
       "raise LockOrderError at the acquire that closes a cycle "
       "(off = record only)", _S)
define("MINIO_TPU_LOCKCHECK_BLOCK_MS", "float", 200.0,
       "acquire wait above this while holding another lock is flagged "
       "held-while-blocking", _S)
define("MINIO_TPU_LOCKCHECK_HELD_MS", "float", 1000.0,
       "hold duration above this is flagged as a long hold", _S)

del _S


# ---------------------------------------------------------------------------
# README table generator (tools/check/knobtable.py drift-checks this)
# ---------------------------------------------------------------------------

TABLE_BEGIN = "<!-- knob-table:begin (generated by tools/check/run.py --write-knob-table) -->"
TABLE_END = "<!-- knob-table:end -->"


def render_table() -> str:
    """The README knob table, grouped by plane — generated, never
    hand-edited (the `knob-env` drift check pins it)."""
    lines: List[str] = []
    section = None
    for k in KNOBS.values():
        if k.section != section:
            section = k.section
            if lines:
                lines.append("")
            lines.append(f"**{section}**")
            lines.append("")
            lines.append("| Knob | Default | Effect |")
            lines.append("|---|---|---|")
        lines.append(f"| `{k.name}` | {k.default_display()} | {k.doc} |")
    return "\n".join(lines) + "\n"
