"""Shared stream adapters.

IterStream is the single home of the file-like-over-chunk-iterator
shim that the rebalancer, the tier transition worker, and the S3
handlers all need (each previously carried its own copy): buffer the
iterator, serve .read(n), forward close() to the source so abandoned
generators release their locks.
"""

from __future__ import annotations

from typing import Iterator


class IterStream:
    """File-like adapter over an iterator of byte chunks."""

    def __init__(self, it: Iterator[bytes]):
        self._it = it
        self._buf = b""
        self._eof = False

    def read(self, n: int = -1) -> bytes:
        while not self._eof and (n < 0 or len(self._buf) < n):
            try:
                self._buf += next(self._it)
            except StopIteration:
                self._eof = True
        if n < 0:
            out, self._buf = self._buf, b""
        else:
            out, self._buf = self._buf[:n], self._buf[n:]
        return bytes(out)

    def close(self) -> None:
        close = getattr(self._it, "close", None)
        if close is not None:
            close()
