"""Self-tuning operation timeouts (cmd/dynamic-timeouts.go).

Tracks the recent outcomes of timed operations; when >25% of a window
hit the deadline the timeout grows 25%, when >95% finish in under half
the deadline it shrinks 25%, clamped to [minimum, maximum].
"""

from __future__ import annotations

import threading

WINDOW = 16
GROW = 1.25
SHRINK = 0.75
TOO_SLOW_FRACTION = 0.25
FAST_FRACTION = 0.95


class DynamicTimeout:
    def __init__(self, timeout: float, minimum: float,
                 maximum: float = 0.0):
        self._timeout = timeout
        self.minimum = minimum
        self.maximum = maximum or timeout * 16
        self._mu = threading.Lock()
        self._entries: list[tuple[float, bool]] = []  # (duration, timedout)

    def timeout(self) -> float:
        with self._mu:
            return self._timeout

    def log_success(self, duration: float) -> None:
        self._log(duration, False)

    def log_failure(self) -> None:
        """The operation hit its deadline."""
        self._log(self._timeout, True)

    def _log(self, duration: float, timedout: bool) -> None:
        with self._mu:
            self._entries.append((duration, timedout))
            if len(self._entries) < WINDOW:
                return
            entries, self._entries = self._entries, []
            timeouts = sum(1 for _, t in entries if t)
            fast = sum(1 for d, t in entries
                       if not t and d < self._timeout / 2)
            if timeouts / len(entries) > TOO_SLOW_FRACTION:
                self._timeout = min(self._timeout * GROW, self.maximum)
            elif fast / len(entries) > FAST_FRACTION:
                self._timeout = max(self._timeout * SHRINK, self.minimum)
