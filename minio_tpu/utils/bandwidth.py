"""Per-bucket bandwidth monitor (reference pkg/bandwidth + admin
/bandwidth): rolling byte-rate measurement for ingress (PUT bodies)
and egress (GET streams), aggregated cluster-wide over the peer plane.

A 10-slot one-second ring per (bucket, direction) gives a smoothed
bytes/sec without unbounded state; totals accumulate forever.
"""

from __future__ import annotations

import threading
import time

WINDOW_SLOTS = 10          # seconds of rate window


class _Meter:
    __slots__ = ("slots", "head", "total")

    def __init__(self):
        self.slots = [0] * WINDOW_SLOTS
        self.head = int(time.monotonic())   # second of the newest slot
        self.total = 0

    def record(self, n: int, now: float) -> None:
        sec = int(now)
        if sec > self.head:
            if sec - self.head >= WINDOW_SLOTS:
                self.slots = [0] * WINDOW_SLOTS
            else:
                for s in range(self.head + 1, sec + 1):
                    self.slots[s % WINDOW_SLOTS] = 0
            self.head = sec
        self.slots[sec % WINDOW_SLOTS] += n
        self.total += n

    def rate(self, now: float) -> float:
        self.record(0, now)            # expire stale slots
        return sum(self.slots) / WINDOW_SLOTS


class BandwidthMonitor:
    def __init__(self):
        self._mu = threading.Lock()
        self._meters: dict[tuple[str, str], _Meter] = {}

    def record(self, bucket: str, direction: str, n: int) -> None:
        """direction: 'rx' (client->server bytes) or 'tx'."""
        if n <= 0 or not bucket:
            return
        now = time.monotonic()
        with self._mu:
            meter = self._meters.get((bucket, direction))
            if meter is None:
                meter = self._meters[(bucket, direction)] = _Meter()
            meter.record(n, now)

    def counting_stream(self, bucket: str, stream):
        """Wrap a GET chunk iterator, recording egress as it flows."""
        def gen():
            for chunk in stream:
                self.record(bucket, "tx", len(chunk))
                yield chunk
        return gen()

    def report(self) -> dict:
        """{bucket: {rx_bps, tx_bps, rx_total, tx_total}}"""
        now = time.monotonic()
        out: dict[str, dict] = {}
        with self._mu:
            for (bucket, direction), meter in self._meters.items():
                b = out.setdefault(bucket, {
                    "rx_bps": 0.0, "tx_bps": 0.0,
                    "rx_total": 0, "tx_total": 0})
                b[f"{direction}_bps"] = round(meter.rate(now), 1)
                b[f"{direction}_total"] = meter.total
        return out


class TokenBucket:
    """Thread-safe token-bucket rate limiter (the per-target
    replication bandwidth budget): `take(n)` blocks until `n` bytes of
    budget are available, refilled at `rate_bps` with one second of
    burst. `rate_bps <= 0` means unlimited (take never blocks)."""

    def __init__(self, rate_bps: float, burst_s: float = 1.0):
        self.rate = float(rate_bps)
        self.burst = max(self.rate * burst_s, 1.0)
        self._mu = threading.Lock()
        self._tokens = self.burst
        self._last = time.monotonic()

    def set_rate(self, rate_bps: float, burst_s: float = 1.0) -> None:
        with self._mu:
            self.rate = float(rate_bps)
            self.burst = max(self.rate * burst_s, 1.0)
            self._tokens = min(self._tokens, self.burst)

    def take(self, n: int) -> None:
        # grant in installments of at most one burst: a single chunk
        # larger than the burst window (1 MiB blocks under a small
        # bw_bps) must pace across refills, not livelock waiting for a
        # token level the cap makes unreachable
        remaining = n
        while remaining > 0:
            with self._mu:
                if self.rate <= 0:
                    return
                now = time.monotonic()
                self._tokens = min(
                    self.burst, self._tokens + (now - self._last)
                    * self.rate)
                self._last = now
                want = min(remaining, self.burst)
                if self._tokens >= want:
                    self._tokens -= want
                    remaining -= want
                    continue
                wait = (want - self._tokens) / self.rate
            time.sleep(min(wait, 1.0))

    def paced(self, stream, on_bytes=None):
        """Wrap a chunk iterator: each chunk waits for budget before it
        flows; `on_bytes(n)` observes the paced bytes (the monitor's
        record hook)."""
        def gen():
            for chunk in stream:
                self.take(len(chunk))
                if on_bytes is not None:
                    on_bytes(len(chunk))
                yield chunk
        return gen()


def merge_reports(reports: list[dict]) -> dict:
    """Sum per-bucket meters across nodes (cluster-wide view)."""
    merged: dict[str, dict] = {}
    for rep in reports:
        if not isinstance(rep, dict):
            continue
        for bucket, vals in rep.items():
            b = merged.setdefault(bucket, {
                "rx_bps": 0.0, "tx_bps": 0.0,
                "rx_total": 0, "tx_total": 0})
            for key in b:
                b[key] = round(b[key] + vals.get(key, 0), 1)
    return merged
