"""Per-bucket bandwidth monitor (reference pkg/bandwidth + admin
/bandwidth): rolling byte-rate measurement for ingress (PUT bodies)
and egress (GET streams), aggregated cluster-wide over the peer plane.

A 10-slot one-second ring per (bucket, direction) gives a smoothed
bytes/sec without unbounded state; totals accumulate forever.
"""

from __future__ import annotations

import threading
import time

WINDOW_SLOTS = 10          # seconds of rate window


class _Meter:
    __slots__ = ("slots", "head", "total")

    def __init__(self):
        self.slots = [0] * WINDOW_SLOTS
        self.head = int(time.monotonic())   # second of the newest slot
        self.total = 0

    def record(self, n: int, now: float) -> None:
        sec = int(now)
        if sec > self.head:
            if sec - self.head >= WINDOW_SLOTS:
                self.slots = [0] * WINDOW_SLOTS
            else:
                for s in range(self.head + 1, sec + 1):
                    self.slots[s % WINDOW_SLOTS] = 0
            self.head = sec
        self.slots[sec % WINDOW_SLOTS] += n
        self.total += n

    def rate(self, now: float) -> float:
        self.record(0, now)            # expire stale slots
        return sum(self.slots) / WINDOW_SLOTS


class BandwidthMonitor:
    def __init__(self):
        self._mu = threading.Lock()
        self._meters: dict[tuple[str, str], _Meter] = {}

    def record(self, bucket: str, direction: str, n: int) -> None:
        """direction: 'rx' (client->server bytes) or 'tx'."""
        if n <= 0 or not bucket:
            return
        now = time.monotonic()
        with self._mu:
            meter = self._meters.get((bucket, direction))
            if meter is None:
                meter = self._meters[(bucket, direction)] = _Meter()
            meter.record(n, now)

    def counting_stream(self, bucket: str, stream):
        """Wrap a GET chunk iterator, recording egress as it flows."""
        def gen():
            for chunk in stream:
                self.record(bucket, "tx", len(chunk))
                yield chunk
        return gen()

    def report(self) -> dict:
        """{bucket: {rx_bps, tx_bps, rx_total, tx_total}}"""
        now = time.monotonic()
        out: dict[str, dict] = {}
        with self._mu:
            for (bucket, direction), meter in self._meters.items():
                b = out.setdefault(bucket, {
                    "rx_bps": 0.0, "tx_bps": 0.0,
                    "rx_total": 0, "tx_total": 0})
                b[f"{direction}_bps"] = round(meter.rate(now), 1)
                b[f"{direction}_total"] = meter.total
        return out


class TokenBucket:
    """Thread-safe token-bucket rate limiter (the per-target
    replication bandwidth budget): `take(n)` blocks until `n` bytes of
    budget are available, refilled at `rate_bps` with one second of
    burst. `rate_bps <= 0` means unlimited (take never blocks)."""

    def __init__(self, rate_bps: float, burst_s: float = 1.0):
        self.rate = float(rate_bps)
        self.burst = max(self.rate * burst_s, 1.0)
        self._mu = threading.Lock()
        self._tokens = self.burst
        self._last = time.monotonic()

    def set_rate(self, rate_bps: float, burst_s: float = 1.0) -> None:
        with self._mu:
            self.rate = float(rate_bps)
            self.burst = max(self.rate * burst_s, 1.0)
            self._tokens = min(self._tokens, self.burst)

    def take(self, n: int) -> float:
        """Block until `n` tokens of budget were consumed; returns the
        total seconds slept (0.0 when the budget was immediately
        available — the QoS lag and tier-throttle counters observe
        this)."""
        # grant in installments of at most one burst: a single chunk
        # larger than the burst window (1 MiB blocks under a small
        # bw_bps) must pace across refills, not livelock waiting for a
        # token level the cap makes unreachable
        remaining = n
        waited = 0.0
        while remaining > 0:
            with self._mu:
                if self.rate <= 0:
                    return waited
                now = time.monotonic()
                self._tokens = min(
                    self.burst, self._tokens + (now - self._last)
                    * self.rate)
                self._last = now
                want = min(remaining, self.burst)
                if self._tokens >= want:
                    self._tokens -= want
                    remaining -= want
                    continue
                wait = (want - self._tokens) / self.rate
            wait = min(wait, 1.0)
            time.sleep(wait)
            waited += wait
        return waited

    def _refill_locked(self) -> None:
        now = time.monotonic()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now

    def try_take(self, n: int) -> float:
        """Non-blocking take: consume one burst-capped installment of
        `n` tokens if available NOW and return 0.0; otherwise consume
        nothing and return the seconds until that installment accrues
        (the Retry-After hint). The admission plane's refusal probe —
        the `admission` lint rule confines callers to the
        AdmissionController and the QoS plane."""
        if n <= 0:
            return 0.0
        with self._mu:
            if self.rate <= 0:
                return 0.0
            self._refill_locked()
            want = min(n, self.burst)
            if self._tokens >= want:
                self._tokens -= want
                return 0.0
            return (want - self._tokens) / self.rate

    def peek(self, n: int) -> float:
        """Like try_take but consumes NOTHING either way: 0.0 when one
        burst-capped installment of `n` is available now, else the
        seconds until it accrues. Lets admission refuse a payload whose
        per-tenant byte budget is exhausted without double-charging the
        stream pacer that meters the admitted bytes."""
        if n <= 0:
            return 0.0
        with self._mu:
            if self.rate <= 0:
                return 0.0
            self._refill_locked()
            want = min(n, self.burst)
            if self._tokens >= want:
                return 0.0
            return (want - self._tokens) / self.rate

    def paced(self, stream, on_bytes=None, on_wait=None):
        """Wrap a chunk iterator: each chunk waits for budget before it
        flows; `on_bytes(n)` observes the paced bytes (the monitor's
        record hook), `on_wait(seconds)` the throttle stalls."""
        def gen():
            for chunk in stream:
                waited = self.take(len(chunk))
                if waited > 0 and on_wait is not None:
                    on_wait(waited)
                if on_bytes is not None:
                    on_bytes(len(chunk))
                yield chunk
        return gen()


class PacedReader:
    """File-like wrapper pacing ``read()`` through a TokenBucket (the
    request-body twin of ``TokenBucket.paced``): bytes are paid for as
    they are delivered, ``on_bytes(n)`` observes the metered bytes and
    ``on_wait(seconds)`` the throttle stalls. An unlimited bucket
    (rate <= 0) degrades to pure accounting."""

    __slots__ = ("_inner", "_bucket", "_on_bytes", "_on_wait")

    def __init__(self, inner, bucket: TokenBucket,
                 on_bytes=None, on_wait=None):
        self._inner = inner
        self._bucket = bucket
        self._on_bytes = on_bytes
        self._on_wait = on_wait

    def read(self, n: int = -1) -> bytes:
        data = self._inner.read(n)
        if data:
            waited = self._bucket.take(len(data))
            if waited > 0 and self._on_wait is not None:
                self._on_wait(waited)
            if self._on_bytes is not None:
                self._on_bytes(len(data))
        return data

    def close(self) -> None:
        close = getattr(self._inner, "close", None)
        if close is not None:
            close()


def merge_reports(reports: list[dict]) -> dict:
    """Sum per-bucket meters across nodes (cluster-wide view)."""
    merged: dict[str, dict] = {}
    for rep in reports:
        if not isinstance(rep, dict):
            continue
        for bucket, vals in rep.items():
            b = merged.setdefault(bucket, {
                "rx_bps": 0.0, "tx_bps": 0.0,
                "rx_total": 0, "tx_total": 0})
            for key in b:
                b[key] = round(b[key] + vals.get(key, 0), 1)
    return merged
