"""Unified telemetry plane: metrics registry + request-scoped spans.

The reference answers "what is the server doing" with two surfaces —
`mc admin trace` (cmd/http-tracer.go over pkg/pubsub) and the
Prometheus endpoint (cmd/metrics.go). This module is the substrate
both are rebuilt on, plus the piece the reference lacks and a
TPU-scale data path needs: per-request SPAN TREES that cross layers
(S3 handler → engine → pipeline/scheduler → shard I/O → internode
RPC), so "where did this slow PUT spend its time" has an answer in
production, not only under a profiler.

Two halves:

* :data:`REGISTRY` — a process-global metrics registry
  (Counter / Gauge / Histogram, labels, `# HELP`/`# TYPE` Prometheus
  text exposition). Every subsystem reports here — the admin metrics
  handler renders it instead of hand-formatting gauge strings, and
  bench.py snapshots it per config. Collector callbacks registered
  with :meth:`MetricsRegistry.register_collector` run at exposition
  time so live values (queue depths, pool pressure) need no polling
  thread.

* the span tracer — `contextvars`-propagated spans. A server
  middleware opens a root span per request; ``with span("encode"):``
  anywhere below attaches a child to whatever span is current on this
  thread (fan-out pools forward the context explicitly,
  `contextvars.copy_context()` per task). Tracing is ZERO-allocation
  when no root span is active: ``span()`` returns a shared no-op.

Sampling is tail-based: the keep/drop decision happens when the ROOT
span finishes, so errors and slow requests are always kept no matter
how rare — head sampling would have dropped most of them before
knowing they mattered. Knobs (also README "Observability"):

  MINIO_TPU_TRACE_SAMPLE=0.0     keep-probability for ordinary traces
  MINIO_TPU_TRACE_SLOW_MS=500    always keep traces at least this slow
  MINIO_TPU_TRACE_KEEP=128       kept-trace ring size

Cross-process joins: the internode transport injects
``x-minio-trace-id`` / ``x-minio-span-id`` headers; the serving side
opens a `join()` span under that identity and records it as a
FRAGMENT. `SPANS.dump()` grafts fragments back into their parent
trees by span id — in one process (tests, single-node multi-drive)
the joined tree is complete; across real processes each node keeps
its own fragments for its own /spans endpoint.
"""

from __future__ import annotations

import bisect
import contextvars
import math
import random
import re
import threading
import time
import uuid
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Sequence

from . import knobs

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "Span", "SpanSink", "SPANS", "span", "trace", "join",
    "current_span", "attach_span", "propagating_context", "traced_iter",
]

# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# default latency buckets (seconds) — spans two orders of magnitude
# around typical object-op latencies on both tmpfs and spinning media
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _fmt(v: float) -> str:
    """Prometheus sample formatting: integers bare, floats plain."""
    if v == math.inf:
        return "+Inf"
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int) or (isinstance(v, float) and v.is_integer()
                              and abs(v) < 1e15):
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _render_labels(key: tuple, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(str(v))}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Family:
    """One metric family: name, help, type, samples keyed by labels."""

    kind = "untyped"

    def __init__(self, name: str, help_: str):
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        self.name = name
        self.help = help_
        self._mu = threading.Lock()
        self._series: Dict[tuple, object] = {}

    def _check_labels(self, labels: dict) -> tuple:
        for k in labels:
            if not _LABEL_RE.match(k):
                raise ValueError(f"bad label name {k!r}")
        return _label_key(labels)

    def clear(self) -> None:
        """Forget every series (label churn hygiene: per-bucket gauges
        refreshed from a snapshot drop deleted buckets)."""
        with self._mu:
            self._series.clear()

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._mu:
            items = sorted(self._series.items())
        for key, value in items:
            lines.extend(self._render_series(key, value))
        return lines

    def _render_series(self, key: tuple, value) -> List[str]:
        return [f"{self.name}{_render_labels(key)} {_fmt(value)}"]


class Counter(_Family):
    """Monotonic counter (optionally labelled)."""

    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> None:
        key = self._check_labels(labels)
        with self._mu:
            self._series[key] = self._series.get(key, 0) + amount

    def value(self, **labels) -> float:
        with self._mu:
            return self._series.get(_label_key(labels), 0)

    def series(self) -> Dict[tuple, float]:
        """label-key -> value snapshot (the SLO engine aggregates
        status-class counts across label sets)."""
        with self._mu:
            return dict(self._series)


class Gauge(_Family):
    """Settable instantaneous value (optionally labelled)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._check_labels(labels)
        with self._mu:
            self._series[key] = value

    def inc(self, amount: float = 1, **labels) -> None:
        key = self._check_labels(labels)
        with self._mu:
            self._series[key] = self._series.get(key, 0) + amount

    def value(self, **labels) -> float:
        with self._mu:
            return self._series.get(_label_key(labels), 0)


class _HistSeries:
    __slots__ = ("counts", "total", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets     # per-bucket (non-cumulative)
        self.total = 0.0
        self.count = 0


class Histogram(_Family):
    """Fixed-bucket histogram; exposes `_bucket` (cumulative, with a
    +Inf bucket), `_sum` and `_count` series per label set."""

    kind = "histogram"

    def __init__(self, name: str, help_: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_)
        self.buckets = tuple(sorted(buckets))

    def observe(self, value: float, **labels) -> None:
        key = self._check_labels(labels)
        idx = bisect.bisect_left(self.buckets, value)
        with self._mu:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistSeries(len(self.buckets) + 1)
            s.counts[idx] += 1
            s.total += value
            s.count += 1

    def count(self, **labels) -> int:
        with self._mu:
            s = self._series.get(_label_key(labels))
            return s.count if s is not None else 0

    def series_snapshot(self) -> Dict[tuple, tuple]:
        """label-key -> (per-bucket counts, sum, count), consistent
        per series — the SLO engine derives over-threshold fractions
        from bucket counts without reaching into family internals."""
        with self._mu:
            return {key: (list(s.counts), s.total, s.count)
                    for key, s in self._series.items()}

    def _render_series(self, key: tuple, s: "_HistSeries") -> List[str]:
        # snapshot under the family lock: a concurrent observe()
        # mutates counts/total/count together, and a torn read here
        # could emit _bucket{+Inf} < _count (breaks the histogram
        # invariant scrapers rely on)
        with self._mu:
            counts = list(s.counts)
            total, count = s.total, s.count
        out = []
        cum = 0
        for le, c in zip(self.buckets + (math.inf,), counts):
            cum += c
            le_pair = 'le="' + _fmt(le) + '"'
            out.append(f"{self.name}_bucket"
                       f"{_render_labels(key, le_pair)} {cum}")
        out.append(f"{self.name}_sum{_render_labels(key)} "
                   f"{_fmt(round(total, 9))}")
        out.append(f"{self.name}_count{_render_labels(key)} {count}")
        return out


class MetricsRegistry:
    """Process-global family registry. Getter methods are idempotent:
    the first call creates the family, later calls return it (and
    reject a kind mismatch — two subsystems silently sharing one name
    with different types is exactly the bug a registry exists to
    catch)."""

    def __init__(self) -> None:
        from . import lockcheck
        self._mu = lockcheck.mutex("telemetry.registry")
        self._families: Dict[str, _Family] = {}
        self._collectors: List[Callable[[], None]] = []

    def _get(self, cls, name: str, help_: str, **kw):
        with self._mu:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = cls(name, help_, **kw)
            elif not isinstance(fam, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{fam.kind}, not {cls.kind}")
            return fam

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(Counter, name, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(Gauge, name, help_)

    def histogram(self, name: str, help_: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get(Histogram, name, help_, buckets=buckets)

    def register_collector(self, fn: Callable[[], None]) -> None:
        """`fn()` runs before every render — the hook live-value
        subsystems (queue depth, pool pressure) refresh gauges from."""
        with self._mu:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def _run_collectors(self) -> None:
        with self._mu:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:  # noqa: BLE001 — telemetry is passive
                pass

    def render(self, extra: Optional[Callable[[], None]] = None) -> str:
        """Prometheus text exposition of every family. `extra` is a
        one-shot collector run after the registered ones — a metrics
        endpoint passes its own server-scoped refresh here instead of
        registering globally, so several servers in one process each
        scrape THEIR values (last-registered-wins clobbering) and a
        dead server stops reporting."""
        self._run_collectors()
        if extra is not None:
            try:
                extra()
            except Exception:  # noqa: BLE001 — telemetry is passive
                pass
        with self._mu:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        lines: List[str] = []
        for fam in fams:
            lines.extend(fam.render())
        return "\n".join(lines) + "\n"

    def snapshot(self, prefix: str = "") -> dict:
        """name -> {labels-json: value} (histograms: {sum, count}) —
        the bench's registry snapshot."""
        self._run_collectors()
        with self._mu:
            fams = [f for f in self._families.values()
                    if f.name.startswith(prefix)]
        out: dict = {}
        for fam in fams:
            series = {}
            with fam._mu:       # consistent sum/count pairs
                for key, v in fam._series.items():
                    lk = ",".join(f"{k}={val}" for k, val in key) or ""
                    if isinstance(v, _HistSeries):
                        series[lk] = {"sum": round(v.total, 6),
                                      "count": v.count}
                    else:
                        series[lk] = v
            out[fam.name] = series
        return out


REGISTRY = MetricsRegistry()


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------

TRACE_HEADER = "x-minio-trace-id"
SPAN_HEADER = "x-minio-span-id"

SLOW_S = knobs.get_float("MINIO_TPU_TRACE_SLOW_MS") / 1e3
SAMPLE = knobs.get_float("MINIO_TPU_TRACE_SAMPLE")
KEEP = knobs.get_int("MINIO_TPU_TRACE_KEEP")
# spans per TRACE cap: a 10 GiB distributed PUT would otherwise
# materialize one span per block per drive (~100k objects) and the
# kept ring would pin all of them; past the budget span() returns the
# no-op and the root counts what was dropped
MAX_SPANS = knobs.get_int("MINIO_TPU_TRACE_MAX_SPANS")

_current: "contextvars.ContextVar[Optional[Span]]" = \
    contextvars.ContextVar("minio_tpu_span", default=None)


class Span:
    """One timed operation in a request's tree. Children append under
    the parent's lock — stage threads and drive fan-outs attach
    concurrently."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start",
                 "t0", "duration_s", "attrs", "error", "children",
                 "remote", "_mu", "_token", "root", "has_error",
                 "slow_exempt", "n_spans", "n_dropped")

    def __init__(self, name: str, trace_id: str, parent_id: str = "",
                 attrs: Optional[dict] = None, remote: bool = False,
                 root: Optional["Span"] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:12]
        self.parent_id = parent_id
        self.start = time.time()
        self.t0 = time.perf_counter()
        self.duration_s = 0.0
        self.attrs = attrs or {}
        self.error = ""
        self.children: List[Span] = []
        self.remote = remote
        self._mu = threading.Lock()
        self._token = None
        # tree root (None when self IS the root): child errors set the
        # root's has_error so the tail-sampling keep decision is O(1)
        # instead of walking the whole tree per request
        self.root = root
        self.has_error = False
        # long-poll/streaming admin surfaces run for minutes by design:
        # exempt from the keep-if-slow rule (errors still keep)
        self.slow_exempt = False
        # per-trace span budget accounting (root only): spans created /
        # spans dropped past MAX_SPANS
        self.n_spans = 0
        self.n_dropped = 0

    def mark_error(self, msg: str) -> None:
        if not self.error:
            self.error = msg
        (self.root or self).has_error = True

    def _admit_child(self) -> bool:
        """Charge one span against this ROOT's budget; False = the
        trace is at MAX_SPANS and the caller should no-op."""
        with self._mu:
            if self.n_spans >= MAX_SPANS:
                self.n_dropped += 1
                return False
            self.n_spans += 1
            return True

    def add_child(self, child: "Span") -> None:
        with self._mu:
            self.children.append(child)

    def finish(self) -> None:
        self.duration_s = time.perf_counter() - self.t0

    def depth(self) -> int:
        with self._mu:
            kids = list(self.children)
        return 1 + max((c.depth() for c in kids), default=0)

    def walk(self) -> Iterable["Span"]:
        yield self
        with self._mu:
            kids = list(self.children)
        for c in kids:
            yield from c.walk()

    def to_dict(self) -> dict:
        with self._mu:
            kids = list(self.children)
        d = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "start": round(self.start, 6),
            "duration_ms": round(self.duration_s * 1e3, 3),
        }
        if self.parent_id:
            d["parent_id"] = self.parent_id
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.error:
            d["error"] = self.error
        if self.remote:
            d["remote"] = True
        if self.n_dropped:
            # spans not recorded past the per-trace MAX_SPANS budget —
            # "covered everything" must not be implied when it wasn't
            d["spans_dropped"] = self.n_dropped
        if kids:
            d["children"] = [c.to_dict() for c in kids]
        return d


class _SpanCtx:
    """Context manager that opens `span` on enter (making it current on
    this thread) and finishes it on exit. `root` spans are offered to
    the sink; `fragment` spans are recorded as RPC-join fragments."""

    __slots__ = ("span", "root", "fragment")

    def __init__(self, sp: Span, root: bool = False,
                 fragment: bool = False):
        self.span = sp
        self.root = root
        self.fragment = fragment

    def __enter__(self) -> Span:
        self.span._token = _current.set(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        sp = self.span
        if sp._token is not None:
            _current.reset(sp._token)
            sp._token = None
        if exc is not None:
            sp.mark_error(f"{type(exc).__name__}: {exc}")
        elif sp.error:
            (sp.root or sp).has_error = True
        sp.finish()
        if self.root:
            SPANS.offer(sp)
        elif self.fragment:
            SPANS.record_fragment(sp)
        return False


class _NoopSpanCtx:
    """Shared do-nothing context manager — the zero-cost path when no
    trace is active on this thread."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc) -> bool:
        return False


_NOOP = _NoopSpanCtx()


def current_span() -> Optional[Span]:
    return _current.get()


def trace(name: str, trace_id: str = "", **attrs) -> _SpanCtx:
    """Open a ROOT span (a new trace). Used by the server middleware
    and the bench; everything below attaches via span()."""
    sp = Span(name, trace_id or uuid.uuid4().hex[:16],
              attrs=attrs or None)
    return _SpanCtx(sp, root=True)


def span(name: str, parent: Optional[Span] = None, **attrs):
    """Child span of `parent` (default: the current span on this
    thread). Returns a shared no-op when there is no active trace, so
    instrumented hot paths cost one context-var read when idle."""
    p = parent if parent is not None else _current.get()
    if p is None:
        return _NOOP
    root = p.root or p
    if not root._admit_child():
        return _NOOP
    sp = Span(name, p.trace_id, parent_id=p.span_id,
              attrs=attrs or None, root=root)
    p.add_child(sp)
    return _SpanCtx(sp)


def join(name: str, trace_id: str, parent_span_id: str = "",
         **attrs) -> _SpanCtx:
    """Server-side half of an internode RPC: open a span under the
    CALLER's trace identity. Finished joined spans are recorded as
    fragments; dump() grafts them back into the caller's tree."""
    sp = Span(name, trace_id, parent_id=parent_span_id,
              attrs=attrs or None, remote=True)
    return _SpanCtx(sp, fragment=True)


def traced_iter(name: str, it, **attrs):
    """Span over a CHUNK STREAM: yields from `it` with the span made
    current only WHILE the underlying iterator runs (set/reset around
    each next()), never across a yield. A plain `with span():` inside
    a generator would mutate the CONSUMER's context (PEP 567:
    generators don't get their own) and an abandoned generator (ranged
    reads, client hangups) would leak the span as that thread's
    current until GC — and then reset a foreign-context token. The
    span's duration covers first-to-last chunk; abandonment finishes
    it from the generator's close."""
    parent = _current.get()
    if parent is None:
        yield from it
        return
    root = parent.root or parent
    if not root._admit_child():
        yield from it
        return
    sp = Span(name, parent.trace_id, parent_id=parent.span_id,
              attrs=attrs or None, root=root)
    parent.add_child(sp)
    try:
        while True:
            token = _current.set(sp)
            try:
                try:
                    chunk = next(it)
                except StopIteration:
                    return
            finally:
                _current.reset(token)
            yield chunk
    except GeneratorExit:
        # the CONSUMER abandoned the stream (client hangup, ranged
        # probe) — routine, not an error: tail-keeping every
        # disconnect would crowd the ring with content-free trees
        sp.attrs["aborted"] = True
        raise
    except BaseException as e:
        sp.mark_error(f"{type(e).__name__}: {e}")
        raise
    finally:
        sp.finish()
        # abandonment (GeneratorExit) must close the inner generator
        # NOW, not at GC: its finally blocks release locks and join
        # in-flight prefetch work (`yield from` did this implicitly)
        close = getattr(it, "close", None)
        if close is not None:
            close()


def attach_span(parent: Span, name: str, start_wall: float,
                duration_s: float, **attrs) -> Optional[Span]:
    """Attach an externally-timed, already-finished span (work done on
    a shared thread no contextvar reaches, e.g. the batch scheduler's
    collector) under `parent`. Returns the new span (so the caller can
    attach stage children under it), or None past the trace's span
    budget."""
    root = parent.root or parent
    if not root._admit_child():
        return None
    sp = Span(name, parent.trace_id, parent_id=parent.span_id,
              attrs=attrs or None, root=root)
    sp.start = start_wall
    sp.duration_s = duration_s
    parent.add_child(sp)
    return sp


def propagating_context() -> Optional[contextvars.Context]:
    """A context copy carrying the current span, or None when no trace
    is active. Fan-out pools call this per task (`ctx.run(fn)`) —
    one Context object must not run in two threads at once, so every
    task needs its own copy."""
    if _current.get() is None:
        return None
    return contextvars.copy_context()


class SpanSink:
    """Tail-sampled store of finished traces + RPC-join fragments."""

    def __init__(self, capacity: int = KEEP,
                 slow_s: float = SLOW_S, sample: float = SAMPLE):
        from . import lockcheck
        self._mu = lockcheck.mutex("telemetry.spans")
        self.capacity = capacity
        self.slow_s = slow_s
        self.sample = sample
        self._kept: "deque[Span]" = deque(maxlen=capacity)
        # trace_id -> [fragment spans]; bounded FIFO eviction
        self._fragments: Dict[str, List[Span]] = {}
        self._fragment_order: "deque[str]" = deque()
        self._fragment_cap = 4 * capacity
        self.kept_total = 0
        self.dropped_total = 0

    def configure(self, slow_s: Optional[float] = None,
                  sample: Optional[float] = None) -> None:
        if slow_s is not None:
            self.slow_s = slow_s
        if sample is not None:
            self.sample = sample

    # -- ingest ------------------------------------------------------------

    def offer(self, root: Span) -> bool:
        """Tail-sampling: always keep errors and slow traces; keep the
        rest with probability `sample`. O(1): child errors were
        propagated to root.has_error as each span finished."""
        keep = bool(root.error) or root.has_error \
            or (root.duration_s >= self.slow_s
                and not root.slow_exempt) \
            or (self.sample > 0 and random.random() < self.sample)
        with self._mu:
            if keep:
                self._kept.append(root)
                self.kept_total += 1
            else:
                self.dropped_total += 1
        return keep

    def record_fragment(self, sp: Span) -> None:
        with self._mu:
            frags = self._fragments.get(sp.trace_id)
            if frags is None:
                frags = self._fragments[sp.trace_id] = []
                self._fragment_order.append(sp.trace_id)
                while len(self._fragment_order) > self._fragment_cap:
                    evicted = self._fragment_order.popleft()
                    self._fragments.pop(evicted, None)
            if len(frags) < 64:           # bound one trace's fragments
                frags.append(sp)

    # -- readback ----------------------------------------------------------

    def _graft(self, tree: dict, frags: List[Span]) -> None:
        """Attach fragments under the span that made the RPC (matched
        by parent span id); unmatched fragments land under the root."""
        index: Dict[str, dict] = {}

        def walk(node: dict) -> None:
            index[node["span_id"]] = node
            for c in node.get("children", ()):
                walk(c)

        walk(tree)
        for f in frags:
            target = index.get(f.parent_id, tree)
            target.setdefault("children", []).append(f.to_dict())

    def dump(self, n: int = 50, slowest: bool = False,
             name: str = "", trace_id: str = "") -> List[dict]:
        """Most recent (or slowest) kept traces as dict trees, with
        matching fragments grafted in. `name` keeps only roots with
        that span name (the per-API filter: root names ARE api names
        under the server middleware); `trace_id` selects one trace.
        Filters apply BEFORE the count cut, so `n` counts matches."""
        with self._mu:
            kept = list(self._kept)
            frags = {tid: list(fs) for tid, fs in self._fragments.items()}
        if name:
            kept = [s for s in kept if s.name == name]
        if trace_id:
            kept = [s for s in kept if s.trace_id == trace_id]
        if slowest:
            kept.sort(key=lambda s: -s.duration_s)
        else:
            kept.reverse()                # newest first
        out = []
        for root in kept[:max(n, 0)]:
            tree = root.to_dict()
            if root.trace_id in frags:
                self._graft(tree, frags[root.trace_id])
            out.append(tree)
        return out

    def clear(self) -> None:
        with self._mu:
            self._kept.clear()
            self._fragments.clear()
            self._fragment_order.clear()


SPANS = SpanSink()
