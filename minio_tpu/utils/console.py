"""In-memory console log ring (reference: ring-buffered console log
served to `mc admin console` via the peer /log verb, cmd/logger +
peer-rest-common.go:56).

One ring per process (singleton): subsystems log through the standard
`logging` machinery (a handler bridges records in) or the direct
`log()` API; the admin/peer planes read `recent()` and merge rings
across nodes.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Optional


class ConsoleLogSys(logging.Handler):
    def __init__(self, capacity: int = 1000, node: str = ""):
        super().__init__()
        self.node = node
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=capacity)
        self._mu = threading.Lock()

    # -- logging.Handler bridge -------------------------------------------

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001 — logging must never throw
            msg = str(record.msg)
        self.log_line(record.levelname, msg)

    # -- direct API --------------------------------------------------------

    def log_line(self, level: str, message: str) -> None:
        entry = {"time": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                       time.gmtime()),
                 "ts": time.time(), "level": level,
                 "node": self.node, "message": message}
        with self._mu:
            self._ring.append(entry)

    def recent(self, n: int = 0) -> list[dict]:
        with self._mu:
            entries = list(self._ring)
        return entries[-n:] if n else entries

    def install(self, logger_name: str = "minio_tpu",
                level: int = logging.INFO) -> None:
        lg = logging.getLogger(logger_name)
        if self not in lg.handlers:
            lg.addHandler(self)
        if lg.level == logging.NOTSET or lg.level > level:
            lg.setLevel(level)


_console: Optional[ConsoleLogSys] = None
_mu = threading.Lock()


def get_console() -> ConsoleLogSys:
    """Process-wide ring (lazily created, handler installed on the
    minio_tpu logger tree)."""
    global _console
    with _mu:
        if _console is None:
            _console = ConsoleLogSys()
            _console.install()
        return _console
