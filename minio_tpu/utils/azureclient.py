"""Azure Blob Storage REST client: SharedKey auth + the blob-service
subset the gateway needs (reference cmd/gateway/azure/gateway-azure.go
drives the Azure Go SDK; this speaks the documented REST surface
directly so the gateway is dependency-free and offline-testable).

Auth follows the published SharedKey scheme (2019-12-12 service
version): HMAC-SHA256 over VERB + canonicalized standard headers +
canonicalized x-ms-* headers + canonicalized resource, keyed by the
base64-decoded account key. The HTTP connection factory is injectable,
so tests run against an in-process server (Azurite-style).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import http.client
import time
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Callable, Iterator, Optional

API_VERSION = "2019-12-12"


class AzureClientError(Exception):
    def __init__(self, status: int, code: str, body: bytes = b""):
        super().__init__(f"{status} {code}")
        self.status = status
        self.code = code
        self.body = body


def _rfc1123_now() -> str:
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime())


def shared_key_signature(account: str, key_b64: str, method: str,
                         path: str, query: dict[str, str],
                         headers: dict[str, str]) -> str:
    """StringToSign per the SharedKey spec; returns the base64 HMAC."""
    h = {k.lower(): v for k, v in headers.items()}
    std = [h.get("content-encoding", ""), h.get("content-language", ""),
           # Content-Length: empty string when 0 (2015-02-21+ behavior)
           h.get("content-length", "") if h.get("content-length", "")
           not in ("0",) else "",
           h.get("content-md5", ""), h.get("content-type", ""),
           # Date is carried in x-ms-date, so the Date line is empty
           "",
           h.get("if-modified-since", ""), h.get("if-match", ""),
           h.get("if-none-match", ""), h.get("if-unmodified-since", ""),
           h.get("range", "")]
    ms = "".join(f"{k}:{h[k]}\n" for k in sorted(h) if
                 k.startswith("x-ms-"))
    res = f"/{account}{path}"
    res += "".join(f"\n{k}:{query[k]}" for k in sorted(query))
    sts = method + "\n" + "\n".join(std) + "\n" + ms + res
    mac = hmac.new(base64.b64decode(key_b64), sts.encode("utf-8"),
                   hashlib.sha256).digest()
    return base64.b64encode(mac).decode()


class AzureBlobClient:
    def __init__(self, account: str, key_b64: str, host: str,
                 port: int = 10000, secure: bool = False,
                 timeout: float = 30.0,
                 connect: Optional[Callable[[], object]] = None):
        self.account = account
        self.key_b64 = key_b64
        self.host, self.port, self.secure = host, port, secure
        self.timeout = timeout
        self._connect = connect or self._default_connect

    def _default_connect(self):
        cls = http.client.HTTPSConnection if self.secure \
            else http.client.HTTPConnection
        return cls(self.host, self.port, timeout=self.timeout)

    # -- plumbing ----------------------------------------------------------

    def _request(self, method: str, path: str,
                 query: Optional[dict[str, str]] = None,
                 headers: Optional[dict[str, str]] = None,
                 body: bytes = b"", want_stream: bool = False):
        query = dict(query or {})
        hdrs = {k.lower(): v for k, v in (headers or {}).items()}
        hdrs.setdefault("x-ms-date", _rfc1123_now())
        hdrs.setdefault("x-ms-version", API_VERSION)
        hdrs["content-length"] = str(len(body))
        hdrs["host"] = f"{self.host}:{self.port}"
        # Sign the percent-encoded path: Azure canonicalizes the escaped
        # URI path (the official SDKs sign EscapedPath), so the string
        # signed must be byte-identical to the one on the request line.
        enc_path = urllib.parse.quote(path)
        sig = shared_key_signature(self.account, self.key_b64, method,
                                   enc_path, query, hdrs)
        hdrs["authorization"] = f"SharedKey {self.account}:{sig}"
        qs = urllib.parse.urlencode(query)
        conn = self._connect()
        conn.request(method, enc_path + (f"?{qs}" if qs else ""),
                     body=body, headers=hdrs)
        resp = conn.getresponse()
        if resp.status >= 300:
            data = resp.read()
            conn.close()
            code = ""
            try:
                code = ET.fromstring(data).findtext("Code") or ""
            except ET.ParseError:
                pass
            raise AzureClientError(resp.status, code, data)
        if want_stream:
            return resp, conn
        data = resp.read()
        out = {k.lower(): v for k, v in resp.getheaders()}
        conn.close()
        return out, data

    # -- containers --------------------------------------------------------

    def create_container(self, name: str) -> None:
        self._request("PUT", f"/{name}", {"restype": "container"})

    def delete_container(self, name: str) -> None:
        self._request("DELETE", f"/{name}", {"restype": "container"})

    def container_exists(self, name: str) -> bool:
        try:
            self._request("HEAD", f"/{name}", {"restype": "container"})
            return True
        except AzureClientError as e:
            if e.status == 404:
                return False
            raise

    def list_containers(self) -> list[str]:
        _h, data = self._request("GET", "/", {"comp": "list"})
        root = ET.fromstring(data)
        return [el.findtext("Name") or ""
                for el in root.iter("Container")]

    # -- blobs -------------------------------------------------------------

    def put_blob(self, container: str, blob: str, body: bytes,
                 metadata: Optional[dict[str, str]] = None,
                 content_type: str = "") -> str:
        hdrs = {"x-ms-blob-type": "BlockBlob"}
        if content_type:
            hdrs["content-type"] = content_type
        for k, v in (metadata or {}).items():
            hdrs[f"x-ms-meta-{k}"] = v
        h, _ = self._request("PUT", f"/{container}/{blob}",
                             headers=hdrs, body=body)
        return h.get("etag", "").strip('"')

    def get_blob_props(self, container: str, blob: str) -> dict:
        h, _ = self._request("HEAD", f"/{container}/{blob}")
        return h

    def get_blob(self, container: str, blob: str, offset: int = 0,
                 length: int = -1) -> tuple[dict, Iterator[bytes]]:
        hdrs = {}
        if offset or length >= 0:
            end = f"{offset + length - 1}" if length >= 0 else ""
            hdrs["x-ms-range"] = f"bytes={offset}-{end}"
        resp, conn = self._request("GET", f"/{container}/{blob}",
                                   headers=hdrs, want_stream=True)
        out = {k.lower(): v for k, v in resp.getheaders()}

        def gen():
            try:
                while True:
                    chunk = resp.read(1 << 20)
                    if not chunk:
                        return
                    yield chunk
            finally:
                conn.close()

        return out, gen()

    def delete_blob(self, container: str, blob: str) -> None:
        self._request("DELETE", f"/{container}/{blob}")

    def list_blobs(self, container: str, prefix: str = "",
                   delimiter: str = "", marker: str = "",
                   max_results: int = 1000
                   ) -> tuple[list[dict], list[str], str]:
        """Returns (blobs, common_prefixes, next_marker)."""
        q = {"restype": "container", "comp": "list",
             "maxresults": str(max_results), "include": "metadata"}
        if prefix:
            q["prefix"] = prefix
        if delimiter:
            q["delimiter"] = delimiter
        if marker:
            q["marker"] = marker
        _h, data = self._request("GET", f"/{container}", q)
        root = ET.fromstring(data)
        blobs = []
        for el in root.iter("Blob"):
            props = el.find("Properties")
            meta_el = el.find("Metadata")
            blobs.append({
                "name": el.findtext("Name") or "",
                "size": int(props.findtext("Content-Length") or 0)
                if props is not None else 0,
                "etag": (props.findtext("Etag") or "").strip('"')
                if props is not None else "",
                "last_modified": props.findtext("Last-Modified") or ""
                if props is not None else "",
                "metadata": {m.tag: (m.text or "")
                             for m in meta_el} if meta_el is not None
                else {},
            })
        prefixes = [el.findtext("Name") or ""
                    for el in root.iter("BlobPrefix")]
        next_marker = root.findtext("NextMarker") or ""
        return blobs, prefixes, next_marker

    # -- block (multipart) API --------------------------------------------

    def put_block(self, container: str, blob: str, block_id: str,
                  body: bytes) -> None:
        """Stage one uncommitted block (the azure-native multipart
        part: cmd/gateway/azure PutObjectPart maps here)."""
        self._request("PUT", f"/{container}/{blob}",
                      {"comp": "block", "blockid": block_id},
                      body=body)

    def put_block_list(self, container: str, blob: str,
                       block_ids: list[str],
                       metadata: Optional[dict[str, str]] = None,
                       content_type: str = "") -> str:
        xml = "<?xml version=\"1.0\" encoding=\"utf-8\"?><BlockList>" \
            + "".join(f"<Uncommitted>{bid}</Uncommitted>"
                      for bid in block_ids) + "</BlockList>"
        hdrs: dict[str, str] = {}
        if content_type:
            hdrs["x-ms-blob-content-type"] = content_type
        for k, v in (metadata or {}).items():
            hdrs[f"x-ms-meta-{k}"] = v
        h, _ = self._request("PUT", f"/{container}/{blob}",
                             {"comp": "blocklist"}, headers=hdrs,
                             body=xml.encode())
        return h.get("etag", "").strip('"')
