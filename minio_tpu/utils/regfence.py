"""Lineage fencing for the epoch-versioned registries.

The topology/tier/replication registries all persist one JSON doc to
EVERY pool and load "highest epoch wins". Under a partition that rule
is a coin flip: two sides that each bump to epoch N commit different
documents claiming the same version, and whichever pool answers first
after heal silently wins — a split brain merged without anyone
noticing.

Fencing makes the commit history a hash chain instead of a bare
counter. Every epoch commit records:

  * ``writer``          — the committing node's id
  * ``parent_lineage``  — the lineage hash of the epoch it advanced
  * ``lineage``         — sha256(parent_lineage ":" epoch ":" writer)

Two documents claiming the same epoch with DIFFERENT lineage hashes
can only arise from divergent histories — a detected **fork**, never a
coin flip. Load picks the deterministic winner (highest
(epoch, writer, lineage) tuple) and fsck surfaces the fork as a
``registry_epoch_fork`` finding whose repair archives the loser
instead of deleting it.

Writes are quorum-gated: ``write_quorum(n_pools)`` reads
``MINIO_TPU_REGISTRY_WRITE_QUORUM`` (a count, or ``majority``); a save
that lands on fewer pools refuses — the epoch bump rolls back instead
of committing on a minority side. The default ("1") preserves the
legacy at-least-one behavior.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Tuple

from . import eventlog, knobs


def lineage(parent: str, epoch: int, writer: str) -> str:
    """Lineage hash of an epoch commit: chains the parent's lineage so
    equal epochs from divergent histories can never collide."""
    return hashlib.sha256(
        f"{parent}:{epoch}:{writer}".encode()).hexdigest()[:16]


def default_writer() -> str:
    """The committing node's identity: its cluster address when the
    process booted as a node, else a single-process placeholder."""
    # lazy import: utils must not pull the distributed plane in at
    # import time (layering), only when a registry actually commits
    from ..distributed import membership
    return membership.local_node() or "local"


def stamp(doc: dict, epoch: int, writer: str, parent: str) -> dict:
    """Attach the fencing fields to a registry doc in place."""
    doc["writer"] = writer
    doc["parent_lineage"] = parent
    doc["lineage"] = lineage(parent, epoch, writer)
    return doc


def _rank(doc: dict) -> Tuple[int, str, str]:
    return (int(doc.get("epoch", 0)), str(doc.get("writer", "")),
            str(doc.get("lineage", "")))


def pick_best(docs: List[dict]) -> Optional[dict]:
    """Deterministic winner across pool copies: highest
    (epoch, writer, lineage). Identical on every node, fork or not —
    the fork is REPORTED (see `find_forks` / fsck), never merged."""
    best = None
    for d in docs:
        if isinstance(d, dict) and (best is None
                                    or _rank(d) > _rank(best)):
            best = d
    return best


def find_forks(docs: List[dict]) -> List[Tuple[dict, dict]]:
    """Pairs of documents claiming the SAME epoch with DIFFERENT
    lineage — divergent histories. Docs predating the fencing fields
    (no lineage) cannot be distinguished and are not flagged."""
    out: List[Tuple[dict, dict]] = []
    by_epoch: dict = {}
    for d in docs:
        if not isinstance(d, dict) or not d.get("lineage"):
            continue
        e = int(d.get("epoch", 0))
        seen = by_epoch.setdefault(e, {})
        lin = str(d["lineage"])
        if lin in seen:
            continue
        for other in seen.values():
            out.append((other, d))
        seen[lin] = d
    if out:
        eventlog.emit("registry.fork",
                      epoch=int(out[0][0].get("epoch", 0)),
                      forks=len(out))
    return out


def write_quorum(n_pools: int) -> int:
    """Pools a registry write must land on before the epoch bump is
    acked. `MINIO_TPU_REGISTRY_WRITE_QUORUM`: a count (clamped to
    [1, n_pools]) or `majority` (n//2 + 1)."""
    raw = knobs.get_str("MINIO_TPU_REGISTRY_WRITE_QUORUM").strip()
    if raw.lower() == "majority":
        return n_pools // 2 + 1
    try:
        want = int(raw)
    except ValueError:
        want = 1
    return max(1, min(want, n_pools))


def check_write_quorum(landed: int, n_pools: int, what: str) -> None:
    """Refuse a minority-side registry commit: raises ValueError when
    fewer than the configured quorum of pools took the write. Callers
    roll the in-memory epoch bump back on the way out."""
    need = write_quorum(n_pools)
    if landed < need:
        raise ValueError(
            f"{what}: write quorum not met — doc landed on {landed} of "
            f"{n_pools} pool(s), need {need}; refusing a minority-side "
            "epoch bump (MINIO_TPU_REGISTRY_WRITE_QUORUM)")
