"""Runtime lock-order watchdog: named locks + an acquisition-order graph.

The static side of the correctness plane (``tools/check``) bans blocking
work *inside* a lock body; this module covers what static analysis
cannot see — the ORDER in which threads nest locks across call chains.
The PR 6 mesh-dispatch incident is the motivating shape: two subsystems
each correct in isolation, deadlocking only when their critical
sections nest in opposite orders under concurrency. A cycle in the
lock-order graph is exactly that hazard, and it is detectable the first
time both orders are *recorded* — no unlucky interleaving required.

How it works (the lockdep idea, sized for this codebase):

  * hot modules create their locks through :func:`mutex` /
    :func:`rlock` / :func:`condition`, passing a stable ROLE name
    ("sched.buckets", "mesh.dispatch", "metacache.cond"). Graph nodes
    are names, not instances — like lockdep's lock classes, so an ABBA
    between two *schedulers* still flags even though the instances
    differ (consistent order by role is the discipline being checked);
  * each acquire records edges ``held → acquiring`` for every lock the
    thread already holds, then checks whether the new edge closes a
    cycle. Cycles are recorded as violations and (by default) raised as
    :class:`LockOrderError` at the offending acquire;
  * an acquire that blocks longer than ``MINIO_TPU_LOCKCHECK_BLOCK_MS``
    while the thread holds another lock is flagged *held-while-blocking*
    (the convoy precursor); holds longer than
    ``MINIO_TPU_LOCKCHECK_HELD_MS`` are flagged *long-hold*.

Always-installed, env-gated: the factories return the checked wrapper
unconditionally, but every acquire first consults a cached enabled
flag, so the disabled cost is one attribute test. Tests flip
``MINIO_TPU_LOCKCHECK`` and call :func:`refresh`; the chaos and
concurrency suites run with the watchdog default-on (tests/conftest.py)
so a future lock-order change fails loudly in tier-1.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set

from . import knobs

__all__ = [
    "LockOrderError", "Violation", "mutex", "rlock", "condition",
    "enabled", "refresh", "reset", "violations", "check", "graph",
]


class LockOrderError(RuntimeError):
    """An acquire would close a cycle in the lock-order graph."""


class Violation:
    __slots__ = ("kind", "lock", "held", "path", "thread", "detail",
                 "when")

    def __init__(self, kind: str, lock: str, held: List[str],
                 path: List[str], thread: str, detail: str):
        self.kind = kind          # "cycle" | "held-while-blocking" | "long-hold"
        self.lock = lock
        self.held = held
        self.path = path          # the cycle, for kind == "cycle"
        self.thread = thread
        self.detail = detail
        self.when = time.time()

    def __repr__(self) -> str:
        return (f"<lockcheck {self.kind} lock={self.lock!r} "
                f"held={self.held} {self.detail} [{self.thread}]>")


# -- global state ------------------------------------------------------------

# a REAL lock (never a checked one) guarding the graph + violation list
_mu = threading.Lock()
_edges: Dict[str, Set[str]] = {}          # held-name -> {acquired-names}
_edge_threads: Dict[tuple, str] = {}      # edge -> first thread that made it
_violations: List[Violation] = []
_local = threading.local()

_enabled = False
_raise_on_cycle = True
_block_s = 0.2
_held_s = 1.0


def refresh() -> None:
    """Re-read the MINIO_TPU_LOCKCHECK_* knobs (tests flip them at
    runtime; per-acquire reads would put an environ lookup on every
    hot-path lock)."""
    global _enabled, _raise_on_cycle, _block_s, _held_s
    _enabled = knobs.get_bool("MINIO_TPU_LOCKCHECK")
    _raise_on_cycle = knobs.get_bool("MINIO_TPU_LOCKCHECK_RAISE")
    _block_s = knobs.get_float("MINIO_TPU_LOCKCHECK_BLOCK_MS") / 1e3
    _held_s = knobs.get_float("MINIO_TPU_LOCKCHECK_HELD_MS") / 1e3


refresh()


def enabled() -> bool:
    return _enabled


def reset() -> None:
    """Drop the recorded graph and violations (test isolation)."""
    with _mu:
        _edges.clear()
        _edge_threads.clear()
        _violations.clear()


def violations(kind: Optional[str] = None) -> List[Violation]:
    with _mu:
        vs = list(_violations)
    return [v for v in vs if kind is None or v.kind == kind]


def graph() -> Dict[str, Set[str]]:
    with _mu:
        return {k: set(v) for k, v in _edges.items()}


def check() -> None:
    """Raise on any recorded cycle (suites call this at teardown so
    cycles detected on daemon threads — where a raise is swallowed —
    still fail the test)."""
    cycles = violations("cycle")
    if cycles:
        raise LockOrderError("; ".join(v.detail for v in cycles))


def _held_stack() -> List[str]:
    st = getattr(_local, "held", None)
    if st is None:
        st = _local.held = []
    return st


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """Existing edge path src -> ... -> dst (DFS under _mu)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in _edges.get(node, ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _record_acquire(name: str, wait_s: float) -> None:
    held = _held_stack()
    if not held:
        return
    tname = threading.current_thread().name
    cycle_detail = None
    with _mu:
        for h in held:
            if h == name:
                continue                  # reentrant by role: no edge
            # adding h -> name: a cycle exists iff name already
            # reaches h through recorded orders
            back = _find_path(name, h)
            if back is not None and (h, name) not in _edge_threads:
                path = [h] + back
                other = _edge_threads.get((back[0], back[1]),
                                          "?") if len(back) > 1 else "?"
                cycle_detail = (
                    f"lock-order cycle {' -> '.join(path)}: this "
                    f"thread ({tname}) holds {h!r} while acquiring "
                    f"{name!r}, but the opposite order was recorded "
                    f"(first by thread {other})")
                _violations.append(Violation(
                    "cycle", name, list(held), path, tname,
                    cycle_detail))
            _edges.setdefault(h, set()).add(name)
            _edge_threads.setdefault((h, name), tname)
        if wait_s > _block_s:
            _violations.append(Violation(
                "held-while-blocking", name, list(held), [], tname,
                f"blocked {wait_s * 1e3:.0f}ms acquiring {name!r} "
                f"while holding {held}"))
    if cycle_detail is not None and _raise_on_cycle:
        raise LockOrderError(cycle_detail)


def _record_release(name: str, held_for_s: float) -> None:
    if held_for_s > _held_s:
        tname = threading.current_thread().name
        with _mu:
            _violations.append(Violation(
                "long-hold", name, [], [], tname,
                f"held {name!r} for {held_for_s * 1e3:.0f}ms"))


class _CheckedLock:
    """threading.Lock/RLock wrapper carrying a role name. Compatible
    with threading.Condition (acquire/release/locked surface)."""

    __slots__ = ("_inner", "name", "_t_acquired", "_depth",
                 "_reentrant", "_owner")

    def __init__(self, inner, name: str, reentrant: bool = False):
        self._inner = inner
        self.name = name
        self._t_acquired = 0.0
        self._depth = 0
        self._reentrant = reentrant
        self._owner = None        # ident of the holding thread (mutex only)

    # -- lock protocol -----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _enabled:
            return self._inner.acquire(blocking, timeout)
        if not self._reentrant and blocking and \
                self._owner == threading.get_ident():
            # the simplest deadlock: this thread re-acquiring a mutex
            # it already holds. The inner acquire would block FOREVER
            # before any recording could happen — flag it instead.
            # (Only the owner ever writes _owner, so owner == me can
            # never be a stale read.)
            tname = threading.current_thread().name
            detail = (f"self-deadlock: thread {tname} re-acquired "
                      f"non-reentrant mutex {self.name!r} it already "
                      "holds")
            with _mu:
                _violations.append(Violation(
                    "cycle", self.name, [self.name], [self.name],
                    tname, detail))
            raise LockOrderError(detail)
        t0 = time.perf_counter()
        got = self._inner.acquire(blocking, timeout)
        if not got:
            return False
        wait = time.perf_counter() - t0
        held = _held_stack()
        reentrant = self.name in held
        held.append(self.name)
        self._depth += 1
        if self._depth == 1:
            self._t_acquired = time.perf_counter()
            self._owner = threading.get_ident()
        if not reentrant:
            try:
                self._record_acquire_safe(wait)
            except LockOrderError:
                # the caller never got the lock as far as it knows —
                # unwind EVERY piece of state this acquire installed
                # (a stale _owner would make the thread's next
                # legitimate acquire a false self-deadlock)
                held.pop()
                self._depth -= 1
                if self._depth == 0:
                    self._owner = None
                self._inner.release()
                raise
        return True

    def _record_acquire_safe(self, wait: float) -> None:
        # the held stack already includes self.name — record against
        # the OUTER holds only
        held = _held_stack()
        saved = held.pop()
        try:
            _record_acquire(self.name, wait)
        finally:
            held.append(saved)

    def release(self) -> None:
        # bookkeeping is unconditional: a lock ACQUIRED while the
        # watchdog was on must unwind its held-stack entry even if the
        # watchdog was flipped off mid-hold (tests refresh() at
        # teardown; a daemon mid-critical-section would otherwise
        # "hold" its role name forever and poison later enabled runs).
        # Threads that never ran enabled have no stack — one getattr.
        held = getattr(_local, "held", None)
        popped = False
        if held:
            # remove the innermost occurrence (LIFO discipline is the
            # common case; out-of-order release still unwinds correctly)
            for i in range(len(held) - 1, -1, -1):
                if held[i] == self.name:
                    del held[i]
                    popped = True
                    break
        if popped:
            # unbalanced pops only happen when the watchdog was flipped
            # on mid-hold — never decrement past the acquires we saw
            self._depth -= 1
            if self._depth == 0:
                self._owner = None
                if _enabled:
                    _record_release(
                        self.name,
                        time.perf_counter() - self._t_acquired)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "_CheckedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition support: wait() must drop EVERY recursion level of an
    # RLock-backed condition (threading's own _release_save contract)
    # and restore them on wake, with the watchdog bookkeeping unwound
    # and rebuilt so the wait never reads as a hold.
    def _release_save(self):
        depth = 0
        if _enabled:
            depth = self._depth
            held = getattr(_local, "held", None)
            for _ in range(depth):
                if held:
                    for i in range(len(held) - 1, -1, -1):
                        if held[i] == self.name:
                            del held[i]
                            break
            if depth:
                self._depth = 0
                self._owner = None
                _record_release(
                    self.name, time.perf_counter() - self._t_acquired)
        inner_rs = getattr(self._inner, "_release_save", None)
        if inner_rs is not None:
            inner_state = inner_rs()
        else:
            self._inner.release()
            inner_state = None
        return (depth, inner_state)

    def _acquire_restore(self, saved) -> None:
        depth, inner_state = saved
        t0 = time.perf_counter()
        inner_ar = getattr(self._inner, "_acquire_restore", None)
        if inner_ar is not None:
            inner_ar(inner_state)
        else:
            self._inner.acquire()
        if _enabled:
            wait = time.perf_counter() - t0
            held = _held_stack()
            reentrant = self.name in held
            for _ in range(max(depth, 1)):
                held.append(self.name)
            self._depth = max(depth, 1)
            self._t_acquired = time.perf_counter()
            self._owner = threading.get_ident()
            if not reentrant:
                self._record_acquire_safe(wait)

    def _is_owned(self) -> bool:
        inner_owned = getattr(self._inner, "_is_owned", None)
        if inner_owned is not None:       # RLock: exact owner answer
            return inner_owned()
        if self.acquire(False):
            self.release()
            return False
        return True


def mutex(name: str) -> _CheckedLock:
    """A named non-reentrant lock (threading.Lock under the hood).
    Re-acquiring it on the holding thread raises LockOrderError when
    the watchdog is on (it would block forever before any recording)."""
    return _CheckedLock(threading.Lock(), name)


def rlock(name: str) -> _CheckedLock:
    """A named reentrant lock."""
    return _CheckedLock(threading.RLock(), name, reentrant=True)


def condition(name: str) -> threading.Condition:
    """A Condition whose underlying lock is watchdog-instrumented.
    RLock-backed, matching ``threading.Condition()``'s default, so
    swapping a plain Condition for a named one never changes reentrancy
    semantics. ``wait()`` rides the checked release/re-acquire
    protocol, so a cond.wait never shows as a long hold."""
    return threading.Condition(rlock(name))
