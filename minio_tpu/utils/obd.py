"""OBD ("on-board diagnostics") bundle: per-node hardware/health facts
(reference cmd/obdinfo.go getLocalDrivesOBD + peer OBD verbs,
cmd/peer-rest-common.go:29-37): CPU and memory facts from /proc, plus a
real latency probe per local drive (timed write+fsync+read of a small
file) — the numbers an operator reads first when a cluster feels slow.
"""

from __future__ import annotations

import os
import shutil
import socket
import time


def _meminfo() -> dict:
    out: dict[str, int] = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                parts = line.split()
                if parts[0] in ("MemTotal:", "MemAvailable:"):
                    out[parts[0][:-1]] = int(parts[1]) * 1024
    except OSError:
        pass
    return {"total": out.get("MemTotal", 0),
            "available": out.get("MemAvailable", 0)}


def probe_drive(path: str, size: int = 64 << 10) -> dict:
    """Timed write+fsync then read of `size` bytes under `path`
    (reference getLocalDrivesOBD performance probe)."""
    info: dict = {"path": path}
    try:
        usage = shutil.disk_usage(path)
        info["total_bytes"] = usage.total
        info["free_bytes"] = usage.free
        probe = os.path.join(path, ".minio.sys", "tmp",
                             f".obd-probe-{os.getpid()}")
        os.makedirs(os.path.dirname(probe), exist_ok=True)
        payload = os.urandom(size)
        t0 = time.perf_counter()
        with open(probe, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        info["write_latency_us"] = round(
            (time.perf_counter() - t0) * 1e6)
        t0 = time.perf_counter()
        with open(probe, "rb") as f:
            got = f.read()
        info["read_latency_us"] = round(
            (time.perf_counter() - t0) * 1e6)
        info["ok"] = got == payload
        os.remove(probe)
    except OSError as e:
        info["error"] = str(e)
        info["ok"] = False
    return info


def drive_fault_counters(disks) -> list[dict]:
    """Per-drive fault counters from the live StorageAPI objects (ROADMAP
    follow-up: surface NaughtyDisk/transport faults in OBD): unwrap each
    drive's wrapper chain (DiskIDCheck → NaughtyDisk → XLStorage /
    RemoteStorage) and collect whatever counters it carries —

      * NaughtyDisk fault-injection stats (errors, latency, bitrot,
        truncated, offline_hits) — what chaos actually injected;
      * RemoteStorage transport counters (calls, net_errors, retries,
        offline_trips) — what the internode plane actually suffered.

    Drives with neither report only their identity; a None slot reports
    offline. Duck-typed so gateways/FS layers return [].

    Each entry also carries the gray-failure plane's view: the tracked
    per-verb latency summary and the quarantine health state, next to
    the fault counters — the "is it slow" answer beside "is it
    failing"."""
    from . import healthtrack
    tracked = {e["key"]: e for e in healthtrack.TRACKER.snapshot("drive")}
    out: list[dict] = []
    for i, d in enumerate(disks):
        entry: dict = {"index": i,
                       "drive": str(d) if d is not None else None,
                       "online": d is not None}
        if d is not None:
            h = tracked.get(healthtrack.disk_key(d))
            if h is not None:
                entry["health"] = {"state": h["state"],
                                   "state_age_s": h["state_age_s"],
                                   "latency": h["verbs"]}
        cur, hops = d, 0
        while cur is not None and hops < 8:
            hops += 1
            stats = getattr(cur, "stats", None)
            if stats is not None and hasattr(stats, "offline_hits"):
                entry["faults"] = {
                    "errors": stats.errors, "latency": stats.latency,
                    "bitrot": stats.bitrot,
                    "truncated": stats.truncated,
                    "offline_hits": stats.offline_hits,
                    "total_ops": getattr(cur, "total_ops", 0),
                }
            rc = getattr(cur, "rc", None)
            if rc is not None and hasattr(rc, "net_counters"):
                entry["transport"] = rc.net_counters()
            cur = getattr(cur, "inner", None)
        out.append(entry)
    return out


def _process_info() -> dict:
    """This server process's own footprint (reference OBD bundles
    process detail alongside host cpu/mem)."""
    out: dict = {"pid": os.getpid()}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    out["rss_bytes"] = int(line.split()[1]) * 1024
                elif line.startswith("Threads:"):
                    out["threads"] = int(line.split()[1])
    except (OSError, ValueError, IndexError):
        pass
    try:
        out["open_fds"] = len(os.listdir("/proc/self/fd"))
    except OSError:
        pass
    return out


def local_obd(drive_paths: list[str] | None = None,
              storage_drives=None) -> dict:
    """This node's OBD facts; the peer plane fans this out cluster-wide.
    `storage_drives` (live StorageAPI objects, any wrapper depth) adds
    per-drive fault counters alongside the latency probes."""
    try:
        load1, load5, load15 = os.getloadavg()
    except OSError:
        load1 = load5 = load15 = 0.0
    out = {
        "hostname": socket.gethostname(),
        "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "cpu": {"count": os.cpu_count() or 0,
                "load1": round(load1, 3), "load5": round(load5, 3),
                "load15": round(load15, 3)},
        "mem": _meminfo(),
        "process": _process_info(),
        "drives": [probe_drive(p) for p in (drive_paths or [])],
    }
    if storage_drives is not None:
        out["drive_faults"] = drive_fault_counters(storage_drives)
    # the gray-failure snapshot: per-peer latency summaries (the
    # per-drive ones ride each drive_faults entry above)
    from . import healthtrack
    out["peer_health"] = healthtrack.TRACKER.snapshot("peer")
    return out
