"""Remote-site target registry: where active-active replication goes.

The reference keeps per-bucket remote targets in bucket metadata
(cmd/bucket-targets.go); this registry promotes them to a first-class
persisted document — ``.minio.sys/replicate/targets.json`` written to
EVERY pool and recovered highest-epoch-wins, exactly the durability
rule the topology and tier planes use: any surviving subset of pools
recovers the newest registry, so replication targets keep working
through decommission and pool expansion.

The document also carries this cluster's own ``site_id`` — the
identity stamped (as the replica-origin metadata key) onto every
version this site pushes, which is what makes loop suppression and
replica pruning possible without any per-version status writes.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import uuid as _uuid
from typing import Optional

from ..object import api_errors
from ..utils import atomicfile, crashpoint, regfence
from ..storage.xl_storage import MINIO_META_BUCKET

REPL_PREFIX = "replicate/"
TARGETS_OBJECT = REPL_PREFIX + "targets.json"

# version metadata key: the site id of the cluster where this version
# was ORIGINALLY written. Absent = a native write of the local site.
# The X-Minio-Internal- prefix rides xl.meta and never leaks to clients.
REPL_ORIGIN_KEY = "X-Minio-Internal-replication-origin"

_SECRET_PARAMS = ("secret_key",)


def origin_of(metadata: Optional[dict], self_site: str) -> str:
    """The site a version originated at (the local site when the
    version carries no replica marker)."""
    return (metadata or {}).get(REPL_ORIGIN_KEY, "") or self_site


def is_replica(metadata: Optional[dict]) -> bool:
    return bool((metadata or {}).get(REPL_ORIGIN_KEY, ""))


class ReplTargetError(api_errors.ObjectApiError):
    """Invalid replication-target operation (duplicate ARN, unknown
    ARN, bad spec)."""


@dataclasses.dataclass
class SiteTarget:
    """One replication destination for one source bucket."""
    arn: str
    bucket: str                    # source bucket on THIS site
    dest_bucket: str               # bucket at the remote site
    site: str = ""                 # remote site id (loop suppression)
    type: str = "s3"               # "s3" (wire) | "layer" (in-process)
    prefix: str = ""               # only keys under this replicate
    bw_bps: int = 0                # per-target budget; 0 = knob default
    params: dict = dataclasses.field(default_factory=dict)

    def matches(self, key: str) -> bool:
        return key.startswith(self.prefix) if self.prefix else True

    def to_dict(self, redact: bool = False) -> dict:
        params = dict(self.params)
        if redact:
            for k in _SECRET_PARAMS:
                if params.get(k):
                    params[k] = "REDACTED"
        return {"arn": self.arn, "bucket": self.bucket,
                "dest_bucket": self.dest_bucket, "site": self.site,
                "type": self.type, "prefix": self.prefix,
                "bw_bps": self.bw_bps, "params": params}

    @classmethod
    def from_dict(cls, d: dict) -> "SiteTarget":
        arn = str(d.get("arn", "")).strip()
        bucket = str(d.get("bucket", "")).strip()
        if not arn or not bucket:
            raise ReplTargetError("target needs an arn and a bucket")
        return cls(arn=arn, bucket=bucket,
                   dest_bucket=str(d.get("dest_bucket") or bucket),
                   site=str(d.get("site", "")),
                   type=str(d.get("type", "s3")),
                   prefix=str(d.get("prefix", "")),
                   bw_bps=int(d.get("bw_bps", 0) or 0),
                   params=dict(d.get("params") or {}))


def new_arn(dest_bucket: str) -> str:
    return f"arn:minio:replication::{_uuid.uuid4().hex[:12]}:{dest_bucket}"


class TargetRegistry:
    """The live target map + client cache. Every mutation bumps
    ``epoch`` and persists BEFORE it takes effect (the TierManager
    discipline: a crash mid-add replays, never forgets a target a
    resync already references)."""

    def __init__(self, object_layer=None, site_id: str = ""):
        self.obj = object_layer
        self._mu = threading.Lock()
        self.epoch = 0
        self.updated = time.time()
        self.site_id = site_id or _uuid.uuid4().hex[:12]
        self.targets: dict[str, SiteTarget] = {}
        self._clients: dict[str, object] = {}
        # lineage fencing: every epoch commit chains a hash of
        # (parent lineage, epoch, writer) — see utils/regfence.py
        self.writer = ""
        self.parent_lineage = ""
        self.lineage = ""

    def _advance_lineage(self) -> None:
        """Chain the fencing hash for the epoch just committed (caller
        holds ``_mu``)."""
        self.parent_lineage = self.lineage
        self.writer = regfence.default_writer()
        self.lineage = regfence.lineage(self.parent_lineage,
                                        self.epoch, self.writer)

    # ------------------------------------------------------------------
    # CRUD
    # ------------------------------------------------------------------

    def add(self, target: SiteTarget, client=None,
            update: bool = False) -> int:
        """Register (or with `update` replace) a target. A wire ("s3")
        target verifies its client constructs before the registry
        mutates; in-process ("layer") targets must inject `client`.
        Returns the new epoch."""
        if client is None:
            if target.type == "layer":
                raise ReplTargetError(
                    "'layer' targets need an injected client")
            from .client import new_repl_client
            try:
                client = new_repl_client(target)
            except (KeyError, ValueError) as e:
                raise ReplTargetError(f"bad target spec: {e}") from None
        with self._mu:
            if not update and target.arn in self.targets:
                raise ReplTargetError(
                    f"target {target.arn!r} already exists")
            prev = self.targets.get(target.arn)
            self.targets[target.arn] = target
            self.epoch += 1
            self.updated = time.time()
            self._advance_lineage()
            epoch = self.epoch
        try:
            self.save()
        except Exception:
            with self._mu:              # roll back the in-memory map
                if prev is None:
                    self.targets.pop(target.arn, None)
                else:
                    self.targets[target.arn] = prev
            raise
        with self._mu:
            self._clients[target.arn] = client
        return epoch

    def remove(self, arn: str) -> int:
        with self._mu:
            if arn not in self.targets:
                raise ReplTargetError(f"unknown target {arn!r}")
            prev = self.targets.pop(arn)
            self._clients.pop(arn, None)
            self.epoch += 1
            self.updated = time.time()
            self._advance_lineage()
            epoch = self.epoch
        try:
            self.save()
        except Exception:
            with self._mu:
                self.targets[arn] = prev
            raise
        return epoch

    def list(self, redact: bool = True) -> list[dict]:
        with self._mu:
            return [t.to_dict(redact=redact)
                    for t in sorted(self.targets.values(),
                                    key=lambda t: t.arn)]

    def get(self, arn: str) -> SiteTarget:
        with self._mu:
            t = self.targets.get(arn)
        if t is None:
            raise ReplTargetError(f"unknown target {arn!r}")
        return t

    def for_bucket(self, bucket: str) -> list[SiteTarget]:
        with self._mu:
            return [t for t in self.targets.values() if t.bucket == bucket]

    def buckets(self) -> set[str]:
        with self._mu:
            return {t.bucket for t in self.targets.values()}

    def client(self, arn: str):
        with self._mu:
            c = self._clients.get(arn)
            t = self.targets.get(arn)
        if c is not None:
            return c
        if t is None:
            raise ReplTargetError(f"unknown target {arn!r}")
        if t.type == "layer":
            raise ReplTargetError(
                f"target {arn!r} has no live client (re-inject with "
                "set_client after a restart)")
        from .client import new_repl_client
        c = new_repl_client(t)
        with self._mu:
            self._clients.setdefault(arn, c)
        return c

    def set_client(self, arn: str, client) -> None:
        """Swap the live client of a registered target (chaos tests
        wrap the real client in a NaughtyReplClient; in-process layer
        targets re-inject after a registry reload)."""
        self.get(arn)
        with self._mu:
            self._clients[arn] = client

    def mount_target_entry(self, entry: dict) -> str:
        """Back-compat: register a bucket-metadata remote-target dict
        (the legacy admin set-remote-target on-disk shape). Mounted as
        a one-way "push" target — the legacy entries point at GENERIC
        S3 endpoints with no peer wire surface; pairing two minio_tpu
        sites uses the replicate/target admin verb (type "s3") instead.
        Returns the ARN. Already-known ARNs refresh in place."""
        target = SiteTarget(
            arn=entry.get("arn") or new_arn(entry.get("bucket", "")),
            bucket=entry.get("source_bucket") or entry.get("bucket", ""),
            dest_bucket=entry.get("bucket", ""),
            site=entry.get("site", ""),
            type="push",
            params={"host": entry.get("host", ""),
                    "port": int(entry.get("port", 9000)),
                    "access_key": entry.get("access_key", ""),
                    "secret_key": entry.get("secret_key", ""),
                    "region": entry.get("region", "us-east-1"),
                    "secure": bool(entry.get("secure", False))})
        self.add(target, update=True)
        return target.arn

    # ------------------------------------------------------------------
    # persistence (every pool, highest epoch wins)
    # ------------------------------------------------------------------

    def to_dict(self) -> dict:
        with self._mu:
            return {"epoch": self.epoch, "updated": self.updated,
                    "site_id": self.site_id,
                    "targets": [t.to_dict()
                                for t in self.targets.values()],
                    "writer": self.writer,
                    "parent_lineage": self.parent_lineage,
                    "lineage": self.lineage}

    def _pools(self):
        if self.obj is None:
            return []
        return getattr(self.obj, "server_sets", None) or [self.obj]

    def save(self) -> int:
        """Write the registry to every pool; at least one copy must
        land or the mutation is rejected (caller rolls back)."""
        pools = self._pools()
        if not pools:
            return 0
        payload = json.dumps(self.to_dict()).encode()
        landed = 0
        last: Optional[Exception] = None
        for z in pools:
            try:
                # one hit per pool (arm :<nth>)
                crashpoint.hit("replicate.registry.save.pool")
                z.put_object(MINIO_META_BUCKET, TARGETS_OBJECT, payload)
                landed += 1
            except Exception as e:  # noqa: BLE001 — per-pool durability
                last = e
        need = regfence.write_quorum(len(pools))
        if landed < need:
            # refusing a minority-side epoch bump (caller rolls back)
            raise ReplTargetError(
                f"replication targets epoch {self.epoch} persisted to "
                f"{landed} of {len(pools)} pool(s), need {need}: "
                f"{last!r}")
        return landed

    def load(self) -> bool:
        """Recover the newest persisted registry (highest epoch across
        pools); returns True when a doc was found. Live clients reset —
        wire targets reconstruct lazily, layer targets need
        set_client."""
        docs: list[dict] = []
        for z in self._pools():
            try:
                _, stream = z.get_object(MINIO_META_BUCKET, TARGETS_OBJECT)
                doc = atomicfile.load_json_doc(b"".join(stream))
            except api_errors.ObjectApiError:
                continue
            if doc is None:     # torn/truncated copy: other pools win
                continue
            docs.append(doc)
        # deterministic winner; same-epoch/different-lineage copies are
        # a fork fsck surfaces — load never coin-flips between them
        best = regfence.pick_best(docs)
        if best is None:
            return False
        targets = {}
        for d in best.get("targets", []):
            try:
                t = SiteTarget.from_dict(d)
            except ReplTargetError:
                continue
            targets[t.arn] = t
        with self._mu:
            self.epoch = int(best.get("epoch", 0))
            self.updated = float(best.get("updated", time.time()))
            self.site_id = str(best.get("site_id", "")) or self.site_id
            self.targets = targets
            self.writer = str(best.get("writer", ""))
            self.parent_lineage = str(best.get("parent_lineage", ""))
            self.lineage = str(best.get("lineage", ""))
            self._clients.clear()
        return True
