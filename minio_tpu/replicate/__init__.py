"""Active-active multi-site replication plane.

Promotes the one-way async copier of ``features/replication.py`` into
a real subsystem: an epoch-versioned persisted target registry
(``targets.py``), transport-agnostic target clients with a
deterministic fault wrapper (``client.py``), the bidirectional sync
plane with loop suppression, conflict resolution, pruning, MRF-style
retry and bandwidth budgets (``plane.py``), and the checkpointed
resync walker that seeds a new site (``resync.py``).
"""

from .client import (HTTPReplClient, LayerReplClient,  # noqa: F401
                     NaughtyReplClient, ReplClientError,
                     ReplTargetClient, ReplTargetOffline,
                     replica_writes_counter)
from .plane import ReplicationPlane  # noqa: F401
from .resync import Resyncer  # noqa: F401
from .targets import (REPL_ORIGIN_KEY, SiteTarget,  # noqa: F401
                      TargetRegistry, is_replica, new_arn, origin_of)
