"""Resync: seed (or re-seed) a replication target from scratch.

The rebalance walker's shape applied to a remote site (reference
``mc admin replicate resync``): walk every bucket the target covers —
names from the metacache namespace feed when attached (the one
amortized walk), marker-paged version listings otherwise — and push
every version the target lacks, oldest first, with full fidelity
(multipart boundaries, markers, stubs as metadata). Unlike the
steady-state sync, a resync pushes EVERY missing version regardless of
origin (a disaster-recovery seed must restore the target's own lost
writes too) and never prunes.

Progress checkpoints (bucket + key marker + counters) persist under
``.minio.sys/replicate/resync-<arn>.json`` on every pool after every
``MINIO_TPU_REPL_RESYNC_CHECKPOINT_EVERY`` keys — a kill mid-resync
resumes from the marker instead of re-listing the site, and the
re-pass is idempotent (the target-lacks check skips what already
landed). Failed keys feed the plane's MRF retry queue.
"""

from __future__ import annotations

import json
import threading
import time
from typing import TYPE_CHECKING, Optional

from ..object import api_errors
from ..utils import atomicfile, crashpoint, eventlog
from ..storage.xl_storage import MINIO_META_BUCKET
from ..utils import knobs, telemetry
from .targets import REPL_PREFIX, TargetRegistry

if TYPE_CHECKING:  # pragma: no cover — typing only
    from .plane import ReplicationPlane

CHECKPOINT_EVERY = knobs.get_int("MINIO_TPU_REPL_RESYNC_CHECKPOINT_EVERY")
PAGE = knobs.get_int("MINIO_TPU_REPL_RESYNC_PAGE")


def _checkpoint_object(arn: str) -> str:
    # ARNs contain ':' — keep the object key filesystem-tame
    return f"{REPL_PREFIX}resync-{arn.replace(':', '_').replace('/', '_')}.json"


class Resyncer:
    """One target seed: a daemon thread walking the local namespace and
    pushing every missing version to the target."""

    def __init__(self, object_layer, registry: TargetRegistry, arn: str,
                 plane: Optional["ReplicationPlane"] = None,
                 resume: bool = True,
                 checkpoint_every: Optional[int] = None,
                 page: Optional[int] = None):
        self.obj = object_layer
        self.registry = registry
        self.arn = arn
        self.plane = plane
        self.checkpoint_every = checkpoint_every or CHECKPOINT_EVERY
        self.page = page or PAGE
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()
        self.state = {
            "arn": arn, "status": "pending",
            "bucket": "", "marker": "",
            "keys_scanned": 0, "versions_pushed": 0, "keys_failed": 0,
            "started": time.time(), "updated": time.time(),
        }
        if resume:
            doc = self.load_checkpoint(object_layer, arn)
            if doc is not None and doc.get("status") != "complete":
                for k in ("bucket", "marker", "keys_scanned",
                          "versions_pushed", "keys_failed"):
                    if k in doc:
                        self.state[k] = doc[k]
                self.state["resumed"] = True

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "Resyncer":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="repl-resync")
        self._thread.start()
        return self

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def stop(self, timeout: float = 10.0) -> bool:
        self._stop.set()
        if self._thread is not None and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout)
        return not self.running()

    def status(self) -> dict:
        with self._mu:
            out = dict(self.state)
        out["running"] = self.running()
        return out

    # -- the walk -------------------------------------------------------

    def _run(self) -> None:
        self._set(status="seeding")
        try:
            self.run_pass()
            if self._stop.is_set():
                self._set(status="stopped")
            else:
                self._set(status="complete", bucket="", marker="")
            self._save_checkpoint()
        except Exception as e:  # noqa: BLE001 — surfaced via status
            self._set(status="failed", error=repr(e))
            self._save_checkpoint()

    def run_pass(self) -> tuple[int, int]:
        """One sweep from the current checkpoint. Returns
        (keys pushed-through, keys failed)."""
        target = self.registry.get(self.arn)
        client = self.registry.client(self.arn)
        client.ensure_bucket()
        done = failed = since_ckpt = 0
        buckets = sorted(v.name for v in self.obj.list_buckets()
                         if v.name == target.bucket or not target.bucket)
        start_bucket = self.state["bucket"]
        for bucket in buckets:
            if self._stop.is_set():
                break
            if start_bucket and bucket < start_bucket:
                continue
            marker = self.state["marker"] \
                if bucket == start_bucket else ""
            for name in self._bucket_names(bucket, marker):
                if self._stop.is_set():
                    break
                if not target.matches(name):
                    continue
                with telemetry.trace("replicate.resync", bucket=bucket,
                                     object=name, target=self.arn):
                    try:
                        pushed = self.plane.sync_key(bucket, name, target,
                                                     resync=True) \
                            if self.plane is not None else 0
                    except Exception:  # noqa: BLE001 — per-key isolation
                        failed += 1
                        with self._mu:
                            self.state["keys_failed"] += 1
                        if self.plane is not None:
                            self.plane.mrf.enqueue(bucket, name, self.arn)
                    else:
                        done += 1
                        with self._mu:
                            self.state["keys_scanned"] += 1
                            self.state["versions_pushed"] += pushed
                self._set(bucket=bucket, marker=name)
                since_ckpt += 1
                if since_ckpt >= self.checkpoint_every:
                    self._save_checkpoint()
                    since_ckpt = 0
        if since_ckpt:
            self._save_checkpoint()
        return done, failed

    def _bucket_names(self, bucket: str, marker: str):
        """Sorted key names after `marker`: the metacache namespace
        feed when attached (versions=True so marker-latest keys are
        covered), else marker-paged version listings."""
        mc = getattr(self.obj, "metacache", None)
        feed = mc.namespace_feed(bucket, versions=True,
                                 consumer="resync") \
            if mc is not None else None
        if feed is not None:
            for name, _vers in feed:
                if marker and name <= marker:
                    continue
                yield name
            return
        from ..object.metacache import walks_counter
        walks_counter().inc(consumer="resync", source="merge")
        vid_marker = ""
        last = None
        while not self._stop.is_set():
            try:
                page, _pfx, nkm, nvm, trunc = \
                    self.obj.list_object_versions(bucket, "", marker,
                                                  self.page, vid_marker)
            except api_errors.ObjectApiError:
                return
            for oi in page:
                if oi.name != last:
                    last = oi.name
                    yield oi.name
            if not trunc:
                return
            marker, vid_marker = nkm, nvm

    # -- checkpoint persistence -----------------------------------------

    def _set(self, **kw) -> None:
        with self._mu:
            self.state.update(kw)
            self.state["updated"] = time.time()

    def _save_checkpoint(self) -> None:
        with self._mu:
            doc = dict(self.state)
        eventlog.emit("resync.checkpoint", target=self.arn,
                      objects=doc.get("versions_pushed", 0))
        payload = json.dumps(doc).encode()
        layers = getattr(self.obj, "server_sets", None) or [self.obj]
        for z in layers:
            try:
                # one hit per pool (arm :<nth>): resume re-covers the
                # un-checkpointed tail idempotently
                crashpoint.hit("resync.checkpoint")
                z.put_object(MINIO_META_BUCKET,
                             _checkpoint_object(self.arn), payload)
            except Exception:  # noqa: BLE001 — best-effort per pool
                pass

    @staticmethod
    def load_checkpoint(object_layer, arn: str) -> Optional[dict]:
        best: Optional[dict] = None
        layers = getattr(object_layer, "server_sets", None) \
            or [object_layer]
        for z in layers:
            try:
                _, stream = z.get_object(MINIO_META_BUCKET,
                                         _checkpoint_object(arn))
                # torn checkpoint (crash mid-write) = absent, never a
                # boot-path crash
                doc = atomicfile.load_json_doc(b"".join(stream))
            except api_errors.ObjectApiError:
                continue
            if doc is None:
                continue
            if best is None or doc.get("updated", 0) > \
                    best.get("updated", 0):
                best = doc
        return best
