"""Replication target clients: how one site writes at another.

One small verb surface so the sync worker and the resync walker stay
transport-agnostic (the reference's TargetClient, cmd/bucket-targets.go
+ the x-minio-source-* internal replication headers its peers honor):

  * :class:`LayerReplClient` — any in-process ObjectLayer (the
    two-cluster test harness, and same-process site pairs);
  * :class:`HTTPReplClient`  — a remote minio_tpu endpoint over SigV4,
    carrying the version-faithful spec in one internal header the S3
    PUT handler honors for owner credentials;
  * :class:`NaughtyReplClient` — deterministic fault wrapper (chaos
    tests: per-verb errors, 503 storms, offline windows, mid-stream
    death on the push body).

Verbs:

  ``remote_site()``            the target cluster's site id
  ``ensure_bucket()``          create the destination bucket if absent
  ``key_versions(key)``        every version of one key, as VersionSpecs
  ``apply_version(key, spec, reader_factory)``  idempotent faithful
      write — returns "applied" or "skipped" (conflict rule: for the
      unversioned slot the higher (mod_time, version_id) wins)
  ``delete_version(key, vid)`` purge one version (replica prune)
"""

from __future__ import annotations

import base64
import hashlib
import http.client
import json
import threading
import time
import urllib.parse
from typing import Callable, List, Optional

from ..object import api_errors
from ..object.faithful import VersionSpec, replay_version, spec_of
from ..utils import telemetry
from .targets import SiteTarget

_REPL_SPEC_HEADER = "x-minio-tpu-repl-spec"
_REPL_PURGE_HEADER = "x-minio-tpu-repl-purge"


class ReplClientError(Exception):
    """Target I/O failed (network, upstream 5xx, short stream)."""


class ReplTargetOffline(ReplClientError):
    """The target did not answer at all (connection-level failure)."""


_REPLICA_WRITES = None


def replica_writes_counter():
    """Replica versions WRITTEN at a site (the apply side). A flat
    count at the origin across repeated sync cycles is the loop-
    suppression proof: a replicated write is never pushed back."""
    global _REPLICA_WRITES
    if _REPLICA_WRITES is None:
        _REPLICA_WRITES = telemetry.REGISTRY.counter(
            "minio_tpu_repl_replica_writes_total",
            "Replica versions applied at this site, by site id")
    return _REPLICA_WRITES


class ReplTargetClient:
    """Minimal replication-target verb surface."""

    # push-only targets (generic S3 endpoints) cannot list versions:
    # the sync sends only the key's LATEST state instead of diffing
    # the whole history (re-pushing every version per mutation would
    # scale bandwidth with version count)
    push_only = False

    def remote_site(self) -> str:
        raise NotImplementedError

    def ensure_bucket(self) -> None:
        raise NotImplementedError

    def key_versions(self, key: str) -> List[VersionSpec]:
        raise NotImplementedError

    def apply_version(self, key: str, spec: VersionSpec,
                      reader_factory: Optional[Callable] = None) -> str:
        raise NotImplementedError

    def delete_version(self, key: str, version_id: str) -> None:
        raise NotImplementedError


def unversioned_conflict_keep(existing: Optional[VersionSpec],
                              incoming: VersionSpec) -> bool:
    """True when the EXISTING unversioned slot wins the deterministic
    conflict rule — (mod_time, version_id, etag) descending. The etag
    tie-break is load-bearing: two sites writing DIFFERENT bytes with
    identical mod times (explicit PutOptions.mod_time, coarse clocks)
    must still converge on ONE copy, and only content identity breaks
    that tie the same way on both sides. A full tie means identical
    content — keeping either copy converges."""
    if existing is None:
        return False
    return (existing.mod_time, existing.version_id, existing.etag) >= \
        (incoming.mod_time, incoming.version_id, incoming.etag)


class LayerReplClient(ReplTargetClient):
    """Adapter: an in-process ObjectLayer as a replication target."""

    def __init__(self, layer, bucket: str, site_id: str):
        self.layer = layer
        self.bucket = bucket
        self.site_id = site_id

    def remote_site(self) -> str:
        return self.site_id

    def ensure_bucket(self) -> None:
        try:
            self.layer.make_bucket(self.bucket)
        except api_errors.BucketExists:
            pass

    def key_versions(self, key: str) -> List[VersionSpec]:
        try:
            return [spec_of(oi)
                    for oi in self.layer.object_versions(self.bucket, key)]
        except api_errors.BucketNotFound:
            return []
        except api_errors.ObjectApiError as e:
            raise ReplClientError(f"target versions read: {e!r}") from e

    def apply_version(self, key: str, spec: VersionSpec,
                      reader_factory: Optional[Callable] = None) -> str:
        try:
            # versioned applies need no pre-read: writing a version id
            # the journal already holds replaces the identical entry
            # (idempotent), and the caller's diff already filtered the
            # common case — re-listing here made a V-version resync
            # O(V^2) quorum reads. The unversioned slot keeps its
            # cheap pre-check; the ENGINE's in-lock if_none_newer gate
            # is the authoritative race-proof decision either way.
            if not spec.version_id:
                have = next((v for v in self.key_versions(key)
                             if not v.version_id), None)
                if unversioned_conflict_keep(have, spec):
                    return "skipped"
            replay_version(self.layer, self.bucket, key, spec,
                           reader_factory=reader_factory)
        except api_errors.PreConditionFailed:
            # the engine's in-lock conflict gate: an equal-or-newer
            # version already occupies the slot — converged
            return "skipped"
        except ReplClientError:
            raise
        except api_errors.ObjectApiError as e:
            raise ReplClientError(f"target apply: {e!r}") from e
        replica_writes_counter().inc(site=self.site_id)
        return "applied"

    def delete_version(self, key: str, version_id: str) -> None:
        try:
            self.layer.delete_object(self.bucket, key,
                                     version_id=version_id,
                                     versioned=False)
        except (api_errors.ObjectNotFound, api_errors.VersionNotFound):
            return
        except api_errors.ObjectApiError as e:
            raise ReplClientError(f"target delete: {e!r}") from e


class HTTPReplClient(ReplTargetClient):
    """SigV4 wire client against a remote minio_tpu endpoint. The
    version spec rides ONE internal header on an ordinary S3 PUT
    (honored only for the owner credential — see handlers.put_object),
    version listings ride the admin replicate/key endpoint."""

    def __init__(self, target: SiteTarget, timeout: float = 30.0):
        p = target.params
        self.host = p["host"]
        self.port = int(p.get("port", 9000))
        self.bucket = target.dest_bucket
        self.access_key = p.get("access_key", "")
        self.secret_key = p.get("secret_key", "")
        self.region = p.get("region", "us-east-1")
        self.timeout = timeout
        self._site: Optional[str] = None

    # -- plumbing ------------------------------------------------------

    def _request(self, method: str, path: str,
                 query: Optional[dict] = None, body: object = b"",
                 headers: Optional[dict] = None,
                 body_sha: Optional[str] = None,
                 content_length: Optional[int] = None
                 ) -> tuple[int, bytes]:
        """`body` may be bytes or a seekable file-like (streamed by
        http.client); a file body needs its `body_sha` pre-computed
        and `content_length` set (http.client cannot stat a spool)."""
        from ..s3 import signature as sig
        from ..s3.credentials import Credentials
        query = {k: [v] for k, v in (query or {}).items()}
        qs = urllib.parse.urlencode({k: v[0] for k, v in query.items()})
        hdrs = {k.lower(): v for k, v in (headers or {}).items()}
        hdrs["host"] = f"{self.host}:{self.port}"
        if content_length is not None:
            hdrs["content-length"] = str(content_length)
        if body_sha is None:
            body_sha = hashlib.sha256(
                body if isinstance(body, (bytes, bytearray)) else b""
            ).hexdigest()
        hdrs = sig.sign_v4(method, urllib.parse.quote(path), query, hdrs,
                           body_sha,
                           Credentials(self.access_key, self.secret_key),
                           self.region)
        try:
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=self.timeout)
            conn.request(method,
                         urllib.parse.quote(path) + (f"?{qs}" if qs
                                                     else ""),
                         body=body, headers=hdrs)
            resp = conn.getresponse()
            data = resp.read()
            conn.close()
        except OSError as e:
            raise ReplTargetOffline(f"{self.host}:{self.port}: {e}") from e
        return resp.status, data

    # -- verbs ---------------------------------------------------------

    def remote_site(self) -> str:
        if self._site is None:
            status, data = self._request(
                "GET", "/minio/admin/v3/replicate")
            if status != 200:
                raise ReplClientError(f"replicate status: HTTP {status}")
            self._site = str(json.loads(data.decode()).get("site", ""))
        return self._site

    def ensure_bucket(self) -> None:
        status, _ = self._request("PUT", f"/{self.bucket}")
        if status not in (200, 409):
            raise ReplClientError(f"make bucket: HTTP {status}")

    def key_versions(self, key: str) -> List[VersionSpec]:
        status, data = self._request(
            "GET", "/minio/admin/v3/replicate/key",
            query={"bucket": self.bucket, "key": key})
        if status == 404:
            return []
        if status != 200:
            raise ReplClientError(f"key versions: HTTP {status}")
        doc = json.loads(data.decode())
        return [VersionSpec.from_dict(d)
                for d in doc.get("versions", [])]

    def apply_version(self, key: str, spec: VersionSpec,
                      reader_factory: Optional[Callable] = None) -> str:
        body: object = b""
        body_sha = None
        content_length = None
        if not spec.delete_marker and not spec.transitioned_stub:
            content_length = spec.size
            if reader_factory is None:
                raise ReplClientError("data version push needs a reader")
            reader = reader_factory()
            # hash in one streaming pass, then send the reader ITSELF
            # as the request body (the plane hands us a seekable spool:
            # RAM below 32 MiB, disk past it) — joining the chunks
            # into one bytes object doubled the resident size of every
            # large push
            h = hashlib.sha256()
            total = 0
            while True:
                chunk = reader.read(1 << 20)
                if not chunk:
                    break
                h.update(chunk)
                total += len(chunk)
            if total != spec.size:
                raise ReplClientError(
                    f"short push stream: {total} of {spec.size}")
            if hasattr(reader, "seek"):
                reader.seek(0)
                body, body_sha = reader, h.hexdigest()
            else:                       # non-seekable: re-read fully
                reader = reader_factory()
                body = reader.read(-1) or b""
                body_sha = hashlib.sha256(body).hexdigest()
        hdr = base64.urlsafe_b64encode(
            json.dumps(spec.to_dict()).encode()).decode()
        status, data = self._request(
            "PUT", f"/{self.bucket}/{key}", body=body,
            body_sha=body_sha, content_length=content_length,
            headers={_REPL_SPEC_HEADER: hdr})
        if status != 200:
            raise ReplClientError(f"apply: HTTP {status} "
                                  f"{data[:200]!r}")
        try:
            return json.loads(data.decode()).get("result", "applied")
        except ValueError:
            return "applied"

    def delete_version(self, key: str, version_id: str) -> None:
        query = {"versionId": version_id} if version_id else None
        status, data = self._request(
            "DELETE", f"/{self.bucket}/{key}", query=query,
            headers={_REPL_PURGE_HEADER: "true"})
        if status not in (200, 204, 404):
            raise ReplClientError(f"delete: HTTP {status} {data[:200]!r}")


class NaughtyReplClient(ReplTargetClient):
    """Deterministic fault wrapper over a real target client — the
    NaughtyDisk/NaughtyTierClient model applied to the replication
    wire:

      * ``fail_verbs[verb] = exc``      fail EVERY call of a verb
      * ``verb_errors[verb][n] = exc``  fail exactly the n-th call
        (1-based per verb)
      * ``offline_until_call[verb] = n``  every call before the n-th
        raises ReplTargetOffline (a target-offline window that heals)
      * ``latency_s``                   sleep before every verb
      * ``die_midstream``               apply's reader dies after half
        the first chunk (push killed mid-body)

    Verbs: site, bucket, versions, apply, delete."""

    VERBS = ("site", "bucket", "versions", "apply", "delete")

    def __init__(self, inner: ReplTargetClient,
                 fail_verbs: Optional[dict] = None,
                 verb_errors: Optional[dict] = None,
                 offline_until_call: Optional[dict] = None,
                 latency_s: float = 0.0,
                 die_midstream: bool = False):
        self.inner = inner
        self.fail_verbs = dict(fail_verbs or {})
        self.verb_errors = {v: dict(m)
                            for v, m in (verb_errors or {}).items()}
        self.offline_until_call = dict(offline_until_call or {})
        self.latency_s = latency_s
        self.die_midstream = die_midstream
        self._mu = threading.Lock()
        self.calls: dict[str, int] = {v: 0 for v in self.VERBS}
        self.stats = {"errors": 0, "offline": 0, "midstream_deaths": 0}

    def clear_faults(self) -> None:
        with self._mu:
            self.fail_verbs.clear()
            self.verb_errors.clear()
            self.offline_until_call.clear()
            self.die_midstream = False

    def _enter(self, verb: str) -> None:
        with self._mu:
            self.calls[verb] += 1
            n = self.calls[verb]
            until = self.offline_until_call.get(verb, 0)
            err = self.fail_verbs.get(verb) \
                or self.verb_errors.get(verb, {}).get(n)
            lat = self.latency_s
        if lat:
            time.sleep(lat)
        if until and n < until:
            self.stats["offline"] += 1
            raise ReplTargetOffline(f"{verb}: offline window")
        if err is not None:
            self.stats["errors"] += 1
            raise err

    def remote_site(self) -> str:
        self._enter("site")
        return self.inner.remote_site()

    def ensure_bucket(self) -> None:
        self._enter("bucket")
        self.inner.ensure_bucket()

    def key_versions(self, key: str) -> List[VersionSpec]:
        self._enter("versions")
        return self.inner.key_versions(key)

    def apply_version(self, key: str, spec: VersionSpec,
                      reader_factory: Optional[Callable] = None) -> str:
        self._enter("apply")
        if self.die_midstream and reader_factory is not None:
            outer = self

            def dying_factory():
                reader = reader_factory()

                class _Dying:
                    def __init__(self):
                        self.fed = 0

                    def read(self, n: int = -1) -> bytes:
                        chunk = reader.read(n)
                        if self.fed + len(chunk) > max(spec.size // 2, 1):
                            outer.stats["midstream_deaths"] += 1
                            raise ReplClientError(
                                "connection died mid-stream")
                        self.fed += len(chunk)
                        return chunk

                return _Dying()

            return self.inner.apply_version(key, spec, dying_factory)
        return self.inner.apply_version(key, spec, reader_factory)

    def delete_version(self, key: str, version_id: str) -> None:
        self._enter("delete")
        self.inner.delete_version(key, version_id)


class PushS3ReplClient(ReplTargetClient):
    """One-way push to a GENERIC S3 endpoint (AWS, reference MinIO) —
    the legacy bucket-metadata remote targets' semantics carried into
    the plane: no peer admin surface, no version listing, no identity
    preservation. Every sync re-pushes the key's versions oldest-first
    (the remote converges on the latest state, like the old
    ReplicationPool's fire-and-forget copier); markers become plain
    DELETEs; transitioned stubs are skipped (a generic remote cannot
    hold a metadata-only version)."""

    push_only = True

    def __init__(self, target: SiteTarget):
        from ..features.replication import (ReplicationTarget,
                                            _S3MiniClient)
        p = target.params
        self._mini = _S3MiniClient(ReplicationTarget(
            arn=target.arn, host=p["host"],
            port=int(p.get("port", 9000)),
            bucket=target.dest_bucket,
            access_key=p.get("access_key", ""),
            secret_key=p.get("secret_key", ""),
            region=p.get("region", "us-east-1"),
            secure=bool(p.get("secure", False))))

    def remote_site(self) -> str:
        return ""                       # not a peer: no site identity

    def ensure_bucket(self) -> None:
        pass                            # remote bucket pre-exists

    def key_versions(self, key: str) -> List[VersionSpec]:
        return []                       # no diff surface (push_only)

    def apply_version(self, key: str, spec: VersionSpec,
                      reader_factory: Optional[Callable] = None) -> str:
        try:
            if spec.delete_marker:
                if not self._mini.delete_object(key):
                    raise ReplClientError(f"remote DELETE {key} failed")
                return "applied"
            if spec.transitioned_stub:
                return "skipped"        # unrepresentable remotely
            if reader_factory is None:
                raise ReplClientError("data push needs a reader")
            reader = reader_factory()
            body = reader.read(-1) or b""
            md = {k: v for k, v in spec.metadata.items()
                  if not k.lower().startswith("x-minio-internal")}
            if not self._mini.put_object(key, body, md):
                raise ReplClientError(f"remote PUT {key} failed")
            return "applied"
        except OSError as e:
            raise ReplTargetOffline(str(e)) from e

    def delete_version(self, key: str, version_id: str) -> None:
        try:
            self._mini.delete_object(key)
        except OSError as e:
            raise ReplTargetOffline(str(e)) from e


def new_repl_client(target: SiteTarget) -> ReplTargetClient:
    """Client factory from a persisted target entry ("s3" = a
    minio_tpu peer over the internal wire form, "push" = a generic
    S3 endpoint, one-way; "layer" targets are injected live via
    registry.set_client)."""
    if target.type == "s3":
        return HTTPReplClient(target)
    if target.type == "push":
        return PushS3ReplClient(target)
    raise ValueError(f"unknown replication target type {target.type!r}")
