"""The active-active replication plane: bidirectional site sync.

One listener on the engines' namespace-change feed (wired by
``ErasureServerSets.attach_replication`` — the lint gate's
hook-coverage rule proves every mutation verb reaches this queue), a
bounded dedup queue of ``(bucket, key)`` sync tasks, and a worker pool
that CONVERGES each touched key against every registered target:

  * **push** — every local version the target lacks replays with full
    fidelity (multipart part boundaries, delete markers, transitioned
    stubs as metadata) carrying its ORIGIN site id in version
    metadata;
  * **loop suppression** — a version that originated AT the target is
    never pushed back (the replica-origin marker, so an A→B replica
    write at B re-fires B's feed but syncs to A as a no-op: no
    ping-pong, proven by a flat replica-write counter);
  * **conflict resolution** — deterministic: the higher
    ``(mod_time, version_id)`` wins the unversioned slot, applied
    identically at push AND apply side, so two sites that saw
    concurrent writes converge to identical listings;
  * **prune** — replicas of THIS site's versions that no longer exist
    here are deleted at the target (versioned deletes and bulk deletes
    converge without per-operation plumbing);
  * failed syncs feed an MRF-style retry queue (the fault plane's
    ``MRFHealer`` with the replication sync as its heal fn) with
    capped exponential backoff — a 503 storm or target-offline window
    drains clean on recovery;
  * pushes throttle off the shared foreground-pressure probe and pace
    through per-target token-bucket bandwidth budgets
    (``utils/bandwidth.py``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from ..object import api_errors
from ..object.background import MRFHealer
from ..object.engine import GetOptions
from ..object.faithful import spec_of
from ..utils import crashpoint, knobs, telemetry
from ..utils.bandwidth import TokenBucket
from ..utils.pressure import ForegroundPressure
from .client import (ReplClientError, ReplTargetClient,
                     unversioned_conflict_keep)
from .targets import (REPL_ORIGIN_KEY, SiteTarget, TargetRegistry,
                      origin_of)

WORKERS = knobs.get_int("MINIO_TPU_REPL_WORKERS")
QUEUE_SIZE = knobs.get_int("MINIO_TPU_REPL_QUEUE")
BACKOFF_S = knobs.get_float("MINIO_TPU_REPL_BACKOFF_S")
BACKOFF_MAX_S = knobs.get_float("MINIO_TPU_REPL_BACKOFF_MAX_S")
BACKOFF_TRIES = knobs.get_int("MINIO_TPU_REPL_BACKOFF_TRIES")

_LAG_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 300)


def _metrics():
    reg = telemetry.REGISTRY
    return (
        reg.counter("minio_tpu_repl_synced_total",
                    "Object versions pushed to replication targets"),
        reg.counter("minio_tpu_repl_failed_total",
                    "Key syncs that failed (fed to the replication "
                    "MRF queue, retried with backoff)"),
        reg.counter("minio_tpu_repl_pruned_total",
                    "Replica versions deleted at targets after their "
                    "origin version was removed here"),
        reg.histogram("minio_tpu_repl_lag_seconds",
                      "Replication lag: push completion minus the "
                      "version's mod time", buckets=_LAG_BUCKETS),
    )


class ReplicationPlane:
    """One site's replication engine (queue + workers + retry)."""

    def __init__(self, object_layer, registry: TargetRegistry,
                 bucket_meta=None,
                 workers: Optional[int] = None,
                 queue_size: Optional[int] = None,
                 busy_fn=None, throttle_s: Optional[float] = None):
        self.obj = object_layer
        self.registry = registry
        # optional bucket metadata system: when present AND a bucket
        # carries a replication XML config, its rules gate which keys
        # replicate (the legacy per-bucket surface); registry targets
        # alone replicate everything under their prefix
        self.bucket_meta = bucket_meta
        self._pressure = ForegroundPressure(object_layer, busy_fn=busy_fn)
        self._throttle_base = BACKOFF_S if throttle_s is None \
            else throttle_s
        self.queue_size = QUEUE_SIZE if queue_size is None else queue_size
        self._cond = threading.Condition()
        self._queue: deque = deque()    # (bucket, key, enqueued_at)
        self._pending: set[tuple[str, str]] = set()
        self._inflight = 0
        # per-target admin surface (ROADMAP item 4 remainder): queue
        # depth + oldest-pending age are derived from the live queue on
        # demand; synced/failed/last-sync/last-lag update as workers
        # push — the JSON twin of minio_tpu_repl_lag_seconds{target}
        self._target_stats: dict[str, dict] = {}
        self._stop = threading.Event()
        self._buckets: dict[str, TokenBucket] = {}
        # optional BandwidthMonitor (cluster wires the S3 server's):
        # replication egress shows up in admin /bandwidth per bucket
        self.bandwidth = None
        # stats (admin surface / tests)
        self.queued = 0
        self.synced = 0
        self.skipped = 0
        self.failed_syncs = 0
        self.pruned = 0
        self.dropped = 0
        # failed target syncs retry here with capped exponential
        # backoff — the fault plane's queue, the replication sync as
        # its heal fn (the version slot carries the target ARN)
        self.mrf = MRFHealer(heal_fn=self._mrf_retry)
        self._resyncer = None
        self._threads = []
        for i in range(WORKERS if workers is None else workers):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"repl-sync-{i}")
            t.start()
            self._threads.append(t)

    # -- admin/metrics compat (the legacy pool's counter names) ---------

    @property
    def replicated(self) -> int:
        return self.synced

    @property
    def failed(self) -> int:
        return self.failed_syncs

    @property
    def targets(self) -> dict:
        return self.registry.targets

    def mount_target_entry(self, entry: dict) -> str:
        return self.registry.mount_target_entry(entry)

    def remove_target(self, arn: str) -> None:
        self.registry.remove(arn)

    # -- the namespace-feed listener ------------------------------------

    def on_namespace_change(self, bucket: str, key: str) -> None:
        """Enqueue one key sync; never blocks (bounded queue, overflow
        drops + counts — the resync verb is the backstop)."""
        if bucket.startswith(".") or not key:
            return
        if not self.registry.for_bucket(bucket):
            return
        with self._cond:
            if self._stop.is_set() or (bucket, key) in self._pending:
                return
            if len(self._queue) >= self.queue_size:
                self.dropped += 1
                return
            self._pending.add((bucket, key))
            self._queue.append((bucket, key, time.time()))
            self.queued += 1
            self._cond.notify_all()

    # -- lifecycle / observability --------------------------------------

    def close(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._resyncer is not None:
            self._resyncer.stop()
        self.mrf.close()

    def stats(self) -> dict:
        with self._cond:
            out = {"pending": len(self._queue) + self._inflight,
                   "queued": self.queued, "synced": self.synced,
                   "skipped": self.skipped, "failed": self.failed_syncs,
                   "pruned": self.pruned, "dropped": self.dropped}
        out["retry"] = self.mrf.stats()
        return out

    def _target_entry(self, arn: str) -> dict:
        # caller holds self._cond
        entry = self._target_stats.get(arn)
        if entry is None:
            entry = self._target_stats[arn] = {
                "synced": 0, "failed": 0,
                "last_sync": 0.0, "last_lag_s": None}
        return entry

    def target_status(self) -> dict:
        """Per-target replication health for the admin plane: live
        queue depth + oldest-pending age (matching keys still waiting
        in the sync queue), last successful push timestamp, last
        observed lag, cumulative synced/failed. The histogram twin is
        ``minio_tpu_repl_lag_seconds{target}``."""
        now = time.time()
        with self._cond:
            queue_snapshot = list(self._queue)
            entries = {arn: dict(st)
                       for arn, st in self._target_stats.items()}
        out: dict = {}
        for target in list(self.registry.targets.values()):
            st = entries.get(target.arn) or {
                "synced": 0, "failed": 0,
                "last_sync": 0.0, "last_lag_s": None}
            matching = [t for b, k, t in queue_snapshot
                        if b == target.bucket and target.matches(k)]
            st["queue_depth"] = len(matching)
            st["oldest_pending_s"] = round(now - min(matching), 3) \
                if matching else 0.0
            st["bucket"] = target.bucket
            out[target.arn] = st
        return out

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait until the sync queue AND the retry queue are empty.
        Returns False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._queue or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    return not (self._queue or self._inflight)
                self._cond.wait(remaining)
        return self.mrf.drain(max(deadline - time.monotonic(), 0.001))

    # -- resync management ----------------------------------------------

    def start_resync(self, arn: str, **kw):
        """Seed (or re-seed) one target from the namespace feed with
        checkpointed resume — see replicate/resync.py."""
        from .resync import Resyncer
        if self._resyncer is not None and self._resyncer.running():
            raise ReplClientError(
                f"a resync of {self._resyncer.arn} is already running")
        self.registry.get(arn)          # must exist
        self._resyncer = Resyncer(self.obj, self.registry, arn,
                                  plane=self, **kw)
        self._resyncer.start()
        return self._resyncer

    def resync_status(self) -> Optional[dict]:
        if self._resyncer is None:
            return None
        return self._resyncer.status()

    def cancel_resync(self) -> bool:
        if self._resyncer is None:
            return False
        return self._resyncer.stop()

    # -- workers ---------------------------------------------------------

    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._stop.is_set() and not self._queue:
                    self._cond.wait()
                if self._stop.is_set():
                    return
                bucket, key, _enq = self._queue.popleft()
                self._pending.discard((bucket, key))
                self._inflight += 1
            try:
                self._pressure.throttle(self._stop, self._throttle_base,
                                        BACKOFF_MAX_S, BACKOFF_TRIES)
                if not self._stop.is_set():
                    self._sync_key_targets(bucket, key)
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def _sync_key_targets(self, bucket: str, key: str) -> None:
        _synced_c, failed_c, _pruned_c, _lag_h = _metrics()
        for target in self.registry.for_bucket(bucket):
            if not target.matches(key) or \
                    not self._rules_allow(bucket, key, target):
                continue
            with telemetry.trace("replicate.sync", bucket=bucket,
                                 object=key, target=target.arn):
                try:
                    self.sync_key(bucket, key, target)
                except Exception:  # noqa: BLE001 — per-target isolation;
                    # the retry queue re-drives with backoff
                    with self._cond:
                        self.failed_syncs += 1
                        self._target_entry(target.arn)["failed"] += 1
                    failed_c.inc()
                    self.mrf.enqueue(bucket, key, target.arn)

    def _mrf_retry(self, bucket: str, key: str, arn: str) -> None:
        """The retry queue's heal fn: re-sync one (key, target); an
        exception requeues with backoff, MRF-style."""
        try:
            target = self.registry.get(arn)
        except api_errors.ObjectApiError:
            return                      # target removed: converged
        self.sync_key(bucket, key, target)

    def _rules_allow(self, bucket: str, key: str,
                     target: SiteTarget) -> bool:
        """Legacy per-bucket replication XML, when present, gates keys
        (rule prefix must match); buckets without a config replicate
        everything the target's own prefix admits."""
        if self.bucket_meta is None:
            return True
        try:
            xml = self.bucket_meta.get(bucket).replication_xml
        except Exception:  # noqa: BLE001 — meta unavailable: no gate
            return True
        if not xml:
            return True
        from ..features.replication import ReplicationConfig
        try:
            cfg = ReplicationConfig.from_xml(xml)
        except Exception:  # noqa: BLE001 — malformed config: no gate
            return True
        return cfg.rule_for(key) is not None

    # -- the convergence step --------------------------------------------

    def _target_site(self, target: SiteTarget,
                     client: ReplTargetClient) -> str:
        if not target.site:
            target.site = client.remote_site()
        return target.site

    def _pacer(self, target: SiteTarget) -> TokenBucket:
        rate = target.bw_bps or knobs.get_int("MINIO_TPU_REPL_BW_BPS")
        with self._cond:
            tb = self._buckets.get(target.arn)
            if tb is None:
                tb = self._buckets[target.arn] = TokenBucket(rate)
            elif tb.rate != rate:
                # a re-registered target (or a flipped env knob) takes
                # effect on the NEXT push, not at process restart
                tb.set_rate(rate)
        return tb

    def _reader_factory(self, bucket: str, key: str, version_id: str,
                        target: SiteTarget):
        pacer = self._pacer(target)
        monitor = getattr(self, "bandwidth", None)

        def factory():
            # spool the version FULLY (RAM below 32 MiB, disk past it)
            # and CLOSE the source stream before the target apply runs:
            # a GET stream holds this site's per-key READ lock, and two
            # sites pushing the same key at each other while holding
            # their local read locks deadlock on the peers' write locks
            # (found live by the two-cluster concurrent-writer test)
            import tempfile
            # the null slot must be read by its SENTINEL: an empty
            # version id resolves to "latest", which under a versioned
            # history is a DIFFERENT version — pushing the null spec
            # with the latest version's bytes would corrupt the replica
            _info, stream = self.obj.get_object(
                bucket, key,
                opts=GetOptions(version_id=version_id or "null"))

            def on_bytes(n: int) -> None:
                if monitor is not None:
                    monitor.record(bucket, "tx", n)

            spool = tempfile.SpooledTemporaryFile(max_size=32 << 20)
            try:
                for chunk in pacer.paced(stream, on_bytes=on_bytes):
                    spool.write(chunk)
            finally:
                try:
                    stream.close()
                except Exception:  # noqa: BLE001 — release best-effort
                    pass
            spool.seek(0)
            return spool

        return factory

    def sync_key(self, bucket: str, key: str, target: SiteTarget,
                 resync: bool = False) -> int:
        """Converge ONE key at one target: push what it lacks, prune
        replicas of our deleted versions. `resync` pushes EVERY version
        the target lacks (disaster reseed — even versions that
        originated at the target) and never prunes. Returns versions
        pushed. Raises on any target failure (callers feed the retry
        queue)."""
        synced_c, _failed_c, pruned_c, lag_h = _metrics()
        client = self.registry.client(target.arn)
        target_site = "" if resync else self._target_site(target, client)
        my = self.registry.site_id
        local = self.obj.object_versions(bucket, key)
        if getattr(client, "push_only", False) and local:
            # generic S3 target: mirror the LATEST state only (the
            # legacy one-way semantics) — re-pushing the whole history
            # per mutation would scale bandwidth with version count
            local = [max(local,
                         key=lambda o: (o.mod_time or 0,
                                        o.version_id or "",
                                        o.etag or ""))]
        remote = client.key_versions(key)
        remote_vids = {v.version_id for v in remote if v.version_id}
        remote_null = next((v for v in remote if not v.version_id), None)
        pushed = 0
        # oldest first: relative history order survives at the target
        # wherever mod times tie
        for oi in sorted(local, key=lambda o: (o.mod_time or 0,
                                               o.version_id or "")):
            md = oi.user_defined or {}
            origin = origin_of(md, my)
            if not resync and origin == target_site:
                continue                # loop suppression: never echo
            spec = spec_of(oi)
            spec.metadata[REPL_ORIGIN_KEY] = origin
            if spec.version_id:
                if spec.version_id in remote_vids:
                    continue
            elif unversioned_conflict_keep(remote_null, spec):
                continue                # remote's unversioned slot wins
            factory = None
            if not spec.delete_marker and not spec.transitioned_stub:
                factory = self._reader_factory(bucket, key,
                                               spec.version_id, target)
            try:
                # spooled and ready, the target has not seen it: a
                # crash here must leave a retryable queue entry, never
                # a half-applied replica
                crashpoint.hit("replicate.push.before_apply")
                result = client.apply_version(key, spec, factory)
            except api_errors.ObjectApiError:
                # the version vanished locally between list and read
                # (raced a delete): the prune below converges it
                with self._cond:
                    self.skipped += 1
                continue
            if result == "applied":
                pushed += 1
                lag = max(time.time() - (oi.mod_time or 0), 0.0)
                with self._cond:
                    self.synced += 1
                    entry = self._target_entry(target.arn)
                    entry["synced"] += 1
                    entry["last_sync"] = time.time()
                    entry["last_lag_s"] = round(lag, 3)
                synced_c.inc()
                lag_h.observe(lag, target=target.arn)
            else:
                with self._cond:
                    self.skipped += 1
        if resync:
            return pushed
        # prune: replicas of OUR versions the target still holds but we
        # no longer do (versioned deletes / bulk deletes converge).
        # Guard: an empty local read must be a PROVEN deletion, not a
        # degraded quorum read — get_object_info distinguishes them.
        local_vids = {oi.version_id for oi in local}
        prunable = [v for v in remote
                    if origin_of(v.metadata, "") == my
                    and (v.version_id not in local_vids
                         if v.version_id
                         else not any(not vid for vid in local_vids))]
        if prunable and not local:
            try:
                self.obj.get_object_info(bucket, key)
            except api_errors.ObjectNotFound:
                pass                    # truly gone: prune is safe
            except api_errors.ObjectApiError as e:
                raise ReplClientError(
                    f"degraded local read, prune deferred: {e!r}") from e
        for v in prunable:
            client.delete_version(key, v.version_id)
            with self._cond:
                self.pruned += 1
            pruned_c.inc()
        return pushed
