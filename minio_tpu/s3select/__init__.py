"""S3 Select: SQL over CSV/JSON objects with event-stream responses
(reference pkg/s3select — SQL parser/evaluator, format readers, message
framing)."""

from .select import SelectRequest, run_select  # noqa: F401
