"""SQL subset for S3 Select (reference pkg/s3select/sql — hand-written
parser + evaluator).

Grammar:
    SELECT projection FROM table [WHERE expr] [LIMIT n]
    projection := * | expr [AS name] ("," expr [AS name])*
    table      := S3Object[.path] [[AS] alias]
    expr       := OR-chains of AND-chains of comparisons over terms
    comparison := term (=|!=|<>|<|<=|>|>=) term | term [NOT] LIKE str
                  | term [NOT] IN (lit, ...) | term [NOT] BETWEEN a AND b
                  | term IS [NOT] NULL
    term       := literal | column | alias.column | _N | -term
                  | term (+|-|*|/|%) term | (expr)
                  | COUNT(*) | SUM/AVG/MIN/MAX/COUNT(expr)
                  | LOWER/UPPER/LENGTH/CHAR_LENGTH(expr)
                  | TRIM([[LEADING|TRAILING|BOTH] [chars] FROM] s)
                  | SUBSTRING(s FROM a [FOR n]) | SUBSTRING(s, a[, n])
                  | COALESCE(a, ...) | NULLIF(a, b)
                  | EXTRACT(part FROM ts) | UTCNOW()
                  | DATE_ADD(part, qty, ts) | DATE_DIFF(part, t1, t2)
                  | TO_TIMESTAMP(s) | TO_STRING(ts, 'pattern')
                  | CAST(expr AS type)   -- incl. TIMESTAMP

Values are Python str/float/int/bool/None/datetime; comparisons coerce
numerics like the reference's typed values; timestamp semantics mirror
pkg/s3select/sql/{funceval,timestampfuncs,stringfuncs}.go (TO_STRING /
TO_TIMESTAMP are implemented here although the reference returns
errNotImplemented for them, funceval.go:140).
"""

from __future__ import annotations

import datetime as _dt
import re
from typing import Any, Optional


class SQLError(Exception):
    pass


# -- SQL timestamps ---------------------------------------------------------
# The reference's accepted layouts (pkg/s3select/sql/timestampfuncs.go:23):
# 2006T | 2006-01T | 2006-01-02T | ..T15:04Z07:00 | ..:05 | ..05.frac

_TS_PATTERNS = [
    re.compile(r"^(\d{4})T$"),
    re.compile(r"^(\d{4})-(\d{2})T$"),
    re.compile(r"^(\d{4})-(\d{2})-(\d{2})T$"),
    re.compile(r"^(\d{4})-(\d{2})-(\d{2})T(\d{2}):(\d{2})"
               r"(?::(\d{2})(\.\d+)?)?(Z|[+-]\d{2}:\d{2})$"),
]


def parse_sql_timestamp(s: str) -> _dt.datetime:
    s = s.strip()
    for rx in _TS_PATTERNS:
        m = rx.match(s)
        if not m:
            continue
        g = m.groups()
        if len(g) <= 3:                        # date-only layouts
            y = int(g[0])
            mo = int(g[1]) if len(g) > 1 else 1
            d = int(g[2]) if len(g) > 2 else 1
            return _dt.datetime(y, mo, d, tzinfo=_dt.timezone.utc)
        y, mo, d, hh, mm = (int(x) for x in g[:5])
        ss = int(g[5]) if g[5] else 0
        # microseconds from the DIGITS (float math truncates .000249
        # into 248 µs); digits past µs precision are dropped
        micro = int(g[6][1:7].ljust(6, "0")) if g[6] else 0
        tz = g[7]
        if tz == "Z":
            tzinfo = _dt.timezone.utc
        else:
            sign = 1 if tz[0] == "+" else -1
            tzinfo = _dt.timezone(sign * _dt.timedelta(
                hours=int(tz[1:3]), minutes=int(tz[4:6])))
        return _dt.datetime(y, mo, d, hh, mm, ss, micro, tzinfo=tzinfo)
    raise SQLError(f"invalid timestamp {s!r}")


def format_sql_timestamp(t: _dt.datetime) -> str:
    """Reference FormatSQLTimestamp: shortest layout that keeps every
    nonzero component (timestampfuncs.go:54)."""
    off = t.utcoffset() or _dt.timedelta(0)

    def tzs() -> str:
        if not off:
            return "Z"
        total = int(off.total_seconds())
        sign = "+" if total >= 0 else "-"
        total = abs(total)
        return f"{sign}{total // 3600:02d}:{total % 3600 // 60:02d}"

    if t.microsecond:
        frac = f"{t.microsecond / 1e6:.9f}"[2:].rstrip("0")
        return (f"{t.year:04d}-{t.month:02d}-{t.day:02d}T"
                f"{t.hour:02d}:{t.minute:02d}:{t.second:02d}"
                f".{frac}{tzs()}")
    if t.second:
        return (f"{t.year:04d}-{t.month:02d}-{t.day:02d}T"
                f"{t.hour:02d}:{t.minute:02d}:{t.second:02d}{tzs()}")
    if t.hour or t.minute or off:
        return (f"{t.year:04d}-{t.month:02d}-{t.day:02d}T"
                f"{t.hour:02d}:{t.minute:02d}{tzs()}")
    if t.day != 1:
        return f"{t.year:04d}-{t.month:02d}-{t.day:02d}T"
    if t.month != 1:
        return f"{t.year:04d}-{t.month:02d}T"
    return f"{t.year:04d}T"


_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<number>\d+(\.\d*)?([eE][+-]?\d+)?|\.\d+)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><>|<=|>=|!=|=|<|>|\(|\)|,|\*|/|\+|-|%|\.)
""", re.VERBOSE)

_KEYWORDS = {
    "select", "from", "where", "limit", "as", "and", "or", "not", "like",
    "in", "between", "is", "null", "true", "false", "escape", "cast",
}


def tokenize(src: str) -> list[tuple[str, str]]:
    out = []
    i = 0
    while i < len(src):
        m = _TOKEN_RE.match(src, i)
        if not m:
            raise SQLError(f"bad character {src[i]!r} at {i}")
        i = m.end()
        kind = m.lastgroup
        text = m.group()
        if kind == "ws":
            continue
        if kind == "ident" and text.lower() in _KEYWORDS:
            out.append(("kw", text.lower()))
        elif kind == "string":
            out.append(("str", text[1:-1].replace("''", "'")))
        elif kind == "qident":
            out.append(("ident", text[1:-1].replace('""', '"')))
        else:
            out.append((kind, text))
    out.append(("eof", ""))
    return out


# -- AST --------------------------------------------------------------------

class Node:
    pass


class Lit(Node):
    def __init__(self, v):
        self.v = v


class Col(Node):
    def __init__(self, name: str):
        self.name = name


class Unary(Node):
    def __init__(self, op, x):
        self.op, self.x = op, x


class Bin(Node):
    def __init__(self, op, a, b):
        self.op, self.a, self.b = op, a, b


class Like(Node):
    def __init__(self, x, pat, negate):
        self.x, self.pat, self.negate = x, pat, negate


class In(Node):
    def __init__(self, x, items, negate):
        self.x, self.items, self.negate = x, items, negate


class Between(Node):
    def __init__(self, x, lo, hi, negate):
        self.x, self.lo, self.hi, self.negate = x, lo, hi, negate


class IsNull(Node):
    def __init__(self, x, negate):
        self.x, self.negate = x, negate


class Func(Node):
    def __init__(self, name, args):
        self.name, self.args = name, args


class Agg(Node):
    def __init__(self, name, arg):
        self.name, self.arg = name, arg   # arg None = COUNT(*)


class Query:
    def __init__(self):
        self.projections: list[tuple[Node, Optional[str]]] = []
        self.star = False
        self.alias = "s3object"
        self.where: Optional[Node] = None
        self.limit: Optional[int] = None

    @property
    def is_aggregate(self) -> bool:
        return any(isinstance(e, Agg) for e, _ in self.projections)


_AGG_FUNCS = {"count", "sum", "avg", "min", "max"}
_SCALAR_FUNCS = {"lower", "upper", "length", "char_length",
                 "character_length", "trim", "abs", "coalesce",
                 "nullif", "utcnow", "to_timestamp", "to_string"}
_DATE_PARTS = {"year", "month", "day", "hour", "minute", "second",
               "timezone_hour", "timezone_minute"}


class Parser:
    def __init__(self, tokens):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect_kw(self, kw):
        k, v = self.next()
        if k != "kw" or v != kw:
            raise SQLError(f"expected {kw.upper()}, got {v!r}")

    def accept_kw(self, kw) -> bool:
        k, v = self.peek()
        if k == "kw" and v == kw:
            self.i += 1
            return True
        return False

    def accept_op(self, op) -> bool:
        k, v = self.peek()
        if k == "op" and v == op:
            self.i += 1
            return True
        return False

    # -- grammar ----------------------------------------------------------

    def parse(self) -> Query:
        q = Query()
        self.expect_kw("select")
        if self.accept_op("*"):
            q.star = True
        else:
            while True:
                e = self.expr()
                alias = None
                if self.accept_kw("as"):
                    k, v = self.next()
                    if k not in ("ident", "str"):
                        raise SQLError("bad alias")
                    alias = v
                q.projections.append((e, alias))
                if not self.accept_op(","):
                    break
        self.expect_kw("from")
        k, v = self.next()
        if k != "ident" or v.lower() not in ("s3object", "s3objects"):
            raise SQLError(f"FROM must be S3Object, got {v!r}")
        while self.accept_op("."):
            self.next()                      # S3Object.path: ignored
        k, v = self.peek()
        if k == "ident":
            q.alias = v.lower()
            self.next()
        elif self.accept_kw("as"):
            k, v = self.next()
            q.alias = v.lower()
        if self.accept_kw("where"):
            q.where = self.expr()
        if self.accept_kw("limit"):
            k, v = self.next()
            if k != "number":
                raise SQLError("LIMIT needs a number")
            q.limit = int(float(v))
        k, v = self.peek()
        if k != "eof":
            raise SQLError(f"unexpected trailing {v!r}")
        return q

    def expr(self) -> Node:
        return self.or_expr()

    def or_expr(self) -> Node:
        left = self.and_expr()
        while self.accept_kw("or"):
            left = Bin("or", left, self.and_expr())
        return left

    def and_expr(self) -> Node:
        left = self.not_expr()
        while self.accept_kw("and"):
            left = Bin("and", left, self.not_expr())
        return left

    def not_expr(self) -> Node:
        if self.accept_kw("not"):
            return Unary("not", self.not_expr())
        return self.comparison()

    def comparison(self) -> Node:
        left = self.additive()
        negate = self.accept_kw("not")
        k, v = self.peek()
        if k == "op" and v in ("=", "!=", "<>", "<", "<=", ">", ">="):
            if negate:
                raise SQLError("NOT before comparison operator")
            self.next()
            return Bin(v, left, self.additive())
        if self.accept_kw("like"):
            k, pat = self.next()
            if k != "str":
                raise SQLError("LIKE needs a string pattern")
            esc = ""
            if self.accept_kw("escape"):
                k2, esc = self.next()
                if k2 != "str" or len(esc) != 1:
                    raise SQLError("ESCAPE needs a 1-char string")
            return Like(left, _like_regex(pat, esc), negate)
        if self.accept_kw("in"):
            if not self.accept_op("("):
                raise SQLError("IN needs a list")
            items = []
            while True:
                items.append(self.additive())
                if not self.accept_op(","):
                    break
            if not self.accept_op(")"):
                raise SQLError("unclosed IN list")
            return In(left, items, negate)
        if self.accept_kw("between"):
            lo = self.additive()
            self.expect_kw("and")
            hi = self.additive()
            return Between(left, lo, hi, negate)
        if self.accept_kw("is"):
            neg = self.accept_kw("not")
            self.expect_kw("null")
            return IsNull(left, neg)
        if negate:
            raise SQLError("dangling NOT")
        return left

    def additive(self) -> Node:
        left = self.multiplicative()
        while True:
            k, v = self.peek()
            if k == "op" and v in ("+", "-"):
                self.next()
                left = Bin(v, left, self.multiplicative())
            else:
                return left

    def multiplicative(self) -> Node:
        left = self.unary()
        while True:
            k, v = self.peek()
            if k == "op" and v in ("*", "/", "%"):
                self.next()
                left = Bin(v, left, self.unary())
            else:
                return left

    def unary(self) -> Node:
        if self.accept_op("-"):
            return Unary("neg", self.unary())
        if self.accept_op("+"):
            return self.unary()
        return self.primary()

    def primary(self) -> Node:
        k, v = self.next()
        if k == "number":
            f = float(v)
            return Lit(int(f) if f.is_integer() and "." not in v
                       and "e" not in v.lower() else f)
        if k == "str":
            return Lit(v)
        if k == "kw" and v in ("true", "false"):
            return Lit(v == "true")
        if k == "kw" and v == "null":
            return Lit(None)
        if k == "kw" and v == "cast":
            if not self.accept_op("("):
                raise SQLError("CAST needs (")
            e = self.expr()
            self.expect_kw("as")
            k2, typ = self.next()
            if not self.accept_op(")"):
                raise SQLError("unclosed CAST")
            return Func("cast_" + typ.lower(), [e])
        if k == "op" and v == "(":
            e = self.expr()
            if not self.accept_op(")"):
                raise SQLError("unclosed (")
            return e
        if k == "ident":
            name = v
            if self.accept_op("("):
                fname = name.lower()
                if fname == "count" and self.accept_op("*"):
                    if not self.accept_op(")"):
                        raise SQLError("unclosed COUNT(*)")
                    return Agg("count", None)
                if fname == "substring":
                    return self._substring()
                if fname == "extract":
                    return self._extract()
                if fname == "trim":
                    return self._trim()
                if fname in ("date_add", "date_diff"):
                    return self._date_fn(fname)
                args = []
                if not self.accept_op(")"):
                    while True:
                        args.append(self.expr())
                        if not self.accept_op(","):
                            break
                    if not self.accept_op(")"):
                        raise SQLError("unclosed function call")
                if fname in _AGG_FUNCS:
                    if len(args) != 1:
                        raise SQLError(f"{fname} takes one argument")
                    return Agg(fname, args[0])
                if fname in _SCALAR_FUNCS:
                    return Func(fname, args)
                raise SQLError(f"unknown function {name}")
            # alias.column / column / _N
            if self.accept_op("."):
                k2, v2 = self.next()
                if k2 not in ("ident", "number"):
                    raise SQLError("bad column reference")
                return Col(str(v2))
            return Col(name)
        raise SQLError(f"unexpected token {v!r}")


    # -- special function forms (reference funceval.go grammar) ------------

    def _accept_ident(self, *names: str) -> Optional[str]:
        k, v = self.peek()
        if k == "ident" and v.lower() in names:
            self.next()
            return v.lower()
        return None

    def _close(self, what: str) -> None:
        if not self.accept_op(")"):
            raise SQLError(f"unclosed {what}")

    def _substring(self) -> Node:
        """SUBSTRING(s FROM start [FOR len]) | SUBSTRING(s, start
        [, len]) — both forms, like the reference
        (funceval.go:281)."""
        s = self.expr()
        args = [s]
        if self.accept_kw("from"):
            args.append(self.additive())
            if self._accept_ident("for"):
                args.append(self.additive())
        else:
            if not self.accept_op(","):
                raise SQLError("SUBSTRING needs FROM or ','")
            args.append(self.additive())
            if self.accept_op(","):
                args.append(self.additive())
        self._close("SUBSTRING")
        return Func("substring", args)

    def _extract(self) -> Node:
        """EXTRACT(part FROM timestamp)."""
        k, part = self.next()
        if k != "ident" or part.lower() not in _DATE_PARTS:
            raise SQLError(f"bad EXTRACT part {part!r}")
        self.expect_kw("from")
        e = self.expr()
        self._close("EXTRACT")
        return Func(f"extract_{part.lower()}", [e])

    def _trim(self) -> Node:
        """TRIM([[LEADING|TRAILING|BOTH] [chars] FROM] s)."""
        where = self._accept_ident("leading", "trailing", "both")
        if self.accept_kw("from"):              # TRIM(LEADING FROM s)
            e = self.expr()
            self._close("TRIM")
            return Func("trim_full",
                        [Lit(where or "both"), Lit(None), e])
        first = self.expr()
        if self.accept_kw("from"):              # TRIM([pos] chars FROM s)
            e = self.expr()
            self._close("TRIM")
            return Func("trim_full",
                        [Lit(where or "both"), first, e])
        if where is not None:
            raise SQLError("TRIM with position needs FROM")
        self._close("TRIM")
        return Func("trim", [first])

    def _date_fn(self, fname: str) -> Node:
        """DATE_ADD(part, qty, ts) / DATE_DIFF(part, ts1, ts2)."""
        k, part = self.next()
        if k != "ident" or part.lower() not in _DATE_PARTS:
            raise SQLError(f"bad {fname.upper()} date part {part!r}")
        if not self.accept_op(","):
            raise SQLError(f"{fname.upper()} needs 3 arguments")
        a = self.expr()
        if not self.accept_op(","):
            raise SQLError(f"{fname.upper()} needs 3 arguments")
        b = self.expr()
        self._close(fname.upper())
        return Func(f"{fname}_{part.lower()}", [a, b])


def _like_regex(pat: str, esc: str) -> "re.Pattern":
    out = []
    i = 0
    while i < len(pat):
        c = pat[i]
        if esc and c == esc and i + 1 < len(pat):
            out.append(re.escape(pat[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


def parse(sql: str) -> Query:
    return Parser(tokenize(sql)).parse()


# -- evaluation -------------------------------------------------------------

def _num(v) -> Optional[float]:
    if isinstance(v, bool) or v is None:
        return None
    if isinstance(v, (int, float)):
        return float(v)
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def _aware(t: _dt.datetime) -> _dt.datetime:
    """Naive datetimes (e.g. pyarrow timestamps without a zone) compare
    as UTC instants — mixing naive and aware must never TypeError."""
    return t.replace(tzinfo=_dt.timezone.utc) if t.tzinfo is None \
        else t


def _try_ts(v) -> Optional[_dt.datetime]:
    if isinstance(v, _dt.datetime):
        return _aware(v)
    if isinstance(v, str):
        try:
            return parse_sql_timestamp(v)
        except SQLError:
            return None
    return None


def _coerce_pair(a, b):
    """Numeric comparison when both sides look numeric; a datetime on
    either side compares as an INSTANT (the other side is parsed as a
    SQL timestamp — '...T10:30Z' equals '...T12:30+02:00'); else
    string."""
    if isinstance(a, _dt.datetime) or isinstance(b, _dt.datetime):
        ta, tb = _try_ts(a), _try_ts(b)
        if ta is not None and tb is not None:
            return ta, tb
        if isinstance(a, _dt.datetime):
            a = format_sql_timestamp(_aware(a))
        if isinstance(b, _dt.datetime):
            b = format_sql_timestamp(_aware(b))
    na, nb = _num(a), _num(b)
    if na is not None and nb is not None:
        return na, nb
    if a is None or b is None:
        return a, b
    return str(a), str(b)


def evaluate(node: Node, row: dict, alias: str) -> Any:
    if isinstance(node, Lit):
        return node.v
    if isinstance(node, Col):
        name = node.name
        if name.lower() == alias:
            return row
        if name in row:
            return row[name]
        # case-insensitive fallback + positional _N
        low = name.lower()
        for k, v in row.items():
            if k.lower() == low:
                return v
        if low.startswith("_") and low[1:].isdigit():
            idx = int(low[1:]) - 1
            vals = list(row.values())
            return vals[idx] if 0 <= idx < len(vals) else None
        return None
    if isinstance(node, Unary):
        v = evaluate(node.x, row, alias)
        if node.op == "not":
            return not _truthy(v)
        n = _num(v)
        return -n if n is not None else None
    if isinstance(node, Bin):
        if node.op == "and":
            return _truthy(evaluate(node.a, row, alias)) and \
                _truthy(evaluate(node.b, row, alias))
        if node.op == "or":
            return _truthy(evaluate(node.a, row, alias)) or \
                _truthy(evaluate(node.b, row, alias))
        a = evaluate(node.a, row, alias)
        b = evaluate(node.b, row, alias)
        if node.op in ("+", "-", "*", "/", "%"):
            na, nb = _num(a), _num(b)
            if na is None or nb is None:
                return None
            try:
                if node.op == "+":
                    r = na + nb
                elif node.op == "-":
                    r = na - nb
                elif node.op == "*":
                    r = na * nb
                elif node.op == "/":
                    r = na / nb
                else:
                    r = na % nb
            except ZeroDivisionError:
                return None
            return int(r) if float(r).is_integer() else r
        a, b = _coerce_pair(a, b)
        if a is None or b is None:
            return False
        if node.op == "=":
            return a == b
        if node.op in ("!=", "<>"):
            return a != b
        if node.op == "<":
            return a < b
        if node.op == "<=":
            return a <= b
        if node.op == ">":
            return a > b
        if node.op == ">=":
            return a >= b
    if isinstance(node, Like):
        v = evaluate(node.x, row, alias)
        ok = v is not None and bool(node.pat.match(str(v)))
        return ok != node.negate
    if isinstance(node, In):
        v = evaluate(node.x, row, alias)
        hit = False
        for item in node.items:
            a, b = _coerce_pair(v, evaluate(item, row, alias))
            if a is not None and a == b:
                hit = True
                break
        return hit != node.negate
    if isinstance(node, Between):
        v = evaluate(node.x, row, alias)
        lo = evaluate(node.lo, row, alias)
        hi = evaluate(node.hi, row, alias)
        a, l2 = _coerce_pair(v, lo)
        a2, h2 = _coerce_pair(v, hi)
        ok = (a is not None and l2 is not None and h2 is not None
              and l2 <= a and a2 <= h2)
        return ok != node.negate
    if isinstance(node, IsNull):
        v = evaluate(node.x, row, alias)
        return (v is None) != node.negate
    if isinstance(node, Func):
        args = [evaluate(a, row, alias) for a in node.args]
        return _scalar_fn(node.name, args)
    if isinstance(node, Agg):
        raise SQLError("aggregate in row context")
    raise SQLError(f"cannot evaluate {node!r}")


def _truthy(v) -> bool:
    return bool(v) and v is not None


def _as_timestamp(v) -> _dt.datetime:
    if isinstance(v, _dt.datetime):
        return v
    if isinstance(v, str):
        return parse_sql_timestamp(v)
    raise SQLError(f"expected a timestamp, got {v!r}")


def _add_months(t: _dt.datetime, months: int) -> _dt.datetime:
    """Month arithmetic with Go time.AddDate's overflow semantics
    (Jan 31 + 1 month normalizes into March, not clamps to Feb 28)."""
    total = (t.year * 12 + t.month - 1) + months
    y, m = divmod(total, 12)
    base = _dt.datetime(y, m + 1, 1, t.hour, t.minute, t.second,
                        t.microsecond, tzinfo=t.tzinfo)
    return base + _dt.timedelta(days=t.day - 1)


def _date_diff(part: str, t1: _dt.datetime, t2: _dt.datetime) -> int:
    """Reference dateDiff (timestampfuncs.go:146): years/months/days
    compare calendar fields; hours/minutes/seconds compare the exact
    duration."""
    if t2 < t1:
        return -_date_diff(part, t2, t1)
    if part == "year":
        dy = t2.year - t1.year
        if (t2.month, t2.day) >= (t1.month, t1.day):
            return dy
        return dy - 1
    if part == "month":
        months = 12 * (t2.year - t1.year)
        if t2.month >= t1.month:
            months += t2.month - t1.month
        else:
            months += 12 + t2.month - t1.month
        if t2.day < t1.day:
            months -= 1
        return months
    if part == "day":
        return (t2.date() - t1.date()).days
    secs = (t2 - t1).total_seconds()
    if part == "hour":
        return int(secs // 3600)
    if part == "minute":
        return int(secs // 60)
    if part == "second":
        return int(secs)
    raise SQLError(f"DATE_DIFF does not support {part.upper()}")


_TO_STRING_RX = re.compile(
    r"yyyy|yy|y|MMMM|MMM|MM|M|dd|d|HH|H|hh|h|mm|m|ss|s|SSS|a|XXX|X"
    r"|'(?:[^']|'')*'|.")

_MONTHS = ["January", "February", "March", "April", "May", "June",
           "July", "August", "September", "October", "November",
           "December"]


def _to_string(t: _dt.datetime, fmt: str) -> str:
    """TO_STRING(ts, pattern) with the Ion/java-style tokens the S3
    Select docs describe (y/M/d/H/h/m/s/a/X, quoted literals). The
    reference leaves TO_STRING unimplemented (funceval.go:140) — this
    implements the documented surface."""
    def off_str(colon: bool) -> str:
        off = t.utcoffset() or _dt.timedelta(0)
        if not off:
            return "Z"
        total = int(off.total_seconds())
        sign = "+" if total >= 0 else "-"
        total = abs(total)
        sep = ":" if colon else ""
        return f"{sign}{total // 3600:02d}{sep}{total % 3600 // 60:02d}"

    out = []
    for tok in _TO_STRING_RX.findall(fmt):
        if tok == "yyyy":
            out.append(f"{t.year:04d}")
        elif tok == "yy":
            out.append(f"{t.year % 100:02d}")
        elif tok == "y":
            out.append(str(t.year))
        elif tok == "MMMM":
            out.append(_MONTHS[t.month - 1])
        elif tok == "MMM":
            out.append(_MONTHS[t.month - 1][:3])
        elif tok == "MM":
            out.append(f"{t.month:02d}")
        elif tok == "M":
            out.append(str(t.month))
        elif tok == "dd":
            out.append(f"{t.day:02d}")
        elif tok == "d":
            out.append(str(t.day))
        elif tok == "HH":
            out.append(f"{t.hour:02d}")
        elif tok == "H":
            out.append(str(t.hour))
        elif tok in ("hh", "h"):
            h12 = t.hour % 12 or 12
            out.append(f"{h12:02d}" if tok == "hh" else str(h12))
        elif tok == "mm":
            out.append(f"{t.minute:02d}")
        elif tok == "m":
            out.append(str(t.minute))
        elif tok == "ss":
            out.append(f"{t.second:02d}")
        elif tok == "s":
            out.append(str(t.second))
        elif tok == "SSS":
            out.append(f"{t.microsecond // 1000:03d}")
        elif tok == "a":
            out.append("AM" if t.hour < 12 else "PM")
        elif tok == "XXX":
            out.append(off_str(True))
        elif tok == "X":
            out.append(off_str(False))
        elif tok.startswith("'"):
            out.append(tok[1:-1].replace("''", "'"))
        else:
            out.append(tok)
    return "".join(out)


def _scalar_fn(name: str, args: list):
    a = args[0] if args else None
    if name == "lower":
        return str(a).lower() if a is not None else None
    if name == "upper":
        return str(a).upper() if a is not None else None
    if name in ("length", "char_length", "character_length"):
        return len(str(a)) if a is not None else None
    if name == "trim":
        return str(a).strip() if a is not None else None
    if name == "trim_full":
        where, chars, s = args
        if s is None:
            return None
        s = str(s)
        cutset = str(chars) if chars is not None else " "
        if where == "leading":
            return s.lstrip(cutset)
        if where == "trailing":
            return s.rstrip(cutset)
        return s.strip(cutset)
    if name == "abs":
        n = _num(a)
        return abs(n) if n is not None else None
    if name == "substring":
        # reference evalSQLSubstring (stringfuncs.go:144): 1-based,
        # start < 1 clamps to 1, start past the end yields "", a
        # negative length errors, an oversized one clamps
        if a is None:
            return None
        s = str(a)
        try:
            start = int(_num(args[1]))
        except (TypeError, ValueError):
            raise SQLError("SUBSTRING start must be a number") from None
        length = None
        if len(args) > 2:
            try:
                length = int(_num(args[2]))
            except (TypeError, ValueError):
                raise SQLError(
                    "SUBSTRING length must be a number") from None
            if length < 0:
                raise SQLError("negative SUBSTRING length")
        start = max(start, 1)
        if start > len(s):
            return ""
        i = start - 1
        return s[i:] if length is None else s[i:i + length]
    if name == "coalesce":
        for v in args:
            if v is not None:
                return v
        return None
    if name == "nullif":
        v1, v2 = (args + [None, None])[:2]
        if v1 is None or v2 is None:
            return v1
        a2, b2 = _coerce_pair(v1, v2)
        return None if a2 == b2 else v1
    if name == "utcnow":
        if args:
            raise SQLError("UTCNOW takes no arguments")
        return _dt.datetime.now(_dt.timezone.utc)
    if name == "to_timestamp":
        return None if a is None else _as_timestamp(a)
    if name == "to_string":
        if a is None:
            return None
        if len(args) != 2 or not isinstance(args[1], str):
            raise SQLError("TO_STRING(ts, 'pattern')")
        return _to_string(_as_timestamp(a), args[1])
    if name.startswith("extract_"):
        part = name[len("extract_"):]
        if a is None:
            return None
        t = _as_timestamp(a)
        if part in ("timezone_hour", "timezone_minute"):
            # Go's / and % truncate toward zero: -05:30 extracts
            # hour -5, minute -30 (timestampfuncs.go:105-110)
            total = int((t.utcoffset()
                         or _dt.timedelta(0)).total_seconds())
            hours = int(total / 3600)
            if part == "timezone_hour":
                return hours
            return int((total - hours * 3600) / 60)
        return getattr(t, part)
    if name.startswith("date_add_"):
        part = name[len("date_add_"):]
        qty_v, ts_v = args
        qty = _num(qty_v)
        if qty is None:
            raise SQLError("DATE_ADD quantity must be a number")
        t = _as_timestamp(ts_v)
        qty = int(qty)
        if part == "year":
            return _add_months(t, 12 * qty)
        if part == "month":
            return _add_months(t, qty)
        if part == "day":
            return t + _dt.timedelta(days=qty)
        if part == "hour":
            return t + _dt.timedelta(hours=qty)
        if part == "minute":
            return t + _dt.timedelta(minutes=qty)
        if part == "second":
            return t + _dt.timedelta(seconds=qty)
        raise SQLError(f"DATE_ADD does not support {part.upper()}")
    if name.startswith("date_diff_"):
        part = name[len("date_diff_"):]
        return _date_diff(part, _as_timestamp(args[0]),
                          _as_timestamp(args[1]))
    if name.startswith("cast_"):
        typ = name[5:]
        if a is None:
            return None
        if typ in ("int", "integer"):
            try:
                return int(float(a))
            except (TypeError, ValueError):
                raise SQLError(f"cannot cast {a!r} to int") from None
        if typ in ("float", "double", "decimal", "numeric"):
            n = _num(a)
            if n is None:
                raise SQLError(f"cannot cast {a!r} to float")
            return n
        if typ in ("string", "varchar", "char", "text"):
            if isinstance(a, _dt.datetime):
                return format_sql_timestamp(a)
            return str(a)
        if typ in ("bool", "boolean"):
            return str(a).lower() in ("true", "1")
        if typ == "timestamp":
            return _as_timestamp(a)
        raise SQLError(f"unknown cast type {typ}")
    raise SQLError(f"unknown function {name}")


class Aggregator:
    """Accumulates aggregate projections over the row stream."""

    def __init__(self, query: Query):
        self.q = query
        self.state = []
        for e, _ in query.projections:
            if isinstance(e, Agg):
                self.state.append({"n": 0, "sum": 0.0, "min": None,
                                   "max": None})
            else:
                self.state.append(None)

    def feed(self, row: dict) -> None:
        for (e, _), st in zip(self.q.projections, self.state):
            if not isinstance(e, Agg):
                continue
            if e.arg is None:                  # COUNT(*)
                st["n"] += 1
                continue
            v = evaluate(e.arg, row, self.q.alias)
            if v is None:
                continue
            st["n"] += 1
            if e.name == "count":
                # COUNT needs no min/max/sum — tracking them over a
                # mixed numeric/string column raised TypeError below
                continue
            if isinstance(v, _dt.datetime):
                v = _aware(v)       # MIN/MAX over mixed-zone rows
            n = _num(v)
            if n is not None:
                st["sum"] += n
            cur = v if n is None else n
            if st["min"] is None or cur < st["min"]:
                st["min"] = cur
            if st["max"] is None or cur > st["max"]:
                st["max"] = cur

    def result(self) -> dict:
        out = {}
        for i, ((e, alias), st) in enumerate(
                zip(self.q.projections, self.state)):
            name = alias or f"_{i + 1}"
            if not isinstance(e, Agg):
                out[name] = None
                continue
            if e.name == "count":
                v = st["n"]
            elif e.name == "sum":
                v = st["sum"] if st["n"] else None
            elif e.name == "avg":
                v = st["sum"] / st["n"] if st["n"] else None
            elif e.name == "min":
                v = st["min"]
            else:
                v = st["max"]
            if isinstance(v, float) and v.is_integer():
                v = int(v)
            out[name] = v
        return out
