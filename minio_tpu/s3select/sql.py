"""SQL subset for S3 Select (reference pkg/s3select/sql — hand-written
parser + evaluator).

Grammar:
    SELECT projection FROM table [WHERE expr] [LIMIT n]
    projection := * | expr [AS name] ("," expr [AS name])*
    table      := S3Object[.path] [[AS] alias]
    expr       := OR-chains of AND-chains of comparisons over terms
    comparison := term (=|!=|<>|<|<=|>|>=) term | term [NOT] LIKE str
                  | term [NOT] IN (lit, ...) | term [NOT] BETWEEN a AND b
                  | term IS [NOT] NULL
    term       := literal | column | alias.column | _N | -term
                  | term (+|-|*|/|%) term | (expr)
                  | COUNT(*) | SUM/AVG/MIN/MAX/COUNT(expr)
                  | LOWER/UPPER/LENGTH/TRIM(expr) | CAST(expr AS type)

Values are Python str/float/int/bool/None; comparisons coerce numerics
like the reference's typed values.
"""

from __future__ import annotations

import re
from typing import Any, Optional


class SQLError(Exception):
    pass


_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<number>\d+(\.\d*)?([eE][+-]?\d+)?|\.\d+)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><>|<=|>=|!=|=|<|>|\(|\)|,|\*|/|\+|-|%|\.)
""", re.VERBOSE)

_KEYWORDS = {
    "select", "from", "where", "limit", "as", "and", "or", "not", "like",
    "in", "between", "is", "null", "true", "false", "escape", "cast",
}


def tokenize(src: str) -> list[tuple[str, str]]:
    out = []
    i = 0
    while i < len(src):
        m = _TOKEN_RE.match(src, i)
        if not m:
            raise SQLError(f"bad character {src[i]!r} at {i}")
        i = m.end()
        kind = m.lastgroup
        text = m.group()
        if kind == "ws":
            continue
        if kind == "ident" and text.lower() in _KEYWORDS:
            out.append(("kw", text.lower()))
        elif kind == "string":
            out.append(("str", text[1:-1].replace("''", "'")))
        elif kind == "qident":
            out.append(("ident", text[1:-1].replace('""', '"')))
        else:
            out.append((kind, text))
    out.append(("eof", ""))
    return out


# -- AST --------------------------------------------------------------------

class Node:
    pass


class Lit(Node):
    def __init__(self, v):
        self.v = v


class Col(Node):
    def __init__(self, name: str):
        self.name = name


class Unary(Node):
    def __init__(self, op, x):
        self.op, self.x = op, x


class Bin(Node):
    def __init__(self, op, a, b):
        self.op, self.a, self.b = op, a, b


class Like(Node):
    def __init__(self, x, pat, negate):
        self.x, self.pat, self.negate = x, pat, negate


class In(Node):
    def __init__(self, x, items, negate):
        self.x, self.items, self.negate = x, items, negate


class Between(Node):
    def __init__(self, x, lo, hi, negate):
        self.x, self.lo, self.hi, self.negate = x, lo, hi, negate


class IsNull(Node):
    def __init__(self, x, negate):
        self.x, self.negate = x, negate


class Func(Node):
    def __init__(self, name, args):
        self.name, self.args = name, args


class Agg(Node):
    def __init__(self, name, arg):
        self.name, self.arg = name, arg   # arg None = COUNT(*)


class Query:
    def __init__(self):
        self.projections: list[tuple[Node, Optional[str]]] = []
        self.star = False
        self.alias = "s3object"
        self.where: Optional[Node] = None
        self.limit: Optional[int] = None

    @property
    def is_aggregate(self) -> bool:
        return any(isinstance(e, Agg) for e, _ in self.projections)


_AGG_FUNCS = {"count", "sum", "avg", "min", "max"}
_SCALAR_FUNCS = {"lower", "upper", "length", "char_length",
                 "character_length", "trim", "abs"}


class Parser:
    def __init__(self, tokens):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect_kw(self, kw):
        k, v = self.next()
        if k != "kw" or v != kw:
            raise SQLError(f"expected {kw.upper()}, got {v!r}")

    def accept_kw(self, kw) -> bool:
        k, v = self.peek()
        if k == "kw" and v == kw:
            self.i += 1
            return True
        return False

    def accept_op(self, op) -> bool:
        k, v = self.peek()
        if k == "op" and v == op:
            self.i += 1
            return True
        return False

    # -- grammar ----------------------------------------------------------

    def parse(self) -> Query:
        q = Query()
        self.expect_kw("select")
        if self.accept_op("*"):
            q.star = True
        else:
            while True:
                e = self.expr()
                alias = None
                if self.accept_kw("as"):
                    k, v = self.next()
                    if k not in ("ident", "str"):
                        raise SQLError("bad alias")
                    alias = v
                q.projections.append((e, alias))
                if not self.accept_op(","):
                    break
        self.expect_kw("from")
        k, v = self.next()
        if k != "ident" or v.lower() not in ("s3object", "s3objects"):
            raise SQLError(f"FROM must be S3Object, got {v!r}")
        while self.accept_op("."):
            self.next()                      # S3Object.path: ignored
        k, v = self.peek()
        if k == "ident":
            q.alias = v.lower()
            self.next()
        elif self.accept_kw("as"):
            k, v = self.next()
            q.alias = v.lower()
        if self.accept_kw("where"):
            q.where = self.expr()
        if self.accept_kw("limit"):
            k, v = self.next()
            if k != "number":
                raise SQLError("LIMIT needs a number")
            q.limit = int(float(v))
        k, v = self.peek()
        if k != "eof":
            raise SQLError(f"unexpected trailing {v!r}")
        return q

    def expr(self) -> Node:
        return self.or_expr()

    def or_expr(self) -> Node:
        left = self.and_expr()
        while self.accept_kw("or"):
            left = Bin("or", left, self.and_expr())
        return left

    def and_expr(self) -> Node:
        left = self.not_expr()
        while self.accept_kw("and"):
            left = Bin("and", left, self.not_expr())
        return left

    def not_expr(self) -> Node:
        if self.accept_kw("not"):
            return Unary("not", self.not_expr())
        return self.comparison()

    def comparison(self) -> Node:
        left = self.additive()
        negate = self.accept_kw("not")
        k, v = self.peek()
        if k == "op" and v in ("=", "!=", "<>", "<", "<=", ">", ">="):
            if negate:
                raise SQLError("NOT before comparison operator")
            self.next()
            return Bin(v, left, self.additive())
        if self.accept_kw("like"):
            k, pat = self.next()
            if k != "str":
                raise SQLError("LIKE needs a string pattern")
            esc = ""
            if self.accept_kw("escape"):
                k2, esc = self.next()
                if k2 != "str" or len(esc) != 1:
                    raise SQLError("ESCAPE needs a 1-char string")
            return Like(left, _like_regex(pat, esc), negate)
        if self.accept_kw("in"):
            if not self.accept_op("("):
                raise SQLError("IN needs a list")
            items = []
            while True:
                items.append(self.additive())
                if not self.accept_op(","):
                    break
            if not self.accept_op(")"):
                raise SQLError("unclosed IN list")
            return In(left, items, negate)
        if self.accept_kw("between"):
            lo = self.additive()
            self.expect_kw("and")
            hi = self.additive()
            return Between(left, lo, hi, negate)
        if self.accept_kw("is"):
            neg = self.accept_kw("not")
            self.expect_kw("null")
            return IsNull(left, neg)
        if negate:
            raise SQLError("dangling NOT")
        return left

    def additive(self) -> Node:
        left = self.multiplicative()
        while True:
            k, v = self.peek()
            if k == "op" and v in ("+", "-"):
                self.next()
                left = Bin(v, left, self.multiplicative())
            else:
                return left

    def multiplicative(self) -> Node:
        left = self.unary()
        while True:
            k, v = self.peek()
            if k == "op" and v in ("*", "/", "%"):
                self.next()
                left = Bin(v, left, self.unary())
            else:
                return left

    def unary(self) -> Node:
        if self.accept_op("-"):
            return Unary("neg", self.unary())
        if self.accept_op("+"):
            return self.unary()
        return self.primary()

    def primary(self) -> Node:
        k, v = self.next()
        if k == "number":
            f = float(v)
            return Lit(int(f) if f.is_integer() and "." not in v
                       and "e" not in v.lower() else f)
        if k == "str":
            return Lit(v)
        if k == "kw" and v in ("true", "false"):
            return Lit(v == "true")
        if k == "kw" and v == "null":
            return Lit(None)
        if k == "kw" and v == "cast":
            if not self.accept_op("("):
                raise SQLError("CAST needs (")
            e = self.expr()
            self.expect_kw("as")
            k2, typ = self.next()
            if not self.accept_op(")"):
                raise SQLError("unclosed CAST")
            return Func("cast_" + typ.lower(), [e])
        if k == "op" and v == "(":
            e = self.expr()
            if not self.accept_op(")"):
                raise SQLError("unclosed (")
            return e
        if k == "ident":
            name = v
            if self.accept_op("("):
                fname = name.lower()
                if fname == "count" and self.accept_op("*"):
                    if not self.accept_op(")"):
                        raise SQLError("unclosed COUNT(*)")
                    return Agg("count", None)
                args = []
                if not self.accept_op(")"):
                    while True:
                        args.append(self.expr())
                        if not self.accept_op(","):
                            break
                    if not self.accept_op(")"):
                        raise SQLError("unclosed function call")
                if fname in _AGG_FUNCS:
                    if len(args) != 1:
                        raise SQLError(f"{fname} takes one argument")
                    return Agg(fname, args[0])
                if fname in _SCALAR_FUNCS:
                    return Func(fname, args)
                raise SQLError(f"unknown function {name}")
            # alias.column / column / _N
            if self.accept_op("."):
                k2, v2 = self.next()
                if k2 not in ("ident", "number"):
                    raise SQLError("bad column reference")
                return Col(str(v2))
            return Col(name)
        raise SQLError(f"unexpected token {v!r}")


def _like_regex(pat: str, esc: str) -> "re.Pattern":
    out = []
    i = 0
    while i < len(pat):
        c = pat[i]
        if esc and c == esc and i + 1 < len(pat):
            out.append(re.escape(pat[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


def parse(sql: str) -> Query:
    return Parser(tokenize(sql)).parse()


# -- evaluation -------------------------------------------------------------

def _num(v) -> Optional[float]:
    if isinstance(v, bool) or v is None:
        return None
    if isinstance(v, (int, float)):
        return float(v)
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def _coerce_pair(a, b):
    """Numeric comparison when both sides look numeric, else string."""
    na, nb = _num(a), _num(b)
    if na is not None and nb is not None:
        return na, nb
    if a is None or b is None:
        return a, b
    return str(a), str(b)


def evaluate(node: Node, row: dict, alias: str) -> Any:
    if isinstance(node, Lit):
        return node.v
    if isinstance(node, Col):
        name = node.name
        if name.lower() == alias:
            return row
        if name in row:
            return row[name]
        # case-insensitive fallback + positional _N
        low = name.lower()
        for k, v in row.items():
            if k.lower() == low:
                return v
        if low.startswith("_") and low[1:].isdigit():
            idx = int(low[1:]) - 1
            vals = list(row.values())
            return vals[idx] if 0 <= idx < len(vals) else None
        return None
    if isinstance(node, Unary):
        v = evaluate(node.x, row, alias)
        if node.op == "not":
            return not _truthy(v)
        n = _num(v)
        return -n if n is not None else None
    if isinstance(node, Bin):
        if node.op == "and":
            return _truthy(evaluate(node.a, row, alias)) and \
                _truthy(evaluate(node.b, row, alias))
        if node.op == "or":
            return _truthy(evaluate(node.a, row, alias)) or \
                _truthy(evaluate(node.b, row, alias))
        a = evaluate(node.a, row, alias)
        b = evaluate(node.b, row, alias)
        if node.op in ("+", "-", "*", "/", "%"):
            na, nb = _num(a), _num(b)
            if na is None or nb is None:
                return None
            try:
                if node.op == "+":
                    r = na + nb
                elif node.op == "-":
                    r = na - nb
                elif node.op == "*":
                    r = na * nb
                elif node.op == "/":
                    r = na / nb
                else:
                    r = na % nb
            except ZeroDivisionError:
                return None
            return int(r) if float(r).is_integer() else r
        a, b = _coerce_pair(a, b)
        if a is None or b is None:
            return False
        if node.op == "=":
            return a == b
        if node.op in ("!=", "<>"):
            return a != b
        if node.op == "<":
            return a < b
        if node.op == "<=":
            return a <= b
        if node.op == ">":
            return a > b
        if node.op == ">=":
            return a >= b
    if isinstance(node, Like):
        v = evaluate(node.x, row, alias)
        ok = v is not None and bool(node.pat.match(str(v)))
        return ok != node.negate
    if isinstance(node, In):
        v = evaluate(node.x, row, alias)
        hit = False
        for item in node.items:
            a, b = _coerce_pair(v, evaluate(item, row, alias))
            if a is not None and a == b:
                hit = True
                break
        return hit != node.negate
    if isinstance(node, Between):
        v = evaluate(node.x, row, alias)
        lo = evaluate(node.lo, row, alias)
        hi = evaluate(node.hi, row, alias)
        a, l2 = _coerce_pair(v, lo)
        a2, h2 = _coerce_pair(v, hi)
        ok = (a is not None and l2 is not None and h2 is not None
              and l2 <= a and a2 <= h2)
        return ok != node.negate
    if isinstance(node, IsNull):
        v = evaluate(node.x, row, alias)
        return (v is None) != node.negate
    if isinstance(node, Func):
        args = [evaluate(a, row, alias) for a in node.args]
        return _scalar_fn(node.name, args)
    if isinstance(node, Agg):
        raise SQLError("aggregate in row context")
    raise SQLError(f"cannot evaluate {node!r}")


def _truthy(v) -> bool:
    return bool(v) and v is not None


def _scalar_fn(name: str, args: list):
    a = args[0] if args else None
    if name == "lower":
        return str(a).lower() if a is not None else None
    if name == "upper":
        return str(a).upper() if a is not None else None
    if name in ("length", "char_length", "character_length"):
        return len(str(a)) if a is not None else None
    if name == "trim":
        return str(a).strip() if a is not None else None
    if name == "abs":
        n = _num(a)
        return abs(n) if n is not None else None
    if name.startswith("cast_"):
        typ = name[5:]
        if a is None:
            return None
        if typ in ("int", "integer"):
            try:
                return int(float(a))
            except (TypeError, ValueError):
                raise SQLError(f"cannot cast {a!r} to int") from None
        if typ in ("float", "double", "decimal", "numeric"):
            n = _num(a)
            if n is None:
                raise SQLError(f"cannot cast {a!r} to float")
            return n
        if typ in ("string", "varchar", "char", "text"):
            return str(a)
        if typ in ("bool", "boolean"):
            return str(a).lower() in ("true", "1")
        raise SQLError(f"unknown cast type {typ}")
    raise SQLError(f"unknown function {name}")


class Aggregator:
    """Accumulates aggregate projections over the row stream."""

    def __init__(self, query: Query):
        self.q = query
        self.state = []
        for e, _ in query.projections:
            if isinstance(e, Agg):
                self.state.append({"n": 0, "sum": 0.0, "min": None,
                                   "max": None})
            else:
                self.state.append(None)

    def feed(self, row: dict) -> None:
        for (e, _), st in zip(self.q.projections, self.state):
            if not isinstance(e, Agg):
                continue
            if e.arg is None:                  # COUNT(*)
                st["n"] += 1
                continue
            v = evaluate(e.arg, row, self.q.alias)
            if v is None:
                continue
            st["n"] += 1
            n = _num(v)
            if n is not None:
                st["sum"] += n
            cur = v if n is None else n
            if st["min"] is None or cur < st["min"]:
                st["min"] = cur
            if st["max"] is None or cur > st["max"]:
                st["max"] = cur

    def result(self) -> dict:
        out = {}
        for i, ((e, alias), st) in enumerate(
                zip(self.q.projections, self.state)):
            name = alias or f"_{i + 1}"
            if not isinstance(e, Agg):
                out[name] = None
                continue
            if e.name == "count":
                v = st["n"]
            elif e.name == "sum":
                v = st["sum"] if st["n"] else None
            elif e.name == "avg":
                v = st["sum"] / st["n"] if st["n"] else None
            elif e.name == "min":
                v = st["min"]
            else:
                v = st["max"]
            if isinstance(v, float) and v.is_integer():
                v = int(v)
            out[name] = v
        return out
