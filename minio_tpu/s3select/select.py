"""S3 Select execution: request parsing, CSV/JSON readers, output
serialization, and the AWS event-stream response framing
(reference pkg/s3select/{select.go,csv,json,message.go})."""

from __future__ import annotations

import bz2
import csv as _csv
import datetime as _dt
import gzip
import io
import json
import struct
import xml.etree.ElementTree as ET
import zlib
from typing import Iterator, Optional

from .sql import (Aggregator, Query, SQLError, evaluate,
                  format_sql_timestamp, parse)

_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


def _find(el, tag):
    r = el.find(tag)
    if r is None:
        r = el.find(_NS + tag)
    return r


def _text(el, tag, default=""):
    r = _find(el, tag)
    return (r.text or "") if r is not None and r.text is not None \
        else default


class SelectRequest:
    """Parsed SelectObjectContent XML body."""

    def __init__(self):
        self.expression = ""
        self.input_format = "CSV"          # CSV | JSON
        self.compression = "NONE"          # NONE | GZIP | BZIP2
        self.csv_header = "NONE"           # NONE | USE | IGNORE
        self.csv_delim = ","
        self.csv_quote = '"'
        self.json_type = "LINES"           # LINES | DOCUMENT
        self.output_format = "CSV"
        self.out_delim = ","
        self.out_quote = '"'
        self.out_record_delim = "\n"

    @classmethod
    def from_xml(cls, raw: bytes) -> "SelectRequest":
        from ..s3.s3errors import S3Error
        try:
            root = ET.fromstring(raw)
        except ET.ParseError as e:
            raise S3Error("MalformedXML", str(e)) from None
        r = cls()
        r.expression = _text(root, "Expression").strip()
        if _text(root, "ExpressionType", "SQL").upper() != "SQL":
            raise S3Error("InvalidArgument", "ExpressionType must be SQL")
        inp = _find(root, "InputSerialization")
        if inp is not None:
            r.compression = (_text(inp, "CompressionType", "NONE")
                             or "NONE").upper()
            csv_el = _find(inp, "CSV")
            json_el = _find(inp, "JSON")
            if json_el is not None:
                r.input_format = "JSON"
                r.json_type = (_text(json_el, "Type", "LINES")
                               or "LINES").upper()
            elif csv_el is not None:
                r.input_format = "CSV"
                r.csv_header = (_text(csv_el, "FileHeaderInfo", "NONE")
                                or "NONE").upper()
                r.csv_delim = _text(csv_el, "FieldDelimiter", ",") or ","
                r.csv_quote = _text(csv_el, "QuoteCharacter", '"') or '"'
            elif _find(inp, "Parquet") is not None:
                r.input_format = "PARQUET"
                r.compression = "NONE"   # parquet is self-compressed
        out = _find(root, "OutputSerialization")
        if out is not None:
            if _find(out, "JSON") is not None:
                r.output_format = "JSON"
                jr = _find(out, "JSON")
                r.out_record_delim = _text(jr, "RecordDelimiter",
                                           "\n") or "\n"
            elif _find(out, "CSV") is not None:
                r.output_format = "CSV"
                co = _find(out, "CSV")
                r.out_delim = _text(co, "FieldDelimiter", ",") or ","
                r.out_quote = _text(co, "QuoteCharacter", '"') or '"'
                r.out_record_delim = _text(co, "RecordDelimiter",
                                           "\n") or "\n"
        if not r.expression:
            raise S3Error("InvalidArgument", "missing Expression")
        return r


# -- input readers ----------------------------------------------------------

def _decompress(data: bytes, kind: str) -> bytes:
    if kind == "GZIP":
        return gzip.decompress(data)
    if kind == "BZIP2":
        return bz2.decompress(data)
    return data


def _rows_csv(data: bytes, req: SelectRequest) -> Iterator[dict]:
    text = data.decode("utf-8", errors="replace")
    reader = _csv.reader(io.StringIO(text), delimiter=req.csv_delim,
                         quotechar=req.csv_quote)
    header: Optional[list[str]] = None
    for i, rec in enumerate(reader):
        if not rec:
            continue
        if i == 0 and req.csv_header in ("USE", "IGNORE"):
            if req.csv_header == "USE":
                header = rec
            continue
        if header is not None:
            yield {header[j] if j < len(header) else f"_{j + 1}": v
                   for j, v in enumerate(rec)}
        else:
            yield {f"_{j + 1}": v for j, v in enumerate(rec)}


def _rows_parquet(data: bytes, req: SelectRequest) -> Iterator[dict]:
    """Columnar Parquet input (reference pkg/s3select/parquet): rows
    stream out batch-by-batch so a large file never materializes as one
    Python list. pyarrow does the columnar decode; values arrive as
    native Python types (int/float/str/bool/None), which the SQL
    evaluator handles like JSON values."""
    from ..s3.s3errors import S3Error
    try:
        import pyarrow.parquet as pq
    except ImportError:
        raise S3Error("NotImplemented",
                      "Parquet support needs pyarrow") from None
    try:
        pf = pq.ParquetFile(io.BytesIO(data))
    except Exception as e:  # noqa: BLE001 — arrow raises its own types
        raise S3Error("InvalidArgument",
                      f"bad Parquet object: {e}") from None
    batches = pf.iter_batches()
    while True:
        try:
            batch = next(batches)
        except StopIteration:
            return
        except Exception as e:  # noqa: BLE001 — a valid footer does
            # not guarantee valid data pages; decode errors surface
            # mid-iteration and must map to S3Error like CSV/JSON
            raise S3Error("InvalidArgument",
                          f"bad Parquet object: {e}") from None
        names = batch.schema.names
        cols = [c.to_pylist() for c in batch.columns]
        for i in range(batch.num_rows):
            yield {names[j]: cols[j][i] for j in range(len(names))}


def _rows_json(data: bytes, req: SelectRequest) -> Iterator[dict]:
    from ..s3.s3errors import S3Error
    text = data.decode("utf-8", errors="replace")
    if req.json_type == "DOCUMENT":
        try:
            doc = json.loads(text)
        except ValueError as e:
            raise S3Error("InvalidArgument", f"bad JSON: {e}") from None
        if isinstance(doc, list):
            for item in doc:
                yield item if isinstance(item, dict) else {"_1": item}
        else:
            yield doc if isinstance(doc, dict) else {"_1": doc}
        return
    dec = json.JSONDecoder()
    idx = 0
    n = len(text)
    while idx < n:
        while idx < n and text[idx] in " \t\r\n":
            idx += 1
        if idx >= n:
            break
        try:
            obj, end = dec.raw_decode(text, idx)
        except ValueError as e:
            raise S3Error("InvalidArgument", f"bad JSON: {e}") from None
        yield obj if isinstance(obj, dict) else {"_1": obj}
        idx = end


# -- output writers ---------------------------------------------------------

def _fmt_value(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    if isinstance(v, _dt.datetime):
        return format_sql_timestamp(v)
    return str(v)


def _json_default(v):
    if isinstance(v, _dt.datetime):
        return format_sql_timestamp(v)
    return str(v)


def _emit(row: dict, req: SelectRequest) -> bytes:
    if req.output_format == "JSON":
        return (json.dumps(row, default=_json_default)
                + req.out_record_delim).encode()
    buf = io.StringIO()
    w = _csv.writer(buf, delimiter=req.out_delim,
                    quotechar=req.out_quote,
                    lineterminator=req.out_record_delim)
    w.writerow([_fmt_value(v) for v in row.values()])
    return buf.getvalue().encode()


# -- engine -----------------------------------------------------------------

def run_select(req: SelectRequest, data: bytes) -> Iterator[bytes]:
    """Yields serialized output records for the query over `data`."""
    from ..s3.s3errors import S3Error
    try:
        q: Query = parse(req.expression)
    except SQLError as e:
        raise S3Error("InvalidArgument", f"SQL: {e}") from None
    data = _decompress(data, req.compression)
    if req.input_format == "JSON":
        rows = _rows_json(data, req)
    elif req.input_format == "PARQUET":
        rows = _rows_parquet(data, req)
    else:
        rows = _rows_csv(data, req)

    try:
        if q.is_aggregate:
            agg = Aggregator(q)
            for row in rows:
                if q.where is None or evaluate(q.where, row, q.alias):
                    agg.feed(row)
            yield _emit(agg.result(), req)
            return
        emitted = 0
        for row in rows:
            if q.where is not None and not evaluate(q.where, row,
                                                    q.alias):
                continue
            if q.star:
                out = dict(row)
            else:
                out = {}
                for i, (e, alias) in enumerate(q.projections):
                    from .sql import Col
                    name = alias or (e.name if isinstance(e, Col)
                                     else f"_{i + 1}")
                    out[name] = evaluate(e, row, q.alias)
            yield _emit(out, req)
            emitted += 1
            if q.limit is not None and emitted >= q.limit:
                return
    except SQLError as e:
        raise S3Error("InvalidArgument", f"SQL: {e}") from None


# -- AWS event-stream framing (pkg/s3select/message.go) ---------------------

def _header(name: str, value: str) -> bytes:
    nb = name.encode()
    vb = value.encode()
    return (bytes([len(nb)]) + nb + b"\x07"
            + struct.pack(">H", len(vb)) + vb)


def _message(headers: bytes, payload: bytes) -> bytes:
    total = 12 + len(headers) + len(payload) + 4
    prelude = struct.pack(">II", total, len(headers))
    pc = struct.pack(">I", zlib.crc32(prelude) & 0xffffffff)
    body = prelude + pc + headers + payload
    return body + struct.pack(">I", zlib.crc32(body) & 0xffffffff)


def records_message(payload: bytes) -> bytes:
    return _message(
        _header(":message-type", "event")
        + _header(":event-type", "Records")
        + _header(":content-type", "application/octet-stream"),
        payload)


def stats_message(scanned: int, processed: int, returned: int) -> bytes:
    xml = (f'<Stats xmlns="">'
           f"<BytesScanned>{scanned}</BytesScanned>"
           f"<BytesProcessed>{processed}</BytesProcessed>"
           f"<BytesReturned>{returned}</BytesReturned></Stats>")
    return _message(
        _header(":message-type", "event")
        + _header(":event-type", "Stats")
        + _header(":content-type", "text/xml"), xml.encode())


def end_message() -> bytes:
    return _message(
        _header(":message-type", "event")
        + _header(":event-type", "End"), b"")


def event_stream(req: SelectRequest, data: bytes) -> Iterator[bytes]:
    """Full SelectObjectContent response body."""
    yield from frame_records(run_select(req, data), len(data))


def frame_records(records: Iterator[bytes], data_len: int
                  ) -> Iterator[bytes]:
    """THE framing loop (128 KiB Records chunks, Stats over the raw
    object length, End) — shared with the device scan path
    (scan/engine.py), whose byte-identity guarantee would otherwise
    rest on a hand-synced copy."""
    returned = 0
    buf = b""
    for rec in records:
        buf += rec
        if len(buf) >= 128 * 1024:
            returned += len(buf)
            yield records_message(buf)
            buf = b""
    if buf:
        returned += len(buf)
        yield records_message(buf)
    yield stats_message(data_len, data_len, returned)
    yield end_message()
