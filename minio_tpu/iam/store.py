"""IAM persistence backends (reference cmd/iam-object-store.go and
cmd/iam-etcd-store.go).

One interface, two stores:

* ``ObjectIAMStore`` — JSON blobs under ``.minio.sys/config/iam/``
  through the ObjectLayer itself (erasure-coded, survives drive loss) —
  the default, and the only option without etcd.
* ``EtcdIAMStore`` — the same records as etcd keys. With federation
  this is what makes a set of clusters ONE deployment: users, policies
  and service accounts created on any cluster are visible to all of
  them (the reference switches IAM to etcd automatically when etcd is
  configured). Cross-cluster visibility is bounded by the periodic IAM
  refresh (no watch: the etcd JSON gateway's watch is a streaming gRPC
  bridge; polling at MINIO_TPU_IAM_REFRESH_S matches this build's
  bounded-staleness design for intra-cluster deltas).

Transient backend trouble raises ``IAMStoreError`` — callers must be
able to distinguish "the record is gone" (None) from "the backend
hiccuped" (exception), or a network blip would read as a user
deletion.
"""

from __future__ import annotations

import json
import urllib.parse
from typing import Optional

IAM_PREFIX = "config/iam"
MINIO_META_BUCKET = ".minio.sys"
ETCD_BASE = "minio/iam"


class IAMStoreError(Exception):
    """Transient persistence failure (quorum blip, etcd unreachable)."""


def entity_path(prefix: str, name: str) -> str:
    """Relative record path; the entity name is percent-encoded so
    federated subjects like 'oidc:a/b' can never collide with 'oidc:a_b'
    and decode back exactly on load."""
    return (f"{IAM_PREFIX}/{prefix}/"
            f"{urllib.parse.quote(name, safe='')}.json")


class ObjectIAMStore:
    def __init__(self, obj):
        self.obj = obj

    def save(self, path: str, payload: dict) -> None:
        from ..object import api_errors
        try:
            self.obj.put_object(MINIO_META_BUCKET, path,
                                json.dumps(payload).encode())
        except api_errors.ObjectApiError as e:
            raise IAMStoreError(str(e)) from e

    def delete(self, path: str) -> None:
        from ..object import api_errors
        try:
            self.obj.delete_object(MINIO_META_BUCKET, path)
        except api_errors.ObjectNotFound:
            pass
        except api_errors.ObjectApiError as e:
            # a failed revocation must surface — the refresh loop would
            # otherwise resurrect the "deleted" credential from the
            # record that never left the store
            raise IAMStoreError(str(e)) from e

    def read_all(self, prefix: str) -> dict[str, dict]:
        from ..object import api_errors
        out: dict[str, dict] = {}
        try:
            objs, _, _ = self.obj.list_objects(
                MINIO_META_BUCKET, prefix=f"{IAM_PREFIX}/{prefix}/",
                max_keys=10000)
        except api_errors.ObjectApiError as e:
            # a listing failure is backend trouble, not an empty store
            # — returning {} here would wipe the caller's cache
            raise IAMStoreError(str(e)) from e
        for oi in objs:
            if not oi.name.endswith(".json"):
                continue
            name = urllib.parse.unquote(
                oi.name[len(f"{IAM_PREFIX}/{prefix}/"):-len(".json")])
            try:
                _, stream = self.obj.get_object(MINIO_META_BUCKET,
                                                oi.name)
                out[name] = json.loads(b"".join(stream).decode())
            except (api_errors.ObjectApiError, ValueError):
                continue
        return out

    def read_one(self, prefix: str, name: str) -> Optional[dict]:
        from ..object import api_errors
        try:
            _, stream = self.obj.get_object(
                MINIO_META_BUCKET, entity_path(prefix, name))
            return json.loads(b"".join(stream).decode())
        except (api_errors.ObjectNotFound, ValueError):
            return None
        except api_errors.ObjectApiError as e:
            raise IAMStoreError(str(e)) from e


class EtcdIAMStore:
    def __init__(self, etcd):
        self.etcd = etcd

    @staticmethod
    def _key(path: str) -> str:
        return f"{ETCD_BASE}/{path}"

    def save(self, path: str, payload: dict) -> None:
        from ..distributed.etcd import EtcdError
        try:
            self.etcd.put(self._key(path),
                          json.dumps(payload).encode())
        except EtcdError as e:
            raise IAMStoreError(str(e)) from e

    def delete(self, path: str) -> None:
        from ..distributed.etcd import EtcdError
        try:
            self.etcd.delete(self._key(path))
        except EtcdError as e:
            raise IAMStoreError(str(e)) from e

    def read_all(self, prefix: str) -> dict[str, dict]:
        from ..distributed.etcd import EtcdError
        base = f"{ETCD_BASE}/{IAM_PREFIX}/{prefix}/"
        out: dict[str, dict] = {}
        try:
            kvs = self.etcd.get_prefix(base)
        except EtcdError as e:
            raise IAMStoreError(str(e)) from e
        for k, raw in kvs.items():
            if not k.endswith(".json"):
                continue
            name = urllib.parse.unquote(k[len(base):-len(".json")])
            try:
                out[name] = json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                continue
        return out

    def read_one(self, prefix: str, name: str) -> Optional[dict]:
        from ..distributed.etcd import EtcdError
        try:
            raw = self.etcd.get(self._key(entity_path(prefix, name)))
        except EtcdError as e:
            raise IAMStoreError(str(e)) from e
        if raw is None:
            return None
        try:
            return json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            return None
