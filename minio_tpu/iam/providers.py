"""STS federation identity providers: OpenID Connect (JWT/JWKS) + LDAP.

The reference authenticates federated STS callers three ways
(cmd/sts-handlers.go:43-86): AssumeRoleWithWebIdentity and
AssumeRoleWithClientGrants validate an OIDC JWT against the provider's
JWKS (cmd/config/identity/openid/jwt.go), AssumeRoleWithLDAPIdentity
simple-binds to an LDAP server (cmd/config/identity/ldap/config.go).
Both map the federated identity to IAM policies: OIDC via a policy
claim in the token, LDAP via the policy DB entry for the bound DN.

This module is transport-real but offline-testable:
  * OpenIDProvider reads a JWKS from inline config or a local file (the
    discovery fetch of config_url is a one-line swap when egress
    exists); RS256/384/512 verify via `cryptography`, HS256 via hmac.
  * LDAPProvider speaks actual LDAPv3 simple bind (BER-encoded over a
    socket, RFC 4511 §4.2) — tests run a loopback server; production
    points server_addr at a real directory.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import socket
import time
from typing import Callable, Optional


class STSValidationError(Exception):
    """Token/credential validation failure (maps to AccessDenied)."""


def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def _b64url_uint(s: str) -> int:
    return int.from_bytes(_b64url_decode(s), "big")


# ---------------------------------------------------------------------------
# OpenID Connect
# ---------------------------------------------------------------------------

class OpenIDProvider:
    """JWT validation against a configured JWKS + policy-claim mapping.

    Config keys (identity_openid subsystem): `jwks` (inline JWKS JSON)
    or `jwks_file` (path), `client_id` (enforced against aud/azp when
    set), `claim_name` (default "policy"), `claim_prefix`.
    """

    ALGS = {"RS256": "sha256", "RS384": "sha384", "RS512": "sha512",
            "HS256": "sha256", "HS384": "sha384", "HS512": "sha512"}

    def __init__(self, cfg: dict):
        self.client_id = cfg.get("client_id", "")
        self.claim_name = cfg.get("claim_name") or "policy"
        self.claim_prefix = cfg.get("claim_prefix", "")
        jwks_raw = cfg.get("jwks", "")
        if not jwks_raw and cfg.get("jwks_file"):
            with open(cfg["jwks_file"]) as f:
                jwks_raw = f.read()
        self._keys: dict[str, dict] = {}
        self._anon_keys: list[dict] = []
        if jwks_raw:
            for k in json.loads(jwks_raw).get("keys", []):
                if k.get("kid"):
                    self._keys[k["kid"]] = k
                else:
                    self._anon_keys.append(k)

    def enabled(self) -> bool:
        return bool(self._keys or self._anon_keys)

    # -- validation --------------------------------------------------------

    def validate(self, token: str, *, now: Optional[float] = None) -> dict:
        """Verify signature + temporal claims + audience; return the
        claim set. Raises STSValidationError on every failure mode."""
        now = time.time() if now is None else now
        try:
            h_b64, p_b64, s_b64 = token.split(".")
            header = json.loads(_b64url_decode(h_b64))
            claims = json.loads(_b64url_decode(p_b64))
            sig = _b64url_decode(s_b64)
        except Exception:
            raise STSValidationError("malformed JWT") from None

        alg = header.get("alg", "")
        if alg not in self.ALGS:
            raise STSValidationError(f"unsupported alg {alg!r}")
        key = self._find_key(header.get("kid"), alg)
        signing_input = f"{h_b64}.{p_b64}".encode()
        if not self._verify_sig(key, alg, signing_input, sig):
            raise STSValidationError("signature verification failed")

        exp = claims.get("exp")
        if not isinstance(exp, (int, float)):
            raise STSValidationError("missing exp claim")
        if now >= exp:
            raise STSValidationError("token expired")
        nbf = claims.get("nbf")
        if isinstance(nbf, (int, float)) and now < nbf:
            raise STSValidationError("token not yet valid")
        if self.client_id:
            aud = claims.get("aud", claims.get("azp"))
            auds = aud if isinstance(aud, list) else [aud]
            if self.client_id not in auds:
                raise STSValidationError("audience mismatch")
        return claims

    def _find_key(self, kid: Optional[str], alg: str) -> dict:
        if kid is not None:
            k = self._keys.get(kid)
            if k is None:
                raise STSValidationError(f"unknown kid {kid!r}")
            return k
        if self._anon_keys:
            return self._anon_keys[0]
        if len(self._keys) == 1:
            return next(iter(self._keys.values()))
        raise STSValidationError("no kid and multiple keys")

    def _verify_sig(self, key: dict, alg: str, signing_input: bytes,
                    sig: bytes) -> bool:
        digest = self.ALGS[alg]
        if alg.startswith("HS"):
            if key.get("kty") != "oct" or "k" not in key:
                raise STSValidationError("key type mismatch for HMAC alg")
            want = hmac.new(_b64url_decode(key["k"]), signing_input,
                            getattr(hashlib, digest)).digest()
            return hmac.compare_digest(want, sig)
        if key.get("kty") != "RSA":
            raise STSValidationError("key type mismatch for RSA alg")
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import (padding,
                                                               rsa)
        hash_cls = {"sha256": hashes.SHA256, "sha384": hashes.SHA384,
                    "sha512": hashes.SHA512}[digest]
        pub = rsa.RSAPublicNumbers(
            _b64url_uint(key["e"]), _b64url_uint(key["n"])).public_key()
        try:
            pub.verify(sig, signing_input, padding.PKCS1v15(),
                       hash_cls())
            return True
        except Exception:
            return False

    # -- policy mapping ----------------------------------------------------

    def policy_names(self, claims: dict) -> list[str]:
        """Policies named by the token's policy claim (reference
        GetDefaultPolicyName over the configured claim,
        cmd/sts-handlers.go WebIdentity flow)."""
        v = claims.get(self.claim_prefix + self.claim_name)
        if v is None and self.claim_prefix:
            v = claims.get(self.claim_name)
        if v is None:
            return []
        if isinstance(v, str):
            return [p.strip() for p in v.split(",") if p.strip()]
        if isinstance(v, list):
            return [str(p) for p in v if str(p)]
        return []


# ---------------------------------------------------------------------------
# LDAP (RFC 4511 simple bind, minimal BER)
# ---------------------------------------------------------------------------

def _ber_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def _tlv(tag: int, content: bytes) -> bytes:
    return bytes([tag]) + _ber_len(len(content)) + content


def _ber_int(v: int) -> bytes:
    body = v.to_bytes(max(1, (v.bit_length() + 8) // 8), "big",
                      signed=True)
    return _tlv(0x02, body)


def _parse_tlv(buf: bytes, at: int) -> tuple[int, bytes, int]:
    """-> (tag, content, next_offset)"""
    tag = buf[at]
    ln = buf[at + 1]
    at += 2
    if ln & 0x80:
        nb = ln & 0x7F
        ln = int.from_bytes(buf[at:at + nb], "big")
        at += nb
    return tag, buf[at:at + ln], at + ln


def _recv_ber_message(s: socket.socket, limit: int = 1 << 20) -> bytes:
    """Read exactly one BER TLV from a socket — length-driven, so a
    response fragmented across TCP segments still parses (a single
    recv() would truncate over a WAN)."""
    def recv_exact(n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = s.recv(n - len(buf))
            if not chunk:
                raise OSError("LDAP connection closed mid-message")
            buf += chunk
        return buf

    head = recv_exact(2)
    ln = head[1]
    if ln & 0x80:
        nb = ln & 0x7F
        if not 0 < nb <= 4:
            raise OSError("bad BER length")
        ext = recv_exact(nb)
        ln = int.from_bytes(ext, "big")
        head += ext
    if ln > limit:
        raise OSError("oversized LDAP message")
    return head + recv_exact(ln)


_DN_ESCAPE = {c: f"\\{c}" for c in ',+"\\<>;='}


def _dn_escape(value: str) -> str:
    """RFC 4514 escaping of a DN attribute value — the client-supplied
    username must not be able to inject DN structure (',ou=admins')
    and thereby choose which DN's policy mapping it inherits."""
    out = "".join(_DN_ESCAPE.get(c, c) for c in value)
    if out.startswith((" ", "#")):
        out = "\\" + out
    if out.endswith(" "):
        out = out[:-1] + "\\ "
    return out.replace("\x00", "\\00")


class LDAPProvider:
    """LDAPv3 simple bind against `server_addr`; DN from
    `user_dn_format` (e.g. "uid=%s,ou=people,dc=example,dc=org" — the
    reference's username format list, cmd/config/identity/ldap).
    """

    def __init__(self, cfg: dict,
                 connect: Optional[Callable[[], socket.socket]] = None):
        self.server_addr = cfg.get("server_addr", "")
        self.user_dn_format = cfg.get("user_dn_format", "")
        self._connect = connect or self._default_connect

    def enabled(self) -> bool:
        return bool(self.server_addr)

    def _default_connect(self) -> socket.socket:
        from ..utils import host_port
        return socket.create_connection(
            host_port(self.server_addr, 389), timeout=10)

    def bind(self, username: str, password: str) -> str:
        """Simple bind; returns the bound DN or raises
        STSValidationError (bad credentials, unreachable server)."""
        if not username or not password:
            raise STSValidationError("empty LDAP username or password")
        dn = (self.user_dn_format % _dn_escape(username)) \
            if self.user_dn_format else _dn_escape(username)
        bind_req = _tlv(0x60,                       # [APPLICATION 0]
                        _ber_int(3)                 # version
                        + _tlv(0x04, dn.encode())   # name
                        + _tlv(0x80, password.encode()))  # simple auth
        msg = _tlv(0x30, _ber_int(1) + bind_req)
        try:
            with self._connect() as s:
                s.sendall(msg)
                resp = _recv_ber_message(s)
        except OSError as e:
            raise STSValidationError(f"LDAP unreachable: {e}") from None
        try:
            _tag, env, _ = _parse_tlv(resp, 0)      # LDAPMessage
            at = 0
            _tag, _msgid, at = _parse_tlv(env, at)  # messageID
            tag, bres, _ = _parse_tlv(env, at)      # BindResponse
            if tag != 0x61:
                raise ValueError("not a BindResponse")
            _tag, code, _ = _parse_tlv(bres, 0)     # resultCode (ENUM)
            result = int.from_bytes(code, "big")
        except Exception:
            raise STSValidationError("malformed LDAP response") from None
        if result != 0:
            raise STSValidationError(
                f"LDAP bind failed (resultCode {result})")
        return dn
