"""IAM: identity, access policies, STS (reference cmd/iam.go +
pkg/iam/policy + cmd/sts-handlers.go)."""

from .policy import Policy, PolicyArgs, Statement  # noqa: F401
from .sys import IAMSys  # noqa: F401
