"""IAM / bucket policy documents and evaluation.

The reference's pkg/iam/policy + pkg/bucket/policy: JSON policy documents
(Version, Statement[] of Effect/Action/Resource/Principal/Condition)
evaluated per request. Explicit Deny always wins; otherwise any matching
Allow grants; default is deny.

Wildcards: Action and Resource support '*' and '?' globs exactly like the
reference's pkg/wildcard. Conditions implement the operators the S3
dialect actually exercises (StringEquals / StringNotEquals / StringLike /
StringNotLike / IpAddress / NotIpAddress with real CIDR containment); an
unknown operator or key makes the condition false (deny-safe, matching
AWS semantics for unresolvable conditions).
"""

from __future__ import annotations

import dataclasses
import fnmatch
import ipaddress
import json
from typing import Optional


def _wild_match(pattern: str, s: str) -> bool:
    """'*'/'?' glob (reference pkg/wildcard.MatchSimple)."""
    if pattern == "*":
        return True
    # fnmatch also honors [] classes; neutralize them to literal chars
    pattern = pattern.replace("[", "[[]")
    return fnmatch.fnmatchcase(s, pattern)


def _ip_in_cidr(have: str, want: str) -> bool:
    """CIDR containment (reference pkg/policy/condition ipaddress.go).
    Malformed addresses or networks never match (deny-safe)."""
    try:
        return ipaddress.ip_address(have.strip()) in \
            ipaddress.ip_network(want.strip(), strict=False)
    except ValueError:
        return False


def _to_num(s: str):
    try:
        return float(s)
    except ValueError:
        return None


def _to_date(s: str):
    """ISO 8601 (with Z or offset) or epoch seconds -> unix ts."""
    s = s.strip()
    n = _to_num(s)
    if n is not None:
        return n
    import datetime as _dt
    try:
        return _dt.datetime.fromisoformat(
            s.replace("Z", "+00:00")).timestamp()
    except ValueError:
        return None


_CMP = {"Equals": lambda a, b: a == b,
        "LessThan": lambda a, b: a < b,
        "LessThanEquals": lambda a, b: a <= b,
        "GreaterThan": lambda a, b: a > b,
        "GreaterThanEquals": lambda a, b: a >= b}


def _op_hit(base: str, vals: list[str], have: str):
    """One positive condition operator over one context value: True /
    False on a known operator, None when the operator is unknown
    (deny-safe at the caller)."""
    if base == "StringEquals":
        return have in vals
    if base == "StringEqualsIgnoreCase":
        return have.lower() in [v.lower() for v in vals]
    if base == "StringLike":
        return any(_wild_match(v, have) for v in vals)
    if base == "IpAddress":
        return any(_ip_in_cidr(have, v) for v in vals)
    if base == "Bool":
        return have.lower() in [v.lower() for v in vals]
    for family, conv in (("Numeric", _to_num), ("Date", _to_date)):
        if base.startswith(family):
            cmp = _CMP.get(base[len(family):])
            if cmp is None:
                return None
            h = conv(have)
            if h is None:
                return False                   # unparsable: never match
            return any(cmp(h, w) for w in
                       (conv(v) for v in vals) if w is not None)
    return None


@dataclasses.dataclass
class PolicyArgs:
    """One authorization query (reference policy.Args)."""
    account: str = ""             # access key of the caller
    action: str = ""              # e.g. "s3:GetObject"
    bucket: str = ""
    object: str = ""
    is_owner: bool = False
    conditions: dict = dataclasses.field(default_factory=dict)

    @property
    def resource(self) -> str:
        if self.object:
            return f"{self.bucket}/{self.object}"
        return self.bucket


class Statement:
    def __init__(self, effect: str, actions: list[str],
                 resources: list[str],
                 principals: Optional[list[str]] = None,
                 conditions: Optional[dict] = None, sid: str = ""):
        if effect not in ("Allow", "Deny"):
            raise ValueError(f"invalid Effect {effect!r}")
        self.sid = sid
        self.effect = effect
        self.actions = actions
        self.resources = resources
        self.principals = principals          # None = identity policy
        self.conditions = conditions or {}

    # -- matching ----------------------------------------------------------

    def _action_matches(self, action: str) -> bool:
        return any(_wild_match(a, action) for a in self.actions)

    def _resource_matches(self, resource: str) -> bool:
        for r in self.resources:
            pat = r
            for prefix in ("arn:aws:s3:::",):
                if pat.startswith(prefix):
                    pat = pat[len(prefix):]
            if _wild_match(pat, resource):
                return True
        return False

    def _principal_matches(self, account: str) -> bool:
        if self.principals is None:
            return True                        # identity policy: implicit
        return any(_wild_match(p, account) for p in self.principals)

    def _conditions_match(self, ctx: dict) -> bool:
        # AWS/reference operator matrix (pkg/policy/condition): String*,
        # Numeric*, Date*, Bool, IpAddress, Null, with Not- and
        # IfExists- modifiers. A NEGATED operator evaluates true when
        # the condition key is absent from the request context; a
        # positive operator evaluates false (unless IfExists). Unknown
        # operators are false — safe because a non-applying Deny is "no
        # opinion", same as the reference's unresolvable conditions.
        for op, kv in self.conditions.items():
            if op == "Null":
                for key, want in kv.items():
                    vals = want if isinstance(want, list) else [want]
                    want_null = str(vals[0]).lower() in ("true", "1")
                    if (ctx.get(key) is None) != want_null:
                        return False
                continue
            base = op
            if_exists = base.endswith("IfExists")
            if if_exists:
                base = base[:-len("IfExists")]
            neg = "Not" in base
            base = base.replace("Not", "", 1)
            for key, want in kv.items():
                vals = [str(v) for v in
                        (want if isinstance(want, list) else [want])]
                have = ctx.get(key)
                if have is None:
                    if neg or if_exists:
                        continue
                    return False
                hit = _op_hit(base, vals, str(have))
                if hit is None:
                    return False               # unknown operator
                if hit == neg:
                    return False
        return True

    def applies(self, args: PolicyArgs) -> bool:
        return (self._action_matches(args.action)
                and self._resource_matches(args.resource)
                and self._principal_matches(args.account)
                and self._conditions_match(args.conditions))

    # -- (de)serialization -------------------------------------------------

    @classmethod
    def from_dict(cls, d: dict) -> "Statement":
        def aslist(v):
            if v is None:
                return []
            return v if isinstance(v, list) else [v]

        principals = None
        if "Principal" in d:
            p = d["Principal"]
            if isinstance(p, dict):
                principals = aslist(p.get("AWS", []))
            else:
                principals = aslist(p)
            principals = [x.replace("arn:aws:iam::", "").replace(
                ":root", "") for x in principals]
        return cls(effect=d.get("Effect", ""),
                   actions=aslist(d.get("Action")),
                   resources=aslist(d.get("Resource")),
                   principals=principals,
                   conditions=d.get("Condition"),
                   sid=d.get("Sid", ""))

    def to_dict(self) -> dict:
        out: dict = {"Effect": self.effect, "Action": self.actions,
                     "Resource": self.resources}
        if self.sid:
            out["Sid"] = self.sid
        if self.principals is not None:
            out["Principal"] = {"AWS": self.principals}
        if self.conditions:
            out["Condition"] = self.conditions
        return out


class Policy:
    def __init__(self, statements: list[Statement],
                 version: str = "2012-10-17"):
        self.version = version
        self.statements = statements

    def is_allowed(self, args: PolicyArgs) -> bool:
        allowed = False
        for st in self.statements:
            if not st.applies(args):
                continue
            if st.effect == "Deny":
                return False                   # explicit deny wins
            allowed = True
        return allowed

    def is_empty(self) -> bool:
        return not self.statements

    @classmethod
    def from_json(cls, raw: str | bytes) -> "Policy":
        d = json.loads(raw)
        sts = [Statement.from_dict(s) for s in d.get("Statement", [])]
        return cls(sts, version=d.get("Version", "2012-10-17"))

    def to_json(self) -> str:
        return json.dumps({
            "Version": self.version,
            "Statement": [s.to_dict() for s in self.statements]})


# -- canned policies (reference pkg/iam/policy/{admin,readonly,...}.go) ----

def _canned(effect: str, actions: list[str]) -> Policy:
    return Policy([Statement(effect, actions, ["*"])])


CANNED_POLICIES: dict[str, Policy] = {
    "readonly": _canned("Allow", ["s3:GetBucketLocation", "s3:GetObject",
                                  "s3:GetObjectVersion",
                                  "s3:ListAllMyBuckets", "s3:ListBucket"]),
    "writeonly": _canned("Allow", ["s3:PutObject",
                                   "s3:ListBucketMultipartUploads",
                                   "s3:AbortMultipartUpload",
                                   "s3:ListMultipartUploadParts"]),
    "readwrite": _canned("Allow", ["s3:*"]),
    "consoleAdmin": _canned("Allow", ["s3:*", "admin:*", "sts:*"]),
    "diagnostics": _canned("Allow", ["admin:ServerInfo", "admin:Profiling",
                                     "admin:TopLocksInfo",
                                     "admin:OBDInfo"]),
}
