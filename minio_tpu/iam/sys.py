"""IAMSys — users, groups, policies, service accounts, STS credentials.

The reference's cmd/iam.go + cmd/iam-object-store.go: all IAM state
persists as JSON objects under `.minio.sys/config/iam/` through the
ObjectLayer itself (so it is erasure-coded and survives drive loss), with
an in-memory cache and peer-reload broadcast on change.

Layout (mirrors iam-object-store keys):
    config/iam/users/<ak>.json          identity (secret, status)
    config/iam/groups/<name>.json       {members, status}
    config/iam/policies/<name>.json     policy document
    config/iam/policydb/users/<ak>.json      {"policy": [names]}
    config/iam/policydb/groups/<name>.json   {"policy": [names]}
    config/iam/svcaccts/<ak>.json       service account (parent, secret)
    config/iam/sts/<ak>.json            temp credentials
"""

from __future__ import annotations

import base64
import json
import secrets
import threading
import time
import urllib.parse
from typing import Callable, Optional

from ..s3.credentials import Credentials, generate_credentials
from .policy import CANNED_POLICIES, Policy, PolicyArgs

IAM_PREFIX = "config/iam"
MINIO_META_BUCKET = ".minio.sys"


class IAMError(Exception):
    pass


class IAMSys:
    """In-memory IAM state over persisted JSON blobs.

    `object_layer=None` gives a purely in-memory IAM (tests, single-shot
    tools); with a layer every mutation persists before the cache updates.
    """

    def __init__(self, object_layer=None, root_cred: Optional[Credentials]
                 = None, store=None):
        from .store import ObjectIAMStore
        self.obj = object_layer
        # persistence backend (cmd/iam-object-store.go vs
        # cmd/iam-etcd-store.go): defaults to the object layer; an
        # EtcdIAMStore makes IAM shared across federated clusters
        self.store = store if store is not None else (
            ObjectIAMStore(object_layer)
            if object_layer is not None else None)
        self.root = root_cred
        self._mu = threading.RLock()
        self.users: dict[str, Credentials] = {}
        self.groups: dict[str, dict] = {}           # name -> {members,status}
        self.policies: dict[str, Policy] = dict(CANNED_POLICIES)
        self.user_policy: dict[str, list[str]] = {}
        self.group_policy: dict[str, list[str]] = {}
        self.sts_creds: dict[str, Credentials] = {}
        self.svc_accounts: dict[str, Credentials] = {}
        # cluster hook: called with no args after every mutation so peers
        # reload (reference NotificationSys.LoadUser/LoadPolicy etc.)
        self.on_change: Optional[Callable[[], None]] = None
        # granular peer propagation: called with the mutation's whole
        # [(kind, name), ...] batch; when unset, on_change (wholesale)
        self.on_delta: Optional[Callable[[list], None]] = None
        # bucket policy lookup seam (bucket -> policy JSON or "")
        self.bucket_policy_lookup: Optional[Callable[[str], str]] = None
        if self.store is not None:
            self.load()

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------

    def _path(self, *parts: str) -> str:
        # one encoder for reads AND writes: store.entity_path owns the
        # percent-encoding (federated subjects like 'oidc:a/b' must
        # never collide with 'oidc:a_b', and the write path and the
        # delta read path must build byte-identical keys)
        from .store import entity_path
        return entity_path("/".join(parts[:-1]), parts[-1])

    def _save(self, path: str, payload: dict) -> None:
        if self.store is not None:
            self.store.save(path, payload)

    def _delete(self, path: str) -> None:
        if self.store is not None:
            self.store.delete(path)

    def _read_all(self, prefix: str) -> dict[str, dict]:
        """name -> parsed payload for every record under
        config/iam/<prefix>/ in the configured store."""
        if self.store is None:
            return {}
        return self.store.read_all(prefix)

    def load(self) -> None:
        """(Re)build the cache from the store (reference IAMSys.Load).
        Every prefix is read BEFORE the cache mutates, and a transient
        store failure keeps the existing cache — a backend blip must
        never read as "all identities deleted"."""
        from .store import IAMStoreError
        try:
            raw_users = self._read_all("users")
            raw_groups = self._read_all("groups")
            raw_policies = self._read_all("policies")
            raw_upol = self._read_all("policydb/users")
            raw_gpol = self._read_all("policydb/groups")
            raw_svc = self._read_all("svcaccts")
            raw_sts = self._read_all("sts")
        except IAMStoreError:
            return                # keep the current cache
        with self._mu:
            self.users = {
                ak: Credentials(access_key=ak,
                                secret_key=d.get("secret_key", ""),
                                status=d.get("status", "on"))
                for ak, d in raw_users.items()}
            self.groups = raw_groups
            self.policies = dict(CANNED_POLICIES)
            for name, d in raw_policies.items():
                try:
                    self.policies[name] = Policy.from_json(json.dumps(d))
                except (ValueError, KeyError):
                    continue
            self.user_policy = {
                ak: list(d.get("policy", []))
                for ak, d in raw_upol.items()}
            self.group_policy = {
                g: list(d.get("policy", []))
                for g, d in raw_gpol.items()}
            self.svc_accounts = {
                ak: Credentials(access_key=ak,
                                secret_key=d.get("secret_key", ""),
                                parent_user=d.get("parent", ""),
                                status=d.get("status", "on"))
                for ak, d in raw_svc.items()}
            now = time.time()
            self.sts_creds = {}
            for ak, d in raw_sts.items():
                c = Credentials(access_key=ak,
                                secret_key=d.get("secret_key", ""),
                                session_token=d.get("session_token", ""),
                                expiration=d.get("expiration", 0.0),
                                parent_user=d.get("parent", ""))
                if not c.is_expired() or c.expiration > now:
                    self.sts_creds[ak] = c

    def migrate_to_store(self, new_store) -> None:
        """Switch persistence backends (the object-store → etcd move
        when federation is first configured). An unseeded target is
        seeded from the current cache so identities that predate etcd
        survive the switch; a SEEDED target is authoritative (another
        federated cluster already populated it) and replaces the cache.
        An unreachable target keeps the current store untouched.

        "Seeded" means the ``format/seed-complete`` marker is present —
        written only AFTER every record landed. A seed that dies
        partway leaves no marker, so the next boot re-seeds instead of
        adopting the partial store as authoritative and silently
        dropping every identity that only the old store held
        (ADVICE r4). An UNMARKED target is scratch space: the seed
        overwrites it from the snapshot and deletes records the
        snapshot doesn't have — leftovers of a prior crashed seed must
        not resurrect identities that were deleted (in the durable old
        store) between the attempts. Two clusters racing the very
        first migration can overwrite each other's pre-marker writes;
        both then converge on the marked store via the final load().

        ``self.store`` stays on the OLD store until the marker lands:
        the bulk seed runs unlocked (many etcd round trips must not
        stall auth checks), so concurrent mutations keep committing to
        the old, durable store; a failed seed therefore abandons
        nothing. A short locked pass then reconciles whatever mutated
        during the bulk copy and cuts over atomically."""
        from .store import IAMStoreError
        try:
            seeded = new_store.read_one("format", "seed-complete")
        except IAMStoreError:
            return
        if seeded:
            self.store = new_store
            self.load()
            return
        prefixes = ("users", "groups", "policies", "policydb/users",
                    "policydb/groups", "svcaccts", "sts")
        with self._mu:
            snap = self._iam_records()
        try:
            stale = {p: new_store.read_all(p) for p in prefixes}
            for prefix in prefixes:
                for name, payload in snap[prefix].items():
                    if stale[prefix].get(name) != payload:
                        new_store.save(self._path(prefix, name),
                                       payload)
                for name in stale[prefix]:
                    if name not in snap[prefix]:
                        new_store.delete(self._path(prefix, name))
            with self._mu:
                # reconcile mutations that landed during the bulk seed
                # (bounded by the mutation rate, not the record count)
                now = self._iam_records()
                for prefix in prefixes:
                    for name, payload in now[prefix].items():
                        if snap[prefix].get(name) != payload:
                            new_store.save(self._path(prefix, name),
                                           payload)
                    for name in snap[prefix]:
                        if name not in now[prefix]:
                            new_store.delete(self._path(prefix, name))
                # marker LAST: until it lands, no cluster treats this
                # store as authoritative
                new_store.save(self._path("format", "seed-complete"),
                               {"complete": True, "at": time.time()})
                self.store = new_store
        except IAMStoreError:
            # partial seed: self.store never moved, so every mutation
            # acknowledged meanwhile is durable in the old store; the
            # next boot retries (no marker → the partial target is
            # never adopted)
            return
        self.load()

    def _iam_records(self) -> dict[str, dict[str, dict]]:
        """prefix -> name -> stored payload for the whole cache, in the
        exact shape the store persists (caller holds ``_mu``)."""
        return {
            "users": {ak: {"secret_key": c.secret_key,
                           "status": c.status}
                      for ak, c in self.users.items()},
            "groups": {g: dict(info)
                       for g, info in self.groups.items()},
            "policies": {n: json.loads(p.to_json())
                         for n, p in self.policies.items()
                         if n not in CANNED_POLICIES},
            "policydb/users": {ak: {"policy": list(v)}
                               for ak, v in self.user_policy.items()},
            "policydb/groups": {g: {"policy": list(v)}
                                for g, v in self.group_policy.items()},
            "svcaccts": {ak: {"secret_key": c.secret_key,
                              "parent": c.parent_user,
                              "status": c.status}
                         for ak, c in self.svc_accounts.items()},
            "sts": {ak: {"secret_key": c.secret_key,
                         "session_token": c.session_token,
                         "expiration": c.expiration,
                         "parent": c.parent_user}
                    for ak, c in self.sts_creds.items()},
        }

    def _notify(self, kind: str = "", name: str = "") -> None:
        self._notify_batch([(kind, name)] if kind else [])

    def _notify_batch(self, pairs: list) -> None:
        """Propagate a mutation to peers. With (kind, name) deltas and
        an on_delta hook, peers reload ONLY those entities — in ONE
        broadcast round for the whole batch (reference granular
        LoadUser/LoadGroup/LoadPolicy peer verbs,
        cmd/peer-rest-common.go:38-46); wholesale reload is the
        fallback, not the steady state (it is O(all users) per change).
        """
        if pairs and self.on_delta is not None:
            try:
                self.on_delta(pairs)
                return
            except Exception:  # noqa: BLE001 — fall back to full reload
                pass
        if self.on_change is not None:
            try:
                self.on_change()
            except Exception:  # noqa: BLE001 — peers reload lazily anyway
                pass

    def _read_one(self, prefix: str, name: str) -> Optional[dict]:
        """Current stored record of one IAM entity, or None when it no
        longer exists (delta application reads the store, so a delete
        and a create are the same verb). A TRANSIENT store error must
        not read as "deleted" — it raises IAMStoreError, and
        apply_delta degrades to a full reload instead of evicting a
        live credential."""
        if self.store is None:
            return None
        return self.store.read_one(prefix, name)

    def apply_delta(self, kind: str, name: str) -> None:
        """Refresh one entity from the store (the receiving side of the
        peer delta verbs). Unknown kinds degrade to a full load."""
        from .store import IAMStoreError
        d = None
        if kind in ("user", "group", "policy", "user-policy",
                    "group-policy", "svcacct", "sts"):
            prefix = {"user": "users", "group": "groups",
                      "policy": "policies",
                      "user-policy": "policydb/users",
                      "group-policy": "policydb/groups",
                      "svcacct": "svcaccts", "sts": "sts"}[kind]
            try:
                d = self._read_one(prefix, name)
            except IAMStoreError:
                # backend blip on the read: keep the cached entry and
                # resync wholesale rather than evicting a live identity
                try:
                    self.load()
                except IAMStoreError:
                    pass
                return
        with self._mu:
            if kind == "user":
                if d is None:
                    self.users.pop(name, None)
                else:
                    self.users[name] = Credentials(
                        access_key=name,
                        secret_key=d.get("secret_key", ""),
                        status=d.get("status", "on"))
                return
            if kind == "group":
                if d is None:
                    self.groups.pop(name, None)
                else:
                    self.groups[name] = d
                return
            if kind == "policy":
                if d is None:
                    self.policies.pop(name, None)
                    if name in CANNED_POLICIES:
                        self.policies[name] = CANNED_POLICIES[name]
                else:
                    try:
                        self.policies[name] = Policy.from_json(
                            json.dumps(d))
                    except (ValueError, KeyError):
                        pass
                return
            if kind == "user-policy":
                if d is None:
                    self.user_policy.pop(name, None)
                else:
                    self.user_policy[name] = list(d.get("policy", []))
                return
            if kind == "group-policy":
                if d is None:
                    self.group_policy.pop(name, None)
                else:
                    self.group_policy[name] = list(d.get("policy", []))
                return
            if kind == "svcacct":
                if d is None:
                    self.svc_accounts.pop(name, None)
                else:
                    self.svc_accounts[name] = Credentials(
                        access_key=name,
                        secret_key=d.get("secret_key", ""),
                        parent_user=d.get("parent", ""),
                        status=d.get("status", "on"))
                return
            if kind == "sts":
                if d is None:
                    self.sts_creds.pop(name, None)
                else:
                    self.sts_creds[name] = Credentials(
                        access_key=name,
                        secret_key=d.get("secret_key", ""),
                        session_token=d.get("session_token", ""),
                        expiration=d.get("expiration", 0.0),
                        parent_user=d.get("parent", ""))
                return
        self.load()

    # ------------------------------------------------------------------
    # users / groups / policies CRUD (cmd/admin-handlers-users.go surface)
    # ------------------------------------------------------------------

    def add_user(self, access_key: str, secret_key: str,
                 status: str = "on") -> None:
        if self.root is not None and access_key == self.root.access_key:
            raise IAMError("cannot override root account")
        with self._mu:
            self._save(self._path("users", access_key),
                       {"secret_key": secret_key, "status": status})
            self.users[access_key] = Credentials(
                access_key=access_key, secret_key=secret_key, status=status)
        self._notify("user", access_key)

    def set_user_status(self, access_key: str, status: str) -> None:
        with self._mu:
            u = self.users.get(access_key)
            if u is None:
                raise IAMError(f"no such user {access_key}")
            u.status = status
            self._save(self._path("users", access_key),
                       {"secret_key": u.secret_key, "status": status})
        self._notify("user", access_key)

    def remove_user(self, access_key: str) -> None:
        dropped_svc: list[str] = []
        dropped_sts: list[str] = []
        with self._mu:
            self.users.pop(access_key, None)
            self.user_policy.pop(access_key, None)
            self._delete(self._path("users", access_key))
            self._delete(self._path("policydb/users", access_key))
            # drop the user's service accounts + STS creds
            for ak, c in list(self.svc_accounts.items()):
                if c.parent_user == access_key:
                    self.svc_accounts.pop(ak, None)
                    self._delete(self._path("svcaccts", ak))
                    dropped_svc.append(ak)
            for ak, c in list(self.sts_creds.items()):
                if c.parent_user == access_key:
                    self.sts_creds.pop(ak, None)
                    self._delete(self._path("sts", ak))
                    dropped_sts.append(ak)
        self._notify_batch(
            [("user", access_key), ("user-policy", access_key)]
            + [("svcacct", ak) for ak in dropped_svc]
            + [("sts", ak) for ak in dropped_sts])

    def list_users(self) -> list[str]:
        with self._mu:
            return sorted(self.users)

    def add_members_to_group(self, group: str, members: list[str]) -> None:
        with self._mu:
            g = self.groups.setdefault(group,
                                       {"members": [], "status": "on"})
            for m in members:
                if m not in self.users:
                    raise IAMError(f"no such user {m}")
                if m not in g["members"]:
                    g["members"].append(m)
            self._save(self._path("groups", group), g)
        self._notify("group", group)

    def remove_members_from_group(self, group: str,
                                  members: list[str]) -> None:
        with self._mu:
            g = self.groups.get(group)
            if g is None:
                raise IAMError(f"no such group {group}")
            g["members"] = [m for m in g["members"] if m not in members]
            if g["members"]:
                self._save(self._path("groups", group), g)
            else:
                self.groups.pop(group, None)
                self.group_policy.pop(group, None)
                self._delete(self._path("groups", group))
                self._delete(self._path("policydb/groups", group))
        self._notify_batch([("group", group), ("group-policy", group)])

    def set_policy(self, name: str, policy: Policy) -> None:
        """Create/replace a named policy document."""
        with self._mu:
            self.policies[name] = policy
            self._save(self._path("policies", name),
                       json.loads(policy.to_json()))
        self._notify("policy", name)

    def delete_policy(self, name: str) -> None:
        with self._mu:
            if name in CANNED_POLICIES:
                raise IAMError(f"cannot delete canned policy {name}")
            self.policies.pop(name, None)
            self._delete(self._path("policies", name))
        self._notify("policy", name)

    def attach_policy(self, names: str | list[str], user: str = "",
                      group: str = "") -> None:
        """Map policy name(s) to a user or group (reference
        IAMSys.PolicyDBSet)."""
        if isinstance(names, str):
            names = [n.strip() for n in names.split(",") if n.strip()]
        with self._mu:
            for n in names:
                if n not in self.policies:
                    raise IAMError(f"no such policy {n}")
            if user:
                self.user_policy[user] = names
                self._save(self._path("policydb/users", user),
                           {"policy": names})
            elif group:
                self.group_policy[group] = names
                self._save(self._path("policydb/groups", group),
                           {"policy": names})
            else:
                raise IAMError("user or group required")
        if user:
            self._notify("user-policy", user)
        else:
            self._notify("group-policy", group)

    # ------------------------------------------------------------------
    # service accounts + STS
    # ------------------------------------------------------------------

    def new_service_account(self, parent_user: str,
                            access_key: str = "",
                            secret_key: str = "") -> Credentials:
        with self._mu:
            if not access_key:
                fresh = generate_credentials()
                access_key = fresh.access_key
                secret_key = fresh.secret_key
            cred = Credentials(access_key=access_key,
                               secret_key=secret_key,
                               parent_user=parent_user)
            self.svc_accounts[access_key] = cred
            self._save(self._path("svcaccts", access_key),
                       {"secret_key": secret_key, "parent": parent_user,
                        "status": "on"})
        self._notify("svcacct", access_key)
        return cred

    def _mint_sts(self, parent: str, duration_seconds: int
                  ) -> Credentials:
        """Shared STS mint-and-persist (one copy of the sts/ record
        format for assume_role and the federation paths)."""
        fresh = generate_credentials()
        token = base64.urlsafe_b64encode(secrets.token_bytes(24)).decode()
        cred = Credentials(
            access_key=fresh.access_key, secret_key=fresh.secret_key,
            session_token=token,
            expiration=time.time() + duration_seconds,
            parent_user=parent)
        with self._mu:
            self.sts_creds[cred.access_key] = cred
            self._save(self._path("sts", cred.access_key),
                       {"secret_key": cred.secret_key,
                        "session_token": cred.session_token,
                        "expiration": cred.expiration,
                        "parent": cred.parent_user})
        return cred

    def assume_role(self, parent_cred: Credentials,
                    duration_seconds: int = 3600) -> Credentials:
        """Mint temp credentials for an authenticated user (reference
        AssumeRole, cmd/sts-handlers.go:43-86)."""
        duration_seconds = max(900, min(duration_seconds, 7 * 24 * 3600))
        cred = self._mint_sts(
            parent_cred.parent_user or parent_cred.access_key,
            duration_seconds)
        self._notify("sts", cred.access_key)
        return cred

    def assume_role_with_claims(self, subject: str,
                                policy_names: Optional[list[str]],
                                duration_seconds: int = 3600,
                                max_seconds: Optional[float] = None
                                ) -> Credentials:
        """Mint temp credentials for a FEDERATED identity (OIDC subject
        or LDAP DN) — reference AssumeRoleWithWebIdentity/ClientGrants/
        LDAPIdentity minting (cmd/sts-handlers.go:43-86). The cred's
        parent is the federated subject; with policy_names given (OIDC
        policy claim) the subject's policy mapping is set from the
        token, with None (LDAP) the policy DB mapping for the DN — set
        by the admin beforehand — stays authoritative. max_seconds (the
        identity token's remaining lifetime) caps the minted cred AFTER
        the floor — credentials must never outlive the token that
        authenticated them (reference bounds STS expiry by JWT exp)."""
        duration_seconds = max(900, min(duration_seconds, 7 * 24 * 3600))
        if max_seconds is not None:
            duration_seconds = min(duration_seconds, int(max_seconds))
            if duration_seconds <= 0:
                raise IAMError("identity token already expired")
        cred = self._mint_sts(subject, duration_seconds)
        pairs = [("sts", cred.access_key)]
        if policy_names is not None:
            with self._mu:
                self.user_policy[subject] = list(policy_names)
                self._save(self._path("policydb/users", subject),
                           {"policy": list(policy_names)})
            pairs.append(("user-policy", subject))
        self._notify_batch(pairs)
        return cred

    # ------------------------------------------------------------------
    # the authorization surface the S3 handlers consume
    # ------------------------------------------------------------------

    def get_credentials(self, access_key: str) -> Optional[Credentials]:
        with self._mu:
            for table in (self.users, self.svc_accounts, self.sts_creds):
                c = table.get(access_key)
                if c is not None:
                    return c
        return None

    def account_of(self, access_key: str) -> Optional[str]:
        """The billing/QoS tenant an access key belongs to: service
        accounts and STS temp creds roll up to their parent user, plain
        users stand for themselves. None when the key is not registered
        here (the root credential lives outside the IAM tables)."""
        cred = self.get_credentials(access_key)
        if cred is None:
            return None
        return cred.parent_user or cred.access_key

    def _effective_policy_names(self, access_key: str) -> list[str]:
        names = list(self.user_policy.get(access_key, []))
        for g, info in self.groups.items():
            if info.get("status", "on") == "on" and \
                    access_key in info.get("members", []):
                names.extend(self.group_policy.get(g, []))
        return names

    def is_allowed(self, cred: Credentials, action: str, bucket: str,
                   object_name: str = "",
                   conditions: Optional[dict] = None) -> bool:
        """Identity-policy + bucket-policy union (reference
        IAMSys.IsAllowed + PolicyDBGet; temp/service creds evaluate their
        parent's policies)."""
        account = cred.parent_user or cred.access_key
        if cred.is_expired():
            return False
        args = PolicyArgs(account=account, action=action, bucket=bucket,
                          object=object_name,
                          conditions=dict(conditions or {}))
        with self._mu:
            names = self._effective_policy_names(account)
            docs = [self.policies[n] for n in names if n in self.policies]
        # bucket policy participates in the same deny/allow algebra
        if bucket and self.bucket_policy_lookup is not None:
            raw = self.bucket_policy_lookup(bucket)
            if raw:
                try:
                    docs.append(Policy.from_json(raw))
                except (ValueError, KeyError):
                    pass
        for doc in docs:
            # explicit deny in ANY applicable policy wins
            for st in doc.statements:
                if st.effect == "Deny" and st.applies(args):
                    return False
        return any(doc.is_allowed(args) for doc in docs)

    def is_anonymous_allowed(self, policy_json: str, action: str,
                             bucket: str, object_name: str = "",
                             conditions: Optional[dict] = None) -> bool:
        if not policy_json:
            return False
        try:
            doc = Policy.from_json(policy_json)
        except (ValueError, KeyError):
            return False
        return doc.is_allowed(PolicyArgs(
            account="*", action=action, bucket=bucket, object=object_name,
            conditions=dict(conditions or {})))
