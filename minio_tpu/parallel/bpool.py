"""Bounded byte-buffer pool (reference pkg/bpool.BytePoolCap, fed to the
erasure encoder at cmd/erasure-sets.go:374).

PUT streams stage each block batch in a same-width buffer; pooling them
caps allocation churn and puts a hard bound on staging memory. get()
blocks when the pool is exhausted — that back-pressure IS the admission
control for raw block memory. The pressure is observable: `waits`
counts gets that had to block, `exhausted` counts gets that timed out
(surfaced as minio_tpu_pipeline_bpool_* metrics), so a stalled pipeline
shows up on a dashboard instead of as a silent hang.
"""

from __future__ import annotations

import queue
from typing import Optional

from ..utils import lockcheck


class BytePoolExhausted(Exception):
    """get() timed out: every buffer is checked out and none returned
    within the deadline — the pipeline is stalled or the pool is
    undersized for the live stream count."""


class BytePool:
    def __init__(self, width: int, capacity: int):
        self.width = width
        self.capacity = capacity
        self.waits = 0          # get() calls that had to block
        self.exhausted = 0      # get() calls that timed out
        self._mu = lockcheck.mutex("bpool.created")
        self._created = 0       # buffers allocated so far (<= capacity)
        self._q: "queue.Queue[bytearray]" = queue.Queue(maxsize=capacity)

    def get(self, timeout: Optional[float] = None) -> bytearray:
        """A pooled buffer; allocated lazily up to `capacity` (an idle
        pool costs nothing), then blocks (up to `timeout` seconds,
        forever when None) while all buffers are checked out. Raises
        BytePoolExhausted on timeout."""
        try:
            return self._q.get_nowait()
        except queue.Empty:
            pass
        with self._mu:
            if self._created < self.capacity:
                self._created += 1
                return bytearray(self.width)
            self.waits += 1
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            with self._mu:
                self.exhausted += 1
            raise BytePoolExhausted(
                f"no {self.width}-byte staging buffer freed within "
                f"{timeout}s (capacity {self.capacity})") from None

    def put(self, buf: bytearray) -> None:
        if len(buf) != self.width:
            # a foreign-width buffer returned here would poison a later
            # get() with a wrong-geometry staging buffer — caller bug,
            # surface it
            raise ValueError(
                f"foreign buffer: width {len(buf)} != pool {self.width}")
        try:
            self._q.put_nowait(buf)
        except queue.Full:
            pass
