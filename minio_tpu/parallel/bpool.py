"""Bounded byte-buffer pool (reference pkg/bpool.BytePoolCap, fed to the
erasure encoder at cmd/erasure-sets.go:374).

PUT streams stage each block in a same-width buffer; pooling them caps
allocation churn and puts a hard bound on staging memory. get() blocks
when the pool is exhausted — that back-pressure IS the admission
control for raw block memory.
"""

from __future__ import annotations

import queue
from typing import Optional


class BytePool:
    def __init__(self, width: int, capacity: int):
        self.width = width
        self.capacity = capacity
        self._q: "queue.Queue[bytearray]" = queue.Queue(maxsize=capacity)
        for _ in range(capacity):
            self._q.put(bytearray(width))

    def get(self, timeout: Optional[float] = None) -> bytearray:
        return self._q.get(timeout=timeout)

    def put(self, buf: bytearray) -> None:
        if len(buf) != self.width:
            return                       # foreign buffer: drop it
        try:
            self._q.put_nowait(buf)
        except queue.Full:
            pass
