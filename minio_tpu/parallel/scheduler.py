"""Cross-request batch scheduler: one device dispatch for many requests.

The engine already batches blocks *within* one request; this scheduler
batches across CONCURRENT requests (BASELINE config #2: 32 concurrent
16 MiB PutObject streams) — the reference's per-set shared buffer pool
+ RAM-gated admission generalized into a device-batch former
(cmd/erasure-sets.go:374, cmd/handler-api.go:46-57).

PR 2 coalesced the PUT side only; the former is now a MULTI-VERB
device dispatcher covering every fused program of the data path:

  * ``encode``  — fused RS-encode + per-shard bitrot digest (PUT);
    with per-row cipher word arrays (sse=), fused ChaCha20 cipher +
    RS + digest — an encrypted batch is still ONE launch
  * ``decode``  — fused verify + reconstruct-missing-data (degraded
    GET); with sse=, verify + decode + decipher fused
  * ``recover`` — fused verify + rebuild-rows + re-digest (heal)
  * ``scan``    — vectorized S3 Select predicate over tokenized pages
    (scan/kernels.py): concurrent SelectObjectContent requests whose
    plan signature and page shape match stack their pages into ONE
    device launch — the analytics-read analog of the PUT coalescing

Concurrent callers hand (B_i, k, S) block groups to the submit_*
methods; a collector thread coalesces groups with identical
(verb, geometry, algorithm, survivor-mask) into one fused (ΣB_i, k, S)
device call through object/codec.py — which routes to parallel/mesh.py
``mesh_*`` sharded programs on a multi-chip pool — and scatters results
back. Under the axon tunnel each dispatch costs ~0.7 s wall —
coalescing N streams' work into one call divides that constant by N;
on real PCIe hosts it amortizes the ~10 ms dispatch + keeps MXU
batches full.

Occupancy smarts (PR 6):
  * a bucket that already holds >= max_batch blocks dispatches
    IMMEDIATELY instead of sleeping the grace window;
  * batch split points round down to multiples of the mesh ``dp`` axis
    so fused batches shard evenly across chips (no pad rows);
  * up to MINIO_TPU_SCHED_INFLIGHT (default 2) dispatches run
    concurrently, so host->device transfer of batch N+1 overlaps
    device compute of batch N.

Env knobs (README "Cross-request batch former"):
  MINIO_TPU_SCHED_MAX_BATCH=32    blocks per fused dispatch
  MINIO_TPU_SCHED_MAX_WAIT_MS=3   coalescing grace window
  MINIO_TPU_SCHED_INFLIGHT=2      concurrent dispatches in flight
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from ..utils import eventlog, knobs, lockcheck, telemetry

MAX_BATCH_BLOCKS = knobs.get_int("MINIO_TPU_SCHED_MAX_BATCH")
MAX_WAIT_S = knobs.get_float("MINIO_TPU_SCHED_MAX_WAIT_MS") / 1e3
INFLIGHT = max(1, knobs.get_int("MINIO_TPU_SCHED_INFLIGHT"))

VERBS = ("encode", "decode", "recover", "scan")

# live schedulers, summed by the registry collector at exposition time
_SCHEDULERS: "weakref.WeakSet[BatchScheduler]" = weakref.WeakSet()

# dispatch totals are MONOTONIC — registered as real Counters (bumped at
# dispatch time, labelled by verb) so Prometheus rate() works; only the
# instantaneous queue/occupancy values stay exposition-time gauges
_BATCHES_TOTAL = telemetry.REGISTRY.counter(
    "minio_tpu_sched_batches_total", "Fused device dispatches issued")
_COALESCED_TOTAL = telemetry.REGISTRY.counter(
    "minio_tpu_sched_coalesced_total",
    "Groups that shared another request's dispatch")
# dispatch-time attribution (ISSUE 13 pillar c): where a fused device
# dispatch spends its time, per verb — "queue" (submit -> dispatch
# start in the former), "transfer" (host batch assembly the dispatch
# thread performs before launch), "compute" (device program to
# completion), "fetch" (device->host readback + result assembly).
# Sub-ms buckets: a dispatch stage on a warm path is 10µs-100ms.
_STAGE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                  0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0)
_DISPATCH_STAGE_SECONDS = telemetry.REGISTRY.histogram(
    "minio_tpu_device_dispatch_seconds",
    "Fused device dispatch stage timings (queue/transfer/compute/"
    "fetch) per verb", buckets=_STAGE_BUCKETS)


def _collect_scheduler_metrics() -> None:
    reg = telemetry.REGISTRY
    queued_groups = queued_blocks = batches = blocks = inflight = 0
    verbs: dict[str, list[int]] = {v: [0, 0] for v in VERBS}
    for s in list(_SCHEDULERS):
        st = s.stats()
        queued_groups += st["queued_groups"]
        queued_blocks += st["queued_blocks"]
        batches += st["batches"]
        blocks += st["dispatched_blocks"]
        inflight += st["inflight"]
        for v, vs in st["verbs"].items():
            verbs[v][0] += vs["batches"]
            verbs[v][1] += vs["coalesced"]
    reg.gauge("minio_tpu_sched_inflight_dispatches",
              "Device dispatches currently airborne (transfer/compute "
              "overlap depth)").set(inflight)
    reg.gauge("minio_tpu_sched_queue_depth",
              "Work groups waiting on the batch former").set(
        queued_groups)
    reg.gauge("minio_tpu_sched_queued_blocks",
              "Blocks waiting on the batch former").set(queued_blocks)
    reg.gauge("minio_tpu_sched_batch_occupancy_blocks",
              "Mean blocks per fused dispatch (MXU batch fill)").set(
        round(blocks / batches, 3) if batches else 0)
    g = reg.gauge("minio_tpu_sched_batch_occupancy_groups",
                  "Mean request groups per fused dispatch, by verb")
    for v, (b, c) in verbs.items():
        g.set(round((b + c) / b, 3) if b else 0, verb=v)


telemetry.REGISTRY.register_collector(_collect_scheduler_metrics)


class _Pending:
    __slots__ = ("data", "payload", "blocks", "event", "out", "error",
                 "span", "t_submit")

    def __init__(self, data: Optional[np.ndarray] = None,
                 payload=None, blocks: Optional[int] = None):
        # erasure verbs carry one (B, k, S) array; the scan verb
        # carries its typed page arrays as an opaque payload — `blocks`
        # is the occupancy unit either way (erasure blocks / pages)
        self.data = data
        self.payload = payload
        self.blocks = int(data.shape[0]) if blocks is None else blocks
        self.event = threading.Event()
        self.out = None
        self.error: Optional[Exception] = None
        # submitter's span: the collector thread is shared across
        # requests, so dispatch spans are attached explicitly
        self.span = None
        # queue-wait attribution: submit time -> dispatch start
        self.t_submit = time.perf_counter()


class DispatchFuture:
    """Handle for one submitted work group — the non-blocking dispatch
    seam of the data paths: the caller submits and moves on; it
    resolves the future when it actually needs the result (the fork's
    async QAT kernel launch pattern).

    result() returns the verb's tuple — encode (full, digests); decode
    (missing, missing_idx, survivor_digests); recover (out, idxs,
    survivor_digests, out_digests) — or None when the work must take
    the caller's local CPU path."""

    __slots__ = ("_pending", "_value")

    def __init__(self, pending: Optional[_Pending] = None, value=None):
        self._pending = pending
        self._value = value

    def done(self) -> bool:
        return self._pending is None or self._pending.event.is_set()

    def result(self, timeout: Optional[float] = None):
        p = self._pending
        if p is None:
            return self._value
        if not p.event.wait(timeout):
            raise TimeoutError("batch dispatch did not complete")
        if p.error is not None:
            raise p.error
        return p.out


# back-compat alias (PR 2 name; the PUT pipeline docstrings use it)
EncodeFuture = DispatchFuture


def _mesh_dp() -> int:
    """Batch-axis width of the active device mesh (1 = single device)."""
    try:
        from ..object.codec import _mesh_active
        mesh = _mesh_active()
        return int(mesh.devices.shape[0]) if mesh is not None else 1
    except Exception:  # noqa: BLE001 — a broken backend never stalls dispatch
        return 1


class BatchScheduler:
    """Geometry-bucketed multi-verb device-batch former."""

    def __init__(self, max_batch: int = MAX_BATCH_BLOCKS,
                 max_wait: float = MAX_WAIT_S,
                 inflight: int = INFLIGHT):
        self.max_batch = max_batch
        self.max_wait = max_wait
        self._mu = lockcheck.mutex("sched.buckets")
        # (verb, k, m, S, algo_value, extra) -> list[_Pending]
        self._buckets: dict[tuple, list[_Pending]] = {}
        self._bucket_blocks: dict[tuple, int] = {}
        self._kick = threading.Condition(self._mu)
        self._stop = False
        self.batches = 0              # dispatch counter (tests/metrics)
        self.coalesced = 0            # groups that shared a dispatch
        self.dispatched_blocks = 0    # blocks through the device path
        self.verb_stats = {v: {"batches": 0, "coalesced": 0, "blocks": 0}
                           for v in VERBS}
        # stage attribution (queue/transfer/compute/fetch histograms +
        # per-dispatch child spans); `off` is the overhead-A/B escape
        # hatch (bench.py --ab-obs re-measures telemetry_overhead_x)
        self.attrib = knobs.get_bool("MINIO_TPU_SCHED_ATTRIB")
        self._airborne = 0            # dispatches currently in flight
        # keeping `inflight` dispatches airborne overlaps batch N+1's
        # host->device transfer with batch N's compute
        self._inflight = threading.BoundedSemaphore(max(1, inflight))
        # scan dispatches get their OWN slot: a Select with a fresh
        # plan signature pays a jax.jit trace+compile (seconds) inside
        # its dispatch — sharing slots would park latency-critical
        # erasure PUT/GET batches behind Select compile time
        self._inflight_scan = threading.BoundedSemaphore(1)
        self._pool = ThreadPoolExecutor(max_workers=max(1, inflight) + 1,
                                        thread_name_prefix="sched-dispatch")
        self._thread = threading.Thread(target=self._collector,
                                        daemon=True)
        self._thread.start()
        _SCHEDULERS.add(self)

    def stats(self) -> dict:
        """Queue depth + dispatch occupancy for the metrics registry."""
        with self._mu:
            plists = list(self._buckets.values())
            queued_groups = sum(len(pl) for pl in plists)
            queued_blocks = sum(p.blocks for pl in plists
                                for p in pl)
            return {"queued_groups": queued_groups,
                    "queued_blocks": queued_blocks,
                    "batches": self.batches,
                    "coalesced": self.coalesced,
                    "dispatched_blocks": self.dispatched_blocks,
                    "inflight": self._airborne,
                    "verbs": {v: dict(s)
                              for v, s in self.verb_stats.items()}}

    def close(self) -> None:
        """Flush pending groups (CPU-route them: waiters resolve to
        None and fall back to their local paths), join the collector,
        and drain the in-flight dispatches."""
        with self._mu:
            if self._stop:
                return
            self._stop = True
            self._kick.notify_all()
        self._thread.join(timeout=10)
        # in-flight dispatches finish and resolve their waiters
        self._pool.shutdown(wait=True)

    # -- caller side -------------------------------------------------------

    def _declined(self, codec, algo) -> bool:
        from .. import bitrot as bitrot_mod
        if algo not in (bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256,
                        bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256S,
                        bitrot_mod.BitrotAlgorithm.SHA256):
            eventlog.emit_once("device.decline", stage="scheduler",
                               reason="algo")
            return True
        if codec.m == 0:
            eventlog.emit_once("device.decline", stage="scheduler",
                               reason="no-parity")
            return True
        # No device, no reason to queue: without a TPU (or an active
        # multi-device mesh) the dispatch always CPU-routes, so the
        # grace window + wakeup round-trip (~max_wait per batch) would
        # be pure hot-path overhead. With a device path present, small
        # batches still enqueue — coalescing with concurrent streams is
        # what pushes them over the routing threshold.
        from ..object.codec import _device_is_tpu, _mesh_active
        declined = not _device_is_tpu() and _mesh_active() is None
        if declined:
            eventlog.emit_once("device.decline", stage="scheduler",
                               reason="no-device")
        return declined

    def _enqueue(self, key: tuple, data: np.ndarray) -> DispatchFuture:
        return self._enqueue_pending(
            key, _Pending(np.ascontiguousarray(data, np.uint8)))

    def _enqueue_pending(self, key: tuple, p: _Pending) -> DispatchFuture:
        p.span = telemetry.current_span()
        with self._mu:
            if self._stop:
                return DispatchFuture()
            self._buckets.setdefault(key, []).append(p)
            self._bucket_blocks[key] = \
                self._bucket_blocks.get(key, 0) + p.blocks
            self._kick.notify_all()
        return DispatchFuture(p)

    def submit(self, codec, data: np.ndarray, algo,
               sse=None) -> DispatchFuture:
        """Non-blocking fused encode+digest dispatch: enqueue the
        (B, k, S) group on the batch former and return immediately. The
        future resolves to (full, digests), or to None when the work
        can't ride the device path (the caller falls back to its local
        CPU path) — declined submissions return an already-done
        future.

        sse = (keys (B, 8), nonces (B, P, 3), pkg_bytes) turns the
        dispatch into the fused cipher+RS+digest program (codec.
        encrypt_encode_and_hash_batch): the word arrays ride the batch
        like survivor masks do, but the bucket key carries only their
        GEOMETRY (package count + size) — concurrent encrypted PUTs
        from different objects, under different keys, coalesce into one
        launch. The resolved `full` then holds CIPHERTEXT data rows."""
        if self._declined(codec, algo):
            return DispatchFuture()
        if sse is None:
            key = ("encode", codec.k, codec.m, data.shape[-1],
                   algo.value, None)
            return self._enqueue(key, data)
        keys, nonces, pkg_bytes = sse
        key = ("encode", codec.k, codec.m, data.shape[-1], algo.value,
               ("sse", nonces.shape[1], pkg_bytes))
        p = _Pending(np.ascontiguousarray(data, np.uint8),
                     payload=(np.ascontiguousarray(keys, np.uint32),
                              np.ascontiguousarray(nonces, np.uint32)))
        return self._enqueue_pending(key, p)

    def submit_decode(self, codec, survivors: np.ndarray,
                      present_mask: int, shard_len: int, algo,
                      sse=None) -> DispatchFuture:
        """Non-blocking fused verify+decode dispatch for a degraded-GET
        bucket: survivors (B, k, S) stacked in missing_data_matrix
        `used` order. Resolves to (missing, missing_idx,
        survivor_digests) or None (caller host-decodes).

        sse = (keys, nonces, pkg_bytes) requests the fused verify →
        decode → DECIPHER program (codec.verify_decode_decrypt_batch):
        the resolved first element is then the deciphered (B, k, S)
        data-shard stack in shard-index order instead of the missing
        ciphertext rows."""
        if self._declined(codec, algo):
            return DispatchFuture()
        if sse is None:
            key = ("decode", codec.k, codec.m, survivors.shape[-1],
                   algo.value, (present_mask, shard_len))
            return self._enqueue(key, survivors)
        keys, nonces, pkg_bytes = sse
        key = ("decode", codec.k, codec.m, survivors.shape[-1],
               algo.value, (present_mask, shard_len, "sse",
                            nonces.shape[1], pkg_bytes))
        p = _Pending(np.ascontiguousarray(survivors, np.uint8),
                     payload=(np.ascontiguousarray(keys, np.uint32),
                              np.ascontiguousarray(nonces, np.uint32)))
        return self._enqueue_pending(key, p)

    def submit_recover(self, codec, survivors: np.ndarray,
                       present_mask: int, rows, shard_len: int, algo
                       ) -> DispatchFuture:
        """Non-blocking fused verify+recover+rehash dispatch for a heal
        bucket: survivors (B, k, S) in recover_matrix `used` order.
        Resolves to (out, idxs, survivor_digests, out_digests) or
        None (caller host-rebuilds)."""
        if self._declined(codec, algo):
            return DispatchFuture()
        key = ("recover", codec.k, codec.m, survivors.shape[-1],
               algo.value, (present_mask, frozenset(rows), shard_len))
        return self._enqueue(key, survivors)

    def submit_scan(self, pages) -> DispatchFuture:
        """Non-blocking device-scan dispatch for one Select request's
        tokenized page set (scan/pager.Pages): pages bucket by (plan
        signature, page shape) so concurrent identical queries coalesce
        into one kernel launch. Resolves to the boolean row mask
        [B, R], or None (caller falls back to the CPU evaluator)."""
        from ..scan import kernels as scan_kernels
        if not scan_kernels.device_allowed():
            return DispatchFuture()
        key = ("scan", 0, 0, pages.shape_key(),
               pages.plan.signature, None)
        p = _Pending(payload=(pages.plan, pages.arrays),
                     blocks=pages.n_pages)
        return self._enqueue_pending(key, p)

    def encode_and_hash(self, codec, data: np.ndarray, algo, sse=None
                        ) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """Blocking fused encode+digest via the shared batch former
        (submit + wait); `sse` as in submit()."""
        return self.submit(codec, data, algo, sse=sse).result()

    # -- collector ---------------------------------------------------------

    def _full_bucket_locked(self) -> bool:
        return any(b >= self.max_batch
                   for b in self._bucket_blocks.values())

    def _collector(self) -> None:
        while True:
            with self._mu:
                while not self._buckets and not self._stop:
                    self._kick.wait(0.25)
                if not self._stop and not self._full_bucket_locked():
                    # small grace window lets concurrent streams
                    # coalesce — but a bucket that is ALREADY full
                    # dispatches now (waiting could not improve its
                    # occupancy, only its latency), and a bucket that
                    # FILLS mid-window cuts the wait short
                    deadline = time.monotonic() + self.max_wait
                    while (not self._stop
                           and not self._full_bucket_locked()):
                        rem = deadline - time.monotonic()
                        if rem <= 0:
                            break
                        self._kick.wait(rem)
                # drain EVERY ready bucket this wakeup: mixed verbs and
                # geometries (12+4 PUTs concurrent with 4+2 degraded
                # GETs) must not serialize behind each other's grace
                # windows (VERDICT r2 weak #5)
                ready = list(self._buckets.items())
                self._buckets.clear()
                self._bucket_blocks.clear()
                stopping = self._stop
            for key, plist in ready:
                if stopping:
                    # close() flush: CPU-route — out stays None, every
                    # waiter falls back to its local path
                    for p in plist:
                        p.event.set()
                else:
                    self._split_dispatch(key, plist)
            if stopping:
                return

    def _split_dispatch(self, key: tuple, plist: list) -> None:
        """Split one bucket into <= cap-block groups and launch them on
        the dispatch pool (bounded to `inflight` airborne at once)."""
        # round the split cap DOWN to a multiple of the mesh dp axis so
        # fused batches shard evenly across chips instead of padding
        cap = self.max_batch
        dp = _mesh_dp()
        if dp > 1 and cap > dp:
            cap -= cap % dp
        groups: list[list] = []
        cur: list = []
        n_blocks = 0
        for p in plist:
            b = p.blocks
            if cur and n_blocks + b > cap:
                groups.append(cur)
                cur, n_blocks = [], 0
            cur.append(p)
            n_blocks += b
        if cur:
            groups.append(cur)
        sem = self._inflight_scan if key[0] == "scan" \
            else self._inflight
        for group in groups:
            sem.acquire()
            try:
                self._pool.submit(self._dispatch_group, key, group, sem)
            except BaseException:  # noqa: BLE001 — pool gone (close race)
                # same contract as the stopping flush: CPU-route (out
                # stays None) so waiters fall back to their local
                # paths instead of failing work the host can serve
                sem.release()
                for p in group:
                    p.event.set()

    def _dispatch_group(self, key: tuple, group: list,
                        sem: threading.Semaphore) -> None:
        with self._mu:
            self._airborne += 1
        try:
            self._dispatch_one(key, group)
        except Exception as e:  # noqa: BLE001 — surfaced to every waiter
            for p in group:
                if not p.event.is_set():
                    p.error = e
                    p.event.set()
        finally:
            with self._mu:
                self._airborne -= 1
            sem.release()

    def _dispatch_one(self, key: tuple, group: list) -> None:
        verb = key[0]
        attrib = self.attrib
        # stage -> seconds for this dispatch ("transfer" is filled by
        # the batch-assembly timer below; "compute"/"fetch" by the
        # codec/kernel stage callback)
        stages: dict[str, float] = {}
        stage_cb = stages.__setitem__ if attrib else None
        t0_wall, t0 = time.time(), time.perf_counter()
        if verb == "scan":
            out = self._run_scan(group, stage_cb)
        else:
            out = self._run_erasure(key, group, stage_cb)
        dt = time.perf_counter() - t0
        nb = sum(p.blocks for p in group)
        with self._mu:
            self.batches += 1
            self.coalesced += len(group) - 1
            self.dispatched_blocks += nb
            vs = self.verb_stats[verb]
            vs["batches"] += 1
            vs["coalesced"] += len(group) - 1
            vs["blocks"] += nb
        _BATCHES_TOTAL.inc(verb=verb)
        if len(group) > 1:
            _COALESCED_TOTAL.inc(len(group) - 1, verb=verb)
        # a dispatch that DECLINED to the device (out is None: CPU
        # routing) must not feed the device-dispatch histogram — a
        # deviceless box would otherwise fill queue/transfer series
        # with no matching compute, misattributing time to launches
        # that never happened
        if attrib and out is not None:
            for p in group:
                _DISPATCH_STAGE_SECONDS.observe(
                    max(t0 - p.t_submit, 0.0), verb=verb, stage="queue")
            for stage, sdt in stages.items():
                _DISPATCH_STAGE_SECONDS.observe(sdt, verb=verb,
                                                stage=stage)
        for p in group:
            if p.span is not None:
                # the collector/dispatch threads serve many requests:
                # attach the dispatch to each submitter's tree as an
                # externally-timed span, with the stage split as its
                # children — /spans?sort=slowest answers WHERE a slow
                # PUT/GET/heal/scan went (former queue? transfer?
                # device compute? readback?)
                d = telemetry.attach_span(
                    p.span, "sched.dispatch", t0_wall, dt, verb=verb,
                    blocks=nb, coalesced=len(group) - 1)
                if d is not None and attrib and out is not None:
                    qw = max(t0 - p.t_submit, 0.0)
                    telemetry.attach_span(d, "sched.queue",
                                          t0_wall - qw, qw)
                    off = t0_wall
                    for stage in ("transfer", "compute", "fetch"):
                        sdt = stages.get(stage)
                        if sdt is not None:
                            telemetry.attach_span(d, f"sched.{stage}",
                                                  off, sdt)
                            off += sdt
        if out is None:
            # CPU routing: let each caller use its own path
            for p in group:
                p.event.set()
            return
        at = 0
        for p in group:
            b = p.blocks
            if verb == "encode":
                full, digests = out
                p.out = (full[at:at + b], digests[at:at + b])
            elif verb == "decode":
                missing, missing_idx, sdig = out
                p.out = (missing[at:at + b], missing_idx,
                         sdig[at:at + b])
            elif verb == "recover":
                rec, idxs, sdig, odig = out
                p.out = (rec[at:at + b], idxs, sdig[at:at + b],
                         odig[at:at + b])
            else:                                # scan: row masks
                p.out = out[at:at + b]
            at += b
            p.event.set()

    @staticmethod
    def _run_erasure(key: tuple, group: list, stage_cb=None):
        from ..object.codec import Codec
        from .. import bitrot as bitrot_mod
        verb, k, m, s, algo_value, extra = key
        algo = bitrot_mod.BitrotAlgorithm.from_string(algo_value)
        codec = Codec(k, m, s * k)
        t0 = time.perf_counter()
        data = np.concatenate([p.data for p in group], axis=0) \
            if len(group) > 1 else group[0].data
        if stage_cb is not None:
            # host-side batch staging: the fused input's assembly into
            # one contiguous array the device upload reads from
            stage_cb("transfer", time.perf_counter() - t0)

        def _sse_arrays():
            # per-row key/nonce word arrays concatenate across the
            # group exactly like the shard data does
            if len(group) == 1:
                return group[0].payload
            return (np.concatenate([p.payload[0] for p in group]),
                    np.concatenate([p.payload[1] for p in group]))

        if verb == "encode":
            if extra is not None and extra[0] == "sse":
                keys, nonces = _sse_arrays()
                return codec.encrypt_encode_and_hash_batch(
                    data, keys, nonces, extra[2], algo,
                    stage_cb=stage_cb)
            return codec.encode_and_hash_batch(data, algo,
                                               stage_cb=stage_cb)
        if verb == "decode":
            if len(extra) > 2 and extra[2] == "sse":
                keys, nonces = _sse_arrays()
                return codec.verify_decode_decrypt_batch(
                    data, extra[0], extra[1], keys, nonces, extra[4],
                    algo, stage_cb=stage_cb)
            mask, shard_len = extra
            return codec.verify_and_decode_batch(data, mask, shard_len,
                                                 algo, stage_cb=stage_cb)
        mask, rows, shard_len = extra
        return codec.verify_and_recover_batch(data, mask, set(rows),
                                              shard_len, algo,
                                              stage_cb=stage_cb)

    @staticmethod
    def _run_scan(group: list, stage_cb=None):
        """One coalesced kernel launch over every member's pages: the
        plan is identical across the group (the bucket keys on its
        signature), pages stack along the batch axis."""
        from ..scan import kernels as scan_kernels
        plan = group[0].payload[0]
        t0 = time.perf_counter()
        if len(group) == 1:
            arrays = group[0].payload[1]
        else:
            names = group[0].payload[1].keys()
            arrays = {name: np.concatenate(
                [p.payload[1][name] for p in group], axis=0)
                for name in names}
        if stage_cb is not None:
            stage_cb("transfer", time.perf_counter() - t0)
        t1 = time.perf_counter()
        out = scan_kernels.run_batch(plan, arrays)
        if stage_cb is not None:
            # run_batch returns host arrays: compute + readback land in
            # one "compute" stage for the scan verb
            stage_cb("compute", time.perf_counter() - t1)
        return out


# ---------------------------------------------------------------------------
# RAM-budgeted request admission (cmd/handler-api.go:46-57)
# ---------------------------------------------------------------------------

def requests_budget(block_size: int, set_drive_count: int,
                    ram_fraction: float = 0.5) -> int:
    """max in-flight object requests = min(RAM budget, CPU budget).

    RAM: RAM/2 / (blockSize·driveCount + 2·blockSize) — the reference's
    per-request staging footprint (cmd/handler-api.go:46-57). CPU: the
    reference's Go runtime timeshares cheaply, but here each data-path
    request runs real erasure+hash work between GIL releases — admitting
    far more streams than cores just splits the cache working set and
    convoys the GIL (measured: 32 concurrent PUTs on one core run at
    half the aggregate of 4). Waiters queue on the admission semaphore,
    so capped requests are delayed, not refused."""
    total = _total_ram()
    per_req = block_size * set_drive_count + 2 * block_size
    ram_budget = int(total * ram_fraction) // max(per_req, 1)
    cpu_budget = 8 * (os.cpu_count() or 1)
    return max(8, min(ram_budget, cpu_budget))


def _total_ram() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 8 << 30
