"""Cross-request batch scheduler: one device dispatch for many PUTs.

The engine already batches blocks *within* one PUT stream; this
scheduler batches across CONCURRENT streams (BASELINE config #2: 32
concurrent 16 MiB PutObject streams) — the reference's per-set shared
buffer pool + RAM-gated admission generalized into a device-batch
former (cmd/erasure-sets.go:374, cmd/handler-api.go:46-57).

Concurrent callers hand (B_i, k, S) block groups to encode_and_hash();
a collector thread coalesces groups with identical geometry into one
(ΣB_i, k, S) fused encode+digest device call and scatters results back.
Under the axon tunnel each dispatch costs ~0.7 s wall — coalescing N
streams' work into one call divides that constant by N; on real PCIe
hosts it amortizes the ~10 ms dispatch + keeps MXU batches full.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Optional

import numpy as np

from ..utils import telemetry

MAX_BATCH_BLOCKS = int(os.environ.get("MINIO_TPU_SCHED_MAX_BATCH", "32"))
MAX_WAIT_S = float(os.environ.get("MINIO_TPU_SCHED_MAX_WAIT_MS", "3")) / 1e3

# live schedulers, summed by the registry collector at exposition time
_SCHEDULERS: "weakref.WeakSet[BatchScheduler]" = weakref.WeakSet()


def _collect_scheduler_metrics() -> None:
    reg = telemetry.REGISTRY
    queued_groups = queued_blocks = batches = coalesced = blocks = 0
    for s in list(_SCHEDULERS):
        st = s.stats()
        queued_groups += st["queued_groups"]
        queued_blocks += st["queued_blocks"]
        batches += st["batches"]
        coalesced += st["coalesced"]
        blocks += st["dispatched_blocks"]
    reg.gauge("minio_tpu_sched_queue_depth",
              "Encode groups waiting on the batch former").set(
        queued_groups)
    reg.gauge("minio_tpu_sched_queued_blocks",
              "Blocks waiting on the batch former").set(queued_blocks)
    reg.gauge("minio_tpu_sched_batches_total",
              "Fused device dispatches issued").set(batches)
    reg.gauge("minio_tpu_sched_coalesced_total",
              "Groups that shared another stream's dispatch").set(
        coalesced)
    reg.gauge("minio_tpu_sched_batch_occupancy_blocks",
              "Mean blocks per fused dispatch (MXU batch fill)").set(
        round(blocks / batches, 3) if batches else 0)


telemetry.REGISTRY.register_collector(_collect_scheduler_metrics)


class _Pending:
    __slots__ = ("data", "event", "full", "digests", "error", "span")

    def __init__(self, data: np.ndarray):
        self.data = data
        self.event = threading.Event()
        self.full: Optional[np.ndarray] = None
        self.digests: Optional[np.ndarray] = None
        self.error: Optional[Exception] = None
        # submitter's span: the collector thread is shared across
        # requests, so dispatch spans are attached explicitly
        self.span = None


class EncodeFuture:
    """Handle for one submitted encode+digest group — the non-blocking
    dispatch seam of the PUT pipeline: the reader thread submits and
    moves on; the write stage resolves the future when it actually
    needs the shards (the fork's async QAT kernel launch pattern).

    result() returns (full, digests) or None when the work must take
    the caller's local CPU path."""

    __slots__ = ("_pending", "_value")

    def __init__(self, pending: Optional[_Pending] = None, value=None):
        self._pending = pending
        self._value = value

    def done(self) -> bool:
        return self._pending is None or self._pending.event.is_set()

    def result(self, timeout: Optional[float] = None):
        p = self._pending
        if p is None:
            return self._value
        if not p.event.wait(timeout):
            raise TimeoutError("encode dispatch did not complete")
        if p.error is not None:
            raise p.error
        if p.full is None:
            return None
        return p.full, p.digests


class BatchScheduler:
    """Geometry-bucketed device-batch former for encode+bitrot work."""

    def __init__(self, max_batch: int = MAX_BATCH_BLOCKS,
                 max_wait: float = MAX_WAIT_S):
        self.max_batch = max_batch
        self.max_wait = max_wait
        self._mu = threading.Lock()
        # (k, m, S, algo_value) -> list[_Pending]
        self._buckets: dict[tuple, list[_Pending]] = {}
        self._kick = threading.Condition(self._mu)
        self._stop = False
        self.batches = 0              # dispatch counter (tests/metrics)
        self.coalesced = 0            # groups that shared a dispatch
        self.dispatched_blocks = 0    # blocks through the device path
        self._thread = threading.Thread(target=self._collector,
                                        daemon=True)
        self._thread.start()
        _SCHEDULERS.add(self)

    def stats(self) -> dict:
        """Queue depth + dispatch occupancy for the metrics registry."""
        with self._mu:
            plists = list(self._buckets.values())
            queued_groups = sum(len(pl) for pl in plists)
            queued_blocks = sum(p.data.shape[0] for pl in plists
                                for p in pl)
            return {"queued_groups": queued_groups,
                    "queued_blocks": queued_blocks,
                    "batches": self.batches,
                    "coalesced": self.coalesced,
                    "dispatched_blocks": self.dispatched_blocks}

    def close(self) -> None:
        with self._mu:
            self._stop = True
            self._kick.notify_all()

    # -- caller side -------------------------------------------------------

    def submit(self, codec, data: np.ndarray, algo) -> EncodeFuture:
        """Non-blocking fused encode+digest dispatch: enqueue the group
        on the batch former and return immediately. The future resolves
        to (full, digests), or to None when the work can't ride the
        device path (the caller falls back to its local CPU path) —
        declined submissions return an already-done future."""
        from .. import bitrot as bitrot_mod
        if algo not in (bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256,
                        bitrot_mod.BitrotAlgorithm.HIGHWAYHASH256S,
                        bitrot_mod.BitrotAlgorithm.SHA256):
            return EncodeFuture()
        if codec.m == 0:
            return EncodeFuture()
        # No device, no reason to queue: without a TPU (or an active
        # multi-device mesh) the dispatch always CPU-routes, so the
        # grace window + wakeup round-trip (~max_wait per encode batch)
        # would be pure hot-path overhead. With a device path present,
        # small batches still enqueue — coalescing with concurrent
        # streams is what pushes them over the routing threshold.
        from ..object.codec import _device_is_tpu, _mesh_active
        if not _device_is_tpu() and _mesh_active() is None:
            return EncodeFuture()
        key = (codec.k, codec.m, data.shape[-1], algo.value)
        p = _Pending(np.ascontiguousarray(data, np.uint8))
        p.span = telemetry.current_span()
        with self._mu:
            if self._stop:
                return EncodeFuture()
            self._buckets.setdefault(key, []).append(p)
            self._kick.notify_all()
        return EncodeFuture(p)

    def encode_and_hash(self, codec, data: np.ndarray, algo
                        ) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """Blocking fused encode+digest via the shared batch former
        (submit + wait)."""
        return self.submit(codec, data, algo).result()

    # -- collector ---------------------------------------------------------

    def _collector(self) -> None:
        while True:
            with self._mu:
                while not self._buckets and not self._stop:
                    self._kick.wait(0.25)
                if self._stop:
                    for plist in self._buckets.values():
                        for p in plist:
                            p.event.set()
                    self._buckets.clear()
                    return
                # small grace window lets concurrent streams coalesce
                self._kick.wait(self.max_wait)
                # drain EVERY ready geometry bucket this wakeup: mixed
                # geometries (12+4 PUTs concurrent with 4+2 RRS) must
                # not serialize behind each other's grace windows
                # (VERDICT r2 weak #5)
                ready = list(self._buckets.items())
                self._buckets.clear()
            for key, plist in ready:
                self._dispatch(key, plist)

    def _dispatch(self, key: tuple, plist: list) -> None:
        from ..object.codec import Codec
        from .. import bitrot as bitrot_mod
        k, m, s, algo_value = key
        algo = bitrot_mod.BitrotAlgorithm.from_string(algo_value)
        try:
            # cap one device call at max_batch blocks; loop the rest
            groups: list[list] = []
            cur: list = []
            n_blocks = 0
            for p in plist:
                b = p.data.shape[0]
                if cur and n_blocks + b > self.max_batch:
                    groups.append(cur)
                    cur, n_blocks = [], 0
                cur.append(p)
                n_blocks += b
            if cur:
                groups.append(cur)
            codec = Codec(k, m, s * k)
            for group in groups:
                data = np.concatenate([p.data for p in group], axis=0)
                t0_wall, t0 = time.time(), time.perf_counter()
                out = codec.encode_and_hash_batch(data, algo)
                dt = time.perf_counter() - t0
                self.batches += 1
                self.coalesced += len(group) - 1
                with self._mu:
                    self.dispatched_blocks += data.shape[0]
                for p in group:
                    if p.span is not None:
                        # the collector thread serves many requests:
                        # attach the dispatch to each submitter's tree
                        # as an externally-timed span
                        telemetry.attach_span(
                            p.span, "sched.dispatch", t0_wall, dt,
                            blocks=int(data.shape[0]),
                            coalesced=len(group) - 1)
                if out is None:
                    # CPU routing: let each caller use its own path
                    for p in group:
                        p.full = None
                        p.event.set()
                    continue
                full, digests = out
                at = 0
                for p in group:
                    b = p.data.shape[0]
                    p.full = full[at:at + b]
                    p.digests = digests[at:at + b]
                    at += b
                    p.event.set()
        except Exception as e:  # noqa: BLE001 — surfaced to every waiter
            for p in plist:
                if not p.event.is_set():
                    p.error = e
                    p.event.set()


# ---------------------------------------------------------------------------
# RAM-budgeted request admission (cmd/handler-api.go:46-57)
# ---------------------------------------------------------------------------

def requests_budget(block_size: int, set_drive_count: int,
                    ram_fraction: float = 0.5) -> int:
    """max in-flight object requests = min(RAM budget, CPU budget).

    RAM: RAM/2 / (blockSize·driveCount + 2·blockSize) — the reference's
    per-request staging footprint (cmd/handler-api.go:46-57). CPU: the
    reference's Go runtime timeshares cheaply, but here each data-path
    request runs real erasure+hash work between GIL releases — admitting
    far more streams than cores just splits the cache working set and
    convoys the GIL (measured: 32 concurrent PUTs on one core run at
    half the aggregate of 4). Waiters queue on the admission semaphore,
    so capped requests are delayed, not refused."""
    total = _total_ram()
    per_req = block_size * set_drive_count + 2 * block_size
    ram_budget = int(total * ram_fraction) // max(per_req, 1)
    cpu_budget = 8 * (os.cpu_count() or 1)
    return max(8, min(ram_budget, cpu_budget))


def _total_ram() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 8 << 30
