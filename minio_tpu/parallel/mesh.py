"""Multi-chip sharding of the erasure data path.

Mapping of the reference's distribution axes onto a TPU mesh (reference
parallelism inventory: SURVEY §2.5):

  dp ("data")     — independent objects/blocks: batch dim of the shard
                    tensors. The analog of the reference's per-request
                    goroutine fan-out (its RAM-gated admission control).
  sp ("sequence") — byte columns of a block. Blocks are GF-columnwise
                    independent, so a huge object's bytes shard across
                    chips with zero cross-talk in encode/decode — the
                    storage analog of sequence/context parallelism (no
                    ring needed; the "attention" here is column-local).
  tp              — output-shard rows (the coding matrix's rows) can be
                    row-sharded for very wide sets; with n <= 32 shards
                    the matrix is tiny, so tp is folded into dp unless
                    explicitly requested.
  ep              — erasure-set routing (sipHashMod object->set) stays on
                    the host control plane (object/sets.py), exactly like
                    the reference's static "expert" routing.

Collectives used (all ride ICI inside a pool): all_gather to reassemble
per-shard integrity tags across sp; psum for global counters/consistency
checks. Cross-host traffic (remote drives) stays on the gRPC/HTTP data
plane (storage/), mirroring the reference's DCN split.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import rs_matrix, rs_tpu
from ..models import pipeline


def make_mesh(n_devices: int | None = None,
              devices=None) -> Mesh:
    """Factor n devices into a (dp, sp) mesh, favoring sp (byte-column
    sharding scales with object size; batch with request rate)."""
    if devices is None:
        devices = jax.devices()[:n_devices] if n_devices else jax.devices()
    n = len(devices)
    sp = 1
    for cand in range(min(n, 8), 0, -1):
        if n % cand == 0:
            sp = cand
            break
    dp = n // sp
    dev_array = np.asarray(devices).reshape(dp, sp)
    return Mesh(dev_array, axis_names=("dp", "sp"))


def sharded_put_step(mesh: Mesh, k: int, m: int):
    """Build the jitted multi-chip PUT step over `mesh`.

    In:  data (B, k, S) uint8, B % dp == 0, S % (sp*128) == 0.
    Out: parity (B, m, S) sharded like the input; tags (B, n, 128)
         replicated along sp (XOR-combined across byte columns).
    """
    pm = np.asarray(rs_matrix.parity_matrix(k, m))
    m2 = rs_tpu._bit_expand_cached(pm.tobytes(), pm.shape)

    def local_step(data):  # data: (B/dp, k, S/sp)
        parity = rs_tpu.gf_matmul_xla(jnp.asarray(m2, jnp.bfloat16), data)
        full = jnp.concatenate([data, parity], axis=-2)
        # local partial integrity tags, XOR-combined across the sp axis:
        # all_gather + fold (XOR has no direct psum; gather stays tiny)
        part = pipeline.xor_fold_digest(full)          # (B/dp, n, 128)
        gathered = jax.lax.all_gather(part, "sp")      # (sp, B/dp, n, 128)
        tags = jax.lax.reduce(gathered, np.uint8(0),
                              jax.lax.bitwise_xor, (0,))
        # global consistency counter (exercises psum across both axes)
        total = jax.lax.psum(
            jax.lax.psum(jnp.sum(parity.astype(jnp.int32) & 1), "sp"), "dp")
        return parity, tags, total

    from jax.experimental.shard_map import shard_map
    fn = shard_map(
        local_step, mesh=mesh,
        in_specs=(P("dp", None, "sp"),),
        out_specs=(P("dp", None, "sp"), P("dp", None, None), P()),
        check_rep=False)
    return jax.jit(fn)


def sharded_heal_step(mesh: Mesh, k: int, m: int, present_mask: int):
    """Multi-chip heal: survivors (B, k, S) -> missing shards, sp/dp
    sharded. Byte-column independence means zero collectives in the hot
    math — the win of sequence-parallel erasure coding."""
    r, _used, _missing = rs_matrix.recover_matrix(k, m, present_mask)
    r = np.asarray(r)
    m2 = rs_tpu._bit_expand_cached(r.tobytes(), r.shape)

    def local_step(survivors):
        return rs_tpu.gf_matmul_xla(jnp.asarray(m2, jnp.bfloat16), survivors)

    from jax.experimental.shard_map import shard_map
    fn = shard_map(
        local_step, mesh=mesh,
        in_specs=(P("dp", None, "sp"),),
        out_specs=P("dp", None, "sp"),
        check_rep=False)
    return jax.jit(fn)


def shard_array(mesh: Mesh, arr, spec: P):
    return jax.device_put(arr, NamedSharding(mesh, spec))
