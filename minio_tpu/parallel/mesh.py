"""Multi-chip sharding of the erasure data path.

Mapping of the reference's distribution axes onto a TPU mesh (reference
parallelism inventory: SURVEY §2.5):

  dp ("data")     — independent objects/blocks: batch dim of the shard
                    tensors. The analog of the reference's per-request
                    goroutine fan-out (its RAM-gated admission control).
  sp ("sequence") — byte columns of a block. Blocks are GF-columnwise
                    independent, so a huge object's bytes shard across
                    chips with zero cross-talk in encode/decode — the
                    storage analog of sequence/context parallelism (no
                    ring needed; the "attention" here is column-local).
  tp              — output-shard rows (the coding matrix's rows) can be
                    row-sharded for very wide sets; with n <= 32 shards
                    the matrix is tiny, so tp is folded into dp unless
                    explicitly requested.
  ep              — erasure-set routing (sipHashMod object->set) stays on
                    the host control plane (object/sets.py), exactly like
                    the reference's static "expert" routing.

Collectives used (all ride ICI inside a pool): all_to_all for the
SP→TP digest reshard; psum for global counters/consistency checks.
Cross-host traffic (remote drives) stays on the gRPC/HTTP data plane
(storage/), mirroring the reference's DCN split.

Serving integration (VERDICT r4 #1): object/codec.py dispatches its
fused put/get/heal batches through the `mesh_*` helpers below whenever
more than one device is visible (real TPU pool, or the virtual CPU mesh
under MINIO_TPU_MESH=1). Shard-row counts that don't divide the sp axis
are zero-padded for the digest reshard (pad-row digests are dropped
before returning), so every erasure geometry rides any mesh shape.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                      # jax >= 0.8
    from jax import shard_map as _shard_map_raw

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map_raw(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=check_rep)
except ImportError:                       # older jax: check_rep kwarg
    from jax.experimental.shard_map import shard_map

from ..ops import rs_matrix, rs_tpu
from ..models import pipeline
from ..utils import lockcheck


def make_mesh(n_devices: int | None = None, devices=None,
              sp: int | None = None) -> Mesh:
    """Factor n devices into a (dp, sp) mesh. By default sp (byte-column
    sharding, scales with object size) takes the largest factor <= 8;
    pass `sp` to pin the split (tests exercise both axes)."""
    if devices is None:
        devices = jax.devices()[:n_devices] if n_devices else jax.devices()
    n = len(devices)
    if sp is None:
        sp = 1
        for cand in range(min(n, 8), 0, -1):
            if n % cand == 0:
                sp = cand
                break
    if n % sp:
        raise ValueError(f"sp={sp} does not divide {n} devices")
    dp = n // sp
    dev_array = np.asarray(devices).reshape(dp, sp)
    return Mesh(dev_array, axis_names=("dp", "sp"))


_DEFAULT_MESH: Optional[Mesh] | bool = None


def default_mesh() -> Optional[Mesh]:
    """Process-wide mesh over every visible device, or None when the
    process is single-device. Built once: the device set is fixed for a
    process lifetime, and the jitted step caches key on the mesh."""
    global _DEFAULT_MESH
    if _DEFAULT_MESH is None:
        try:
            devs = jax.devices()
        except Exception:  # noqa: BLE001 — no backend at all
            devs = []
        _DEFAULT_MESH = make_mesh(devices=devs) if len(devs) > 1 else False
    return _DEFAULT_MESH or None


def _digest_reshard(rows3: jax.Array, n_rows: int, sp_size: int,
                    shard_len: int, algo: str) -> jax.Array:
    """Shared SP→TP digest pass: (B/dp, n_rows, S/sp) column-sharded
    shard rows -> (B/dp, n_pad/sp, 32) digests of WHOLE rows.

    Bitrot digests are sequential over a shard's full byte stream, so
    the pipeline re-shards from column-sharded to shard-row-sharded
    with an all_to_all over sp (the storage analog of a
    sequence-parallel attention's SP→TP switch), then each device
    hashes its rows whole. n_rows that doesn't divide sp is zero-padded
    (pad-row digests hash garbage nobody reads; callers slice them
    off)."""
    n_pad = -(-n_rows // sp_size) * sp_size
    if n_pad != n_rows:
        rows3 = jnp.pad(rows3, ((0, 0), (0, n_pad - n_rows), (0, 0)))
    rows = jax.lax.all_to_all(rows3, "sp", split_axis=1, concat_axis=2,
                              tiled=True)       # (B/dp, n_pad/sp, S)
    b_loc, r_loc, s_full = rows.shape
    return pipeline._hash_rows(
        rows.reshape(b_loc * r_loc, s_full), shard_len or s_full, b"",
        algo).reshape(b_loc, r_loc, 32)


@functools.lru_cache(maxsize=64)
def sharded_put_step(mesh: Mesh, k: int, m: int,
                     algo: str = "highwayhash", shard_len: int = 0):
    """Build the jitted multi-chip PUT step over `mesh`: the full
    encode+bitrot pipeline with real collectives.

    In:  data (B, k, S) uint8, B % dp == 0, S % sp == 0.
    Out: parity (B, m, S) column-sharded like the input; digests
         (B, k+m, 32) per-shard bitrot digests (HighwayHash256 or
         SHA-256 per `algo`); a psum'd consistency counter.

    Encode runs column-sharded (sp = byte columns, GF-columnwise
    independent — zero collectives); digests ride _digest_reshard's
    all_to_all. (k+m) need not divide sp — pad rows are sliced off.
    """
    pm = np.asarray(rs_matrix.parity_matrix(k, m))
    m2 = rs_tpu._bit_expand_cached(pm.tobytes(), pm.shape)
    n = k + m
    sp_size = mesh.devices.shape[1]

    def local_step(data):  # data: (B/dp, k, S/sp)
        parity = rs_tpu.gf_matmul_xla(jnp.asarray(m2, jnp.bfloat16), data)
        full = jnp.concatenate([data, parity], axis=-2)  # (B/dp, n, S/sp)
        digests = _digest_reshard(full, n, sp_size, shard_len, algo)
        # global consistency counter (exercises psum across both axes)
        total = jax.lax.psum(
            jax.lax.psum(jnp.sum(parity.astype(jnp.int32) & 1), "sp"), "dp")
        return parity, digests, total

    fn = shard_map(
        local_step, mesh=mesh,
        in_specs=(P("dp", None, "sp"),),
        out_specs=(P("dp", None, "sp"), P("dp", "sp", None), P()),
        check_rep=False)
    jitted = jax.jit(fn)

    def run(data):
        parity, digests, total = jitted(data)
        return parity, digests[:, :n], total
    return run


@functools.lru_cache(maxsize=64)
def sharded_get_step(mesh: Mesh, k: int, m: int, present_mask: int,
                     algo: str = "highwayhash", shard_len: int = 0):
    """Multi-chip fused verify+decode (the r3 flagship in SPMD form):
    survivors (B, k, S) in decode `used` order, column-sharded ->
    (missing data rows, survivor bitrot digests).

    The decode matmul is GF-columnwise independent (zero collectives);
    the digest pass reshards survivors SP→TP with an all_to_all so
    each device hashes whole shard rows — identical collective pattern
    to the PUT pipeline, so GET-with-failures scales the same way.
    k that doesn't divide the sp axis is zero-padded for the digest
    reshard (pad-row digests are dropped before returning).
    """
    dm, _used, missing = rs_matrix.missing_data_matrix(
        k, m, present_mask)
    m2 = rs_tpu._bit_expand_cached(dm.tobytes(), dm.shape)
    sp_size = mesh.devices.shape[1]

    def local_step(survivors):  # (B/dp, k, S/sp)
        out = rs_tpu.gf_matmul_xla(jnp.asarray(m2, jnp.bfloat16),
                                   survivors)
        digests = _digest_reshard(survivors, k, sp_size, shard_len, algo)
        return out, digests

    fn = shard_map(
        local_step, mesh=mesh,
        in_specs=(P("dp", None, "sp"),),
        out_specs=(P("dp", None, "sp"), P("dp", "sp", None)),
        check_rep=False)
    jitted = jax.jit(fn)

    def run(survivors):
        out, digests = jitted(survivors)
        return out, digests[:, :k]            # drop the pad rows
    return run, missing


@functools.lru_cache(maxsize=64)
def sharded_heal_step(mesh: Mesh, k: int, m: int, present_mask: int,
                      rows: tuple = (), algo: str = "highwayhash",
                      shard_len: int = 0):
    """Multi-chip heal with the fused single-device semantics
    (models/pipeline.heal_step): verify the survivors, rebuild the lost
    shards, and digest the rebuilt shards for their new bitrot frames —
    all sharded. Byte-column independence keeps the matmul
    collective-free; digests ride the same SP→TP all_to_all as PUT.

    `rows` restricts recovery to those shard indices (empty = all
    missing). Returns (run, idxs): run(survivors (B, k, S)) ->
    (recovered (B, R, S), survivor_digests (B, k, 32),
    recovered_digests (B, R, 32)); idxs maps output rows to shard
    indices.
    """
    rec, idxs = rs_matrix.recover_rows(k, m, present_mask, rows)
    m2 = rs_tpu._bit_expand_cached(rec.tobytes(), rec.shape)
    r_cnt = len(idxs)
    sp_size = mesh.devices.shape[1]

    def local_step(survivors):  # (B/dp, k, S/sp)
        out = rs_tpu.gf_matmul_xla(jnp.asarray(m2, jnp.bfloat16),
                                   survivors)
        both = jnp.concatenate([survivors, out], axis=-2)
        digests = _digest_reshard(both, k + r_cnt, sp_size, shard_len,
                                  algo)
        return out, digests

    fn = shard_map(
        local_step, mesh=mesh,
        in_specs=(P("dp", None, "sp"),),
        out_specs=(P("dp", None, "sp"), P("dp", "sp", None)),
        check_rep=False)
    jitted = jax.jit(fn)

    def run(survivors):
        out, digests = jitted(survivors)
        return out, digests[:, :k], digests[:, k:k + r_cnt]
    return run, idxs


def shard_array(mesh: Mesh, arr, spec: P):
    return jax.device_put(arr, NamedSharding(mesh, spec))


# ---------------------------------------------------------------------------
# numpy-facing serving dispatch (object/codec.py calls these)
# ---------------------------------------------------------------------------

class _Dispatches:
    """Thread-safe mesh-dispatch counter (BatchScheduler workers and
    direct callers bump it concurrently). Compares like an int."""

    def __init__(self):
        self._n = 0
        self._mu = threading.Lock()

    def bump(self):
        with self._mu:
            self._n += 1

    @property
    def value(self) -> int:
        return self._n

    def __index__(self):
        return self._n

    def __eq__(self, other):
        return self._n == other

    def __gt__(self, other):
        return self._n > other

    def __lt__(self, other):
        return self._n < other

    def __add__(self, other):
        return self._n + other

    def __repr__(self):
        return f"_Dispatches({self._n})"


DISPATCHES = _Dispatches()    # mesh device calls (tests/metrics)

# On NON-TPU backends, host-side mesh dispatches serialize on this
# lock (held through materialization, so dispatches fully serialize):
# two threads executing collective (all_to_all) programs concurrently
# can starve the virtual-device execution pool of each other's
# participants and deadlock — observed on the 8-virtual-device CPU
# mesh under concurrent per-request dispatch (the scheduler-bypass
# A/B), and the same hazard exists for any concurrent direct caller.
# Real TPU pools keep concurrent dispatch (the scheduler's INFLIGHT
# overlap): the PjRt TPU client runs concurrent executions safely.
_DISPATCH_MU = lockcheck.mutex("mesh.dispatch")
_NULL_MU = contextlib.nullcontext()


def _dispatch_guard(mesh: Mesh):
    devs = mesh.devices.flat
    return _NULL_MU if devs[0].platform == "tpu" else _DISPATCH_MU


def _shardable(mesh: Mesh, b: int, s: int) -> Optional[tuple[int, int]]:
    """(dp, sp) when a (B, *, S) batch can shard over `mesh`: byte
    columns must split exactly (no pad — GF columns are real data);
    short batches are padded up to dp by the callers."""
    dp, sp = mesh.devices.shape
    if s == 0 or s % sp:
        return None
    return dp, sp


def _pad_batch(data: np.ndarray, dp: int) -> tuple[np.ndarray, int]:
    b = data.shape[0]
    pad = -b % dp
    if pad:
        data = np.concatenate(
            [data, np.zeros((pad,) + data.shape[1:], np.uint8)])
    return data, b


def mesh_encode_and_hash(mesh: Mesh, data: np.ndarray, k: int, m: int,
                         algo: str = "highwayhash"):
    """Sharded form of Codec.encode_and_hash_batch: (B, k, S) ->
    (full (B, k+m, S), digests (B, k+m, 32)) numpy, or None when the
    shapes can't shard over this mesh (caller falls through to the
    single-device path)."""
    b_, k_, s = data.shape
    geom = _shardable(mesh, b_, s)
    if geom is None:
        return None
    dp, _sp = geom
    data, b = _pad_batch(np.ascontiguousarray(data, np.uint8), dp)
    step = sharded_put_step(mesh, k, m, algo)
    with _dispatch_guard(mesh):
        arr = shard_array(mesh, data, P("dp", None, "sp"))
        parity, digests, _total = step(arr)
        DISPATCHES.bump()
        full = np.concatenate([data[:b], np.asarray(parity)[:b]],
                              axis=1)
        return full, np.asarray(digests)[:b]


def mesh_verify_and_decode(mesh: Mesh, survivors: np.ndarray, k: int,
                           m: int, present_mask: int, shard_len: int,
                           algo: str = "highwayhash"):
    """Sharded form of Codec.verify_and_decode_batch: survivors
    (B, k, S) in `used` order -> (missing (B, r, S), missing_idxs,
    survivor_digests (B, k, 32)), or None when unshardable."""
    b_, _k, s = survivors.shape
    geom = _shardable(mesh, b_, s)
    if geom is None:
        return None
    # nothing missing -> nothing to fuse with; bail BEFORE building a
    # jitted step that would only pollute the lru cache
    _dm, _used, missing = rs_matrix.missing_data_matrix(
        k, m, present_mask)
    if not missing:
        return None
    dp, _sp = geom
    survivors, b = _pad_batch(
        np.ascontiguousarray(survivors, np.uint8), dp)
    run, missing = sharded_get_step(mesh, k, m, present_mask, algo,
                                    shard_len)
    with _dispatch_guard(mesh):
        arr = shard_array(mesh, survivors, P("dp", None, "sp"))
        out, digests = run(arr)
        DISPATCHES.bump()
        return np.asarray(out)[:b], missing, np.asarray(digests)[:b]


def mesh_verify_and_recover(mesh: Mesh, survivors: np.ndarray, k: int,
                            m: int, present_mask: int, rows,
                            shard_len: int, algo: str = "highwayhash"):
    """Sharded form of Codec.verify_and_recover_batch: -> (out
    (B, R, S), idxs, survivor_digests, out_digests), or None."""
    b_, _k, s = survivors.shape
    geom = _shardable(mesh, b_, s)
    if geom is None:
        return None
    # requested rows that are actually missing, BEFORE building a step
    _rec, idxs = rs_matrix.recover_rows(k, m, present_mask,
                                        tuple(sorted(rows)))
    if not idxs:
        return None
    dp, _sp = geom
    survivors, b = _pad_batch(
        np.ascontiguousarray(survivors, np.uint8), dp)
    run, idxs = sharded_heal_step(mesh, k, m, present_mask,
                                  tuple(sorted(rows)), algo, shard_len)
    with _dispatch_guard(mesh):
        arr = shard_array(mesh, survivors, P("dp", None, "sp"))
        out, sdig, odig = run(arr)
        DISPATCHES.bump()
        return (np.asarray(out)[:b], idxs, np.asarray(sdig)[:b],
                np.asarray(odig)[:b])
